"""Crash flight recorder: a bounded ring of recent runtime events plus
a one-call crash dump.

Training and serving both feed it for free (`StepTimeline.record`,
retrace-sentinel events, `ServingEngine` recovery, checkpoint saves);
on a crash — an uncaught exception once `install()` ran, or an explicit
``dump()`` from a recovery path — the ring, the exception, and a full
metrics-registry snapshot are written to one JSON file under
``.flight_recorder/`` (override with PADDLE_FLIGHT_DIR). The file is
what a postmortem needs: the last N steps' telemetry and what the
counters said at the moment of death, without any always-on log volume.
"""
from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback

__all__ = ["FlightRecorder", "recorder", "install"]


class FlightRecorder:
    def __init__(self, capacity=512):
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=int(capacity))
        self.last_dump_path = None

    def note(self, kind, **fields):
        """Append one event (O(1), bounded). Values should be JSON
        scalars/short lists — this is a black box, not a log."""
        ev = {"ts": round(time.time(), 6), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._events.append(ev)

    def snapshot(self):
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def dump(self, reason="", exc=None, path=None) -> str:
        """Write the black box to disk; returns the file path. Never
        raises (a failing dump must not mask the original crash) —
        returns None on failure."""
        try:
            from .registry import registry

            rec = {
                "reason": reason,
                "ts": round(time.time(), 6),
                "pid": os.getpid(),
                "events": self.snapshot(),
            }
            if exc is not None:
                rec["exception"] = {
                    "type": type(exc).__name__,
                    "message": str(exc)[:2000],
                    "traceback": "".join(traceback.format_exception(
                        type(exc), exc, exc.__traceback__))[-8000:],
                }
            try:
                rec["metrics"] = registry().snapshot()
            except Exception:
                rec["metrics"] = {}
            if path is None:
                root = os.environ.get("PADDLE_FLIGHT_DIR",
                                      ".flight_recorder")
                os.makedirs(root, exist_ok=True)
                path = os.path.join(
                    root,
                    f"crash_{os.getpid()}_{int(time.time() * 1e3)}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f, default=str)
            os.replace(tmp, path)
            self.last_dump_path = path
            return path
        except Exception:
            return None


_lock = threading.Lock()
_recorder = None
_installed = False
_prev_hook = None


def recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def install():
    """Chain the flight recorder into ``sys.excepthook``: an uncaught
    exception dumps the black box before the normal traceback prints.
    Idempotent."""
    global _installed, _prev_hook
    with _lock:
        if _installed:
            return
        _prev_hook = sys.excepthook
        _installed = True

    def hook(exc_type, exc, tb):
        try:
            e = exc if isinstance(exc, BaseException) else exc_type(exc)
            if tb is not None and getattr(e, "__traceback__", None) is None:
                e = e.with_traceback(tb)
            recorder().dump(reason="uncaught exception", exc=e)
        except Exception:
            pass
        (_prev_hook or sys.__excepthook__)(exc_type, exc, tb)

    sys.excepthook = hook
