"""SqueezeNet 1.0/1.1 (Iandola et al., 2016). Reference parity surface:
python/paddle/vision/models/squeezenet.py; architecture from the paper
(fire modules: squeeze 1x1 → expand 1x1 + 3x3 concat)."""
from __future__ import annotations

from ... import nn


class Fire(nn.Layer):
    def __init__(self, inp, squeeze, expand1, expand3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(inp, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, expand1, 1),
                                     nn.ReLU())
        self.expand3 = nn.Sequential(
            nn.Conv2D(squeeze, expand3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        from ... import ops

        s = self.squeeze(x)
        return ops.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2),
                Fire(512, 64, 256, 256),
            )
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256),
            )
        else:
            raise ValueError(f"unknown SqueezeNet version {version!r}")
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5),
                nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights need egress; load a state_dict instead")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights need egress; load a state_dict instead")
    return SqueezeNet("1.1", **kwargs)
