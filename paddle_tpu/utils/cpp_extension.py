"""Custom C++ ops.

Reference parity: paddle/extension.h + python/paddle/utils/cpp_extension/
— user-compiled C++ kernels registered as framework ops with autograd.
TPU-first shape: the custom kernel is HOST code (the device path is XLA;
custom device kernels would be Pallas), so a compiled function enters the
graph through `jax.pure_callback` — it works eagerly AND inside jit/
TrainStep programs, on CPU or as a host callback from TPU. A paired
backward function makes the op differentiable via `jax.custom_vjp`.

Contract for `load()`-built functions: `extern "C" void f(const T* in0,
const T* in1..., T* out, int64_t n)` over flat arrays (elementwise-style;
richer signatures can be wrapped by hand with `custom_op`).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

import jax
import jax.numpy as jnp


class CppExtension:
    """Handle to a compiled .so (reference CppExtension role)."""

    def __init__(self, so_path: str):
        self.so_path = so_path
        self.lib = ctypes.CDLL(so_path)

    def elementwise(self, fn_name: str, n_inputs: int = 1,
                    dtype=np.float32):
        """Wrap `extern "C" void fn(const T* in..., T* out, int64_t n)` as
        a numpy function."""
        cfn = getattr(self.lib, fn_name)
        ptr = np.ctypeslib.ndpointer(dtype=dtype, flags="C_CONTIGUOUS")
        cfn.argtypes = [ptr] * n_inputs + [ptr, ctypes.c_int64]
        cfn.restype = None

        def call(*arrays):
            arrays = [np.ascontiguousarray(a, dtype=dtype) for a in arrays]
            out = np.empty_like(arrays[0])
            cfn(*arrays, out, arrays[0].size)
            return out

        call.__name__ = fn_name
        return call


def load(name: str, sources, extra_cflags=None, build_directory=None,
         verbose=False) -> CppExtension:
    """Compile C++ sources into a loadable extension
    (reference cpp_extension.load)."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), f"paddle_tpu_ext_{name}")
    os.makedirs(build_dir, exist_ok=True)
    # flags are part of the cache key: a stale .so built with different
    # cflags must not be reused (the reference hashes build options too)
    import hashlib

    tag = hashlib.sha1(
        ("\x00".join(extra_cflags or [])).encode()).hexdigest()[:8]
    so_path = os.path.join(build_dir, f"{name}_{tag}.so")
    srcs = [os.path.abspath(s) for s in (
        sources if isinstance(sources, (list, tuple)) else [sources])]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(so_path) or os.path.getmtime(so_path) < newest_src:
        cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o",
                so_path] + srcs + (extra_cflags or []))
        try:
            res = subprocess.run(cmd, capture_output=True, text=True)
        except FileNotFoundError as e:  # no toolchain: keep the contract
            raise RuntimeError(f"cpp_extension build failed: {e}") from e
        if res.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{res.stderr}")
        if verbose:
            print(f"[cpp_extension] built {so_path}")
    return CppExtension(so_path)


def custom_op(forward, infer_meta=None, backward=None, name="custom_op"):
    """Register a host function as a framework op.

    Args:
      forward: numpy function (arrays...) -> array.
      infer_meta: (jax ShapeDtypeStructs...) -> output ShapeDtypeStruct;
        default: same shape/dtype as input 0 (reference InferMeta role).
      backward: numpy function (saved_inputs..., grad_out) -> tuple of
        input grads; omitted = non-differentiable.

    Returns a callable over paddle Tensors, usable eagerly and under jit.
    """
    from ..framework.tensor import Tensor
    from ..ops._dispatch import nary

    def default_meta(*avals):
        return jax.ShapeDtypeStruct(avals[0].shape, avals[0].dtype)

    meta = infer_meta or default_meta

    def fwd_jax(*datas):
        out_aval = meta(*[jax.ShapeDtypeStruct(d.shape, d.dtype)
                          for d in datas])
        return jax.pure_callback(
            lambda *a: np.asarray(forward(*[np.asarray(x) for x in a]),
                                  dtype=out_aval.dtype),
            out_aval, *datas, vmap_method="sequential")

    if backward is None:
        op = fwd_jax
    else:
        @jax.custom_vjp
        def op(*datas):
            return fwd_jax(*datas)

        def op_fwd(*datas):
            return fwd_jax(*datas), datas

        def op_bwd(saved, g):
            avals = [jax.ShapeDtypeStruct(d.shape, d.dtype) for d in saved]

            def host(*args):
                *ins, gout = args
                grads = backward(*[np.asarray(x) for x in ins],
                                 np.asarray(gout))
                grads = grads if isinstance(grads, (tuple, list)) else (
                    grads,)
                return tuple(np.asarray(gr, dtype=a.dtype)
                             for gr, a in zip(grads, avals))
            return jax.pure_callback(host, tuple(avals), *saved, g,
                                     vmap_method="sequential")

        op.defvjp(op_fwd, op_bwd)

    def apply(*tensors):
        return nary(op, [t if isinstance(t, Tensor) else Tensor(t)
                         for t in tensors], name)

    return apply
