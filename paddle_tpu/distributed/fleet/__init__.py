"""paddle.distributed.fleet parity (reference python/paddle/distributed/fleet/).

Strategy layers over the collective core: topology/HCG, distributed_model
wrappers, hybrid optimizer, sharding stages, recompute.
"""
from .recompute import recompute, recompute_hybrid, recompute_sequential  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from .fleet import (  # noqa: F401
    Fleet,
    DistributedStrategy,
    fleet,
    init,
    distributed_model,
    distributed_optimizer,
)
from . import layers  # noqa: F401
from . import elastic  # noqa: F401
from . import metrics  # noqa: F401
from . import utils  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import meta_optimizers  # noqa: F401
from .meta_parallel import (  # noqa: F401
    LayerDesc,
    SharedLayerDesc,
    HybridParallel,
    PipelineLayer,
    PipelineParallel,
    TensorParallel,
    SegmentParallel,
    ShardingParallel,
)
from .meta_optimizers import (  # noqa: F401
    HybridParallelOptimizer,
    DygraphShardingOptimizer,
)


def get_rng_state_tracker():
    from .layers.mpu.random import get_rng_state_tracker as _g

    return _g()


class Role:
    """reference fleet/base/role_maker.py Role constants."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """reference role_maker.PaddleCloudRoleMaker: resolve this process's
    role from the cluster env. Single-controller collective mode: this
    process is worker 0 of a world the mesh defines; PS roles belong to
    the descoped parameter-server stack (docs/DECISIONS.md §3)."""

    def __init__(self, is_collective=True, **kwargs):
        if not is_collective:
            raise NotImplementedError(
                "parameter-server role resolution is descoped "
                "(docs/DECISIONS.md §3); use is_collective=True")
        self._is_collective = True

    def _is_worker(self):
        return True

    is_worker = _is_worker

    def is_server(self):
        return False

    def is_first_worker(self):
        return True

    def worker_index(self):
        import os

        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    def worker_num(self):
        import os

        return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    def role(self):
        return Role.WORKER


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference role_maker.UserDefinedRoleMaker: explicit role wiring."""

    def __init__(self, is_collective=True, current_id=0, role=None,
                 worker_num=1, **kwargs):
        super().__init__(is_collective=is_collective)
        self._current_id = int(current_id)
        self._worker_num = int(worker_num)

    def worker_index(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num


class UtilBase:
    """reference fleet/utils UtilBase: barrier/all-gather over the
    control plane for host-side values."""

    def barrier(self, comm_world="worker"):
        from .. import collective as C

        C.barrier()

    def all_gather(self, input, comm_world="worker"):
        return [input]          # single controller: world of one host

    def get_file_shard(self, files):
        """Split a file list across workers (reference util.get_file_shard)."""
        import os

        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        return [f for i, f in enumerate(files) if i % n == rank]


class MultiSlotDataGenerator:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "MultiSlot data generators feed the parameter-server "
            "dataset pipeline (descoped, docs/DECISIONS.md §3); use "
            "paddle.io.Dataset/DataLoader")


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    pass
