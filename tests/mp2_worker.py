"""Worker for the REAL multi-process branch test (test_multiprocess.py).

Forms a 2-process jax.distributed CPU cluster (the reference's
multi-process-on-one-node strategy, test_parallel_dygraph_dataparallel.py:55)
and exercises the branches that only run when jax.process_count() > 1:
Group.rank's SPMD branch, cross-process barrier, and distributed
checkpoint save with metapart merge + reshard-on-load.
"""
import os
import pickle
import sys


def main():
    proc_id = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    port = sys.argv[3]
    outdir = sys.argv[4]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs, process_id=proc_id)
    assert jax.process_count() == nprocs, jax.process_count()

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.distributed.collective import get_group, barrier
    from paddle_tpu.distributed.checkpoint import (
        load_state_dict, save_state_dict,
    )

    devices = jax.devices()          # global: 2 per process
    assert len(devices) == 2 * nprocs, devices
    mesh = denv.build_mesh({"dp": len(devices)}, devices=devices)
    denv.set_mesh(mesh)

    # --- Group.rank SPMD branch (collective.py: process_count > 1) ------
    g = get_group()
    rank = g.rank
    assert rank == proc_id * 2, (rank, proc_id)   # first owned device's coord

    # --- global sharded array, multi-process save + metapart merge ------
    full = np.arange(32, dtype=np.float32).reshape(8, 4)
    sharding = NamedSharding(mesh, P("dp", None))
    arr = jax.make_array_from_callback(
        full.shape, sharding, lambda idx: full[idx])
    sd = {"w": paddle.Tensor._wrap(arr), "step": 7}
    ckpt = os.path.join(outdir, "ckpt")
    save_state_dict(sd, ckpt)

    # both processes see the merged manifest after the closing barrier
    with open(os.path.join(ckpt, "0.metadata"), "rb") as f:
        meta = pickle.load(f)
    chunks = meta.state_dict_metadata["w"]
    assert len(chunks) == len(devices), chunks          # all shards present
    files = set(meta.storage_metadata.values())
    assert files == {f"{p}_0.distcp" for p in range(nprocs)}, files

    # --- reshard-on-load: read back replicated, verify every element ----
    target = jax.make_array_from_callback(
        full.shape, NamedSharding(mesh, P()), lambda idx: np.zeros_like(full[idx]))
    out = {"w": paddle.Tensor._wrap(target), "step": 0}
    load_state_dict(out, ckpt)
    got = np.asarray(out["w"]._data.addressable_shards[0].data)
    np.testing.assert_allclose(got, full)
    assert int(out["step"]) == 7

    barrier()
    print(f"MP2-OK rank={rank} proc={proc_id}", flush=True)


if __name__ == "__main__":
    main()
