"""HLO receipts for the distributed linalg tier.

Two contracts, checked on the COMPILED per-device program:

1. **No full-matrix materialization**: no buffer in any rank's program
   reaches the global matrix's element count — the operands enter
   block-sharded, panels move, and nothing ever gathers a whole
   operand/result on one rank (`assert_no_full_matrix`).
2. **Collective census**: the per-axis collective counts from
   tools/hlo_overlap.py (all-reduce per SUMMA panel over exactly one
   axis, one all-gather per Cholesky iteration, ONE gather for TSQR) —
   the same receipt machinery the mp/pp training paths use
   (`collective_receipt`).
"""
from __future__ import annotations

import re

from ._grid import ROWS, COLS, grid_shape

__all__ = ["assert_no_full_matrix", "collective_receipt",
           "compiled_text", "load_hlo_overlap", "max_buffer_elems"]

_SHAPE_RE = re.compile(r"\b(?:f|bf|s|u|pred)[0-9]*\[([0-9,]*)\]")


def compiled_text(lowered):
    """Optimized per-device HLO text of a `.lower(...)`ed program."""
    return lowered.compile().as_text()


def max_buffer_elems(text):
    """Largest array-shape element count appearing in the HLO text."""
    worst = 0
    for m in _SHAPE_RE.finditer(text):
        dims = m.group(1)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        worst = max(worst, n)
    return worst


def assert_no_full_matrix(text, full_elems, what="matrix"):
    """Raise unless every buffer in the compiled per-device program is
    strictly smaller than the full global matrix — the "no rank ever
    materializes the whole thing" contract."""
    worst = max_buffer_elems(text)
    if worst >= full_elems:
        raise AssertionError(
            f"a {worst}-element buffer appears in the compiled program "
            f"but the full {what} is only {full_elems} elements — some "
            "rank materializes the whole thing")
    return worst


def load_hlo_overlap():
    """tools/hlo_overlap.py by path (tools/ is repo-root only)."""
    import importlib.util
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    path = os.path.join(root, "tools", "hlo_overlap.py")
    if os.path.exists(path):
        spec = importlib.util.spec_from_file_location("hlo_overlap", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    import tools.hlo_overlap as mod  # namespace-package fallback

    return mod


def collective_receipt(lowered, grid, full_elems=None, what="matrix"):
    """Analyze a lowered linalg program: per-axis collective counts
    (rows/cols labels) + the no-full-matrix bound. Returns the verdict
    dict (hlo_overlap.analyze output + max_buffer_elems)."""
    text = compiled_text(lowered)
    r, c = grid_shape(grid)
    verdict = load_hlo_overlap().analyze(
        text, axis_degrees={ROWS: r, COLS: c})
    verdict["max_buffer_elems"] = max_buffer_elems(text)
    if full_elems is not None:
        assert_no_full_matrix(text, full_elems, what=what)
        verdict["full_matrix_elems"] = full_elems
        verdict["no_full_matrix"] = True
    return verdict
