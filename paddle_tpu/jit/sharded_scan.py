"""Sharded fused-scan train step: weight-update sharding INSIDE the scan,
and (ISSUE 11) sharded PARAMETER STORAGE with gather-on-use.

`FusedScanTrainStep` made the 1.3b north star fit one chip by fusing the
Adam update into a manual per-layer reverse scan. This module is its
multi-chip form, per Xu et al., "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" (PAPERS.md): gradients, moments,
masters and the update computation are 1/N-sharded per rank — and, with
``param_storage="sharded"`` (the default since ISSUE 11), the weights
THEMSELVES live as 1/N flat bucket shards between steps, all-gathered on
use inside the forward scan (double-buffered prefetch), re-gathered by
the backward recompute, and written back as shards by the update scan —
no full replicated parameter pytree exists at any point between steps
(ZeRO-3-style storage on the same ``__scan_shard_*__`` flat layout the
optimizer state uses). ``param_storage="replicated"`` restores the
original layout (the bit-parity reference). The replicated-mode
structure —

  backward scan (reverse, per chunk of K layers):
      dp      = vjp(block chunk)(dy)                 (full, dies here)
      flat    = bucket-pack(dp)   [K, F]             (comm_bucketer layout)
      gshard  = reduce_scatter(flat) over the axis   [K, F/N]  <- survives
      sq     += ||gshard/N||^2                       (in the scan carry)
  one scalar all-reduce:  gnorm = sqrt(psum(sq));  clip = min(c/gnorm, 1)
  update scan (per chunk):
      adam on the 1/N shard (clip applied, moments/masters sharded)
      all_gather(updated shard) -> write the chunk's param slices
  outer params (embed/ln_f/head): same, without the scan.

Because only the 1/N grad shard outlives a scan iteration, the whole
gradient set per rank is full_grads/N — which is what makes the fused
GLOBAL-NORM CLIP affordable here (the single-device step needs a second
backward pass for it, docs/DECISIONS.md §12) and keeps grad memory off
the per-layer OOM cliff. The per-bucket reduce-scatter reuses the
comm_bucketer packing (deterministic entry offsets, FLAGS_comm_bucket_mb
cap, padding to the axis degree) and optionally the EQuARX-style
compressed wire format (FLAGS_comm_quant -> int8/bf16 scatter leg,
collective.quantized_psum_scatter_traced). Inside one scan iteration the
reduce-scatter of bucket b is independent of bucket b+1's packing and of
the norm accumulation, and the update scan's all_gather of bucket b is
independent of bucket b+1's Adam math — with scan_unroll >= 2 adjacent
layers' collectives and compute land in ONE while-loop body where XLA's
latency-hiding scheduler can overlap them (tools/hlo_overlap.py is the
receipt; the multichip lane records its verdict).

Dropout rides the carry-free per-layer PRNG offset scheme of the base
class, with the dp-axis rank folded in so each rank draws distinct masks
for its own batch rows.

Semantics note: the per-rank loss is the criterion's mean over the
rank's batch shard and the returned loss is their mean — equal to the
full-batch mean when every rank holds the same number of unmasked
tokens (the standard data-parallel contract; ragged -100 masks make it
a weighted mean, same as the reference DataParallel).

Round 12 (ISSUE 8) generalized the whole machinery from ONE mesh axis
to an axis tuple: grads scatter and params gather over the flattened
(dp, mp[, pp]) product (first axis major — `_flat_rank` mirrors the
tuple-collective split order), optimizer shards are 1/(dp·mp·pp), and
the per-rank loss/grad carry a uniform ×(mp·pp) joint-vjp replication
factor (every mp/pp rank computes the identical loss) that the 1/N
normalization divides back out. On top of that ride:

* dp×mp Megatron tensor parallelism (`mp_axis=`): `_setup_mp` compiles
  the spmd_rules role table into per-leaf slicers (head-interleaved
  qkv / column fc1 / row out_proj+fc2 with bias/mp) bound into the
  SAME block template at trace time, one psum per row-parallel
  projection, and the vocab-parallel sharded fused CE as `_head_fn` —
  each rank's grads cover its slice (zero-padded), so the axis-tuple
  scatter IS the tensor-parallel gradient assembly.
* dp×pp ring pipelining: jit/pipeline_step.py overrides `_grads` (the
  seam this module exposes) with the ppermute ring schedule and reuses
  the clip/guard/update machinery unchanged.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .fused_scan_step import FusedScanTrainStep, _donate_argnums, _key
from ..utils import flags as _flags


# ---------------------------------------------------------------------------
# flat bucket packing (the comm_bucketer layout, applied per layer chunk)
# ---------------------------------------------------------------------------

def pack_flat(leaf_of_key, bucket, lead=(), dtype=None):
    """Pack per-leaf arrays (each [*lead, *entry.shape]) into the
    bucket's flat layout [*lead, bucket.numel] (zero-padded), matching
    comm_bucketer._flatten_bucket offsets exactly. `dtype` overrides the
    bucket dtype (moment packing)."""
    dt = dtype or bucket.dtype
    parts = []
    for e in bucket.entries:
        parts.append(leaf_of_key(e.key).reshape(lead + (-1,)).astype(dt))
    pad = bucket.numel - sum(e.numel for e in bucket.entries)
    if pad:
        parts.append(jnp.zeros(lead + (pad,), dt))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, -1)


def unpack_flat(flat, bucket):
    """[*lead, bucket.numel] -> {entry.key: [*lead, *entry.shape]}."""
    lead = flat.shape[:-1]
    return {e.key: flat[..., e.offset:e.offset + e.numel]
            .reshape(lead + tuple(e.shape)) for e in bucket.entries}


def scatter_flat(flat, axes, nranks, quant=""):
    """Reduce-scatter a packed flat bucket over `axes` (a single axis
    name or a tuple — the dp×mp/pp hybrid steps scatter over the
    FLATTENED product, first axis major) along its LAST dim: one
    collective per bucket (vs one per leaf), bit-identical to
    comm_bucketer.bucketed_reduce_scatter's per-bucket psum_scatter on
    the same packing for the single-axis case. `quant` routes the
    compressed scatter leg — since ISSUE 11 the int8/bf16 all_to_all
    wire format covers flattened axis tuples too (the chunk split is
    first-axis-major, matching tuple psum_scatter; see
    collective.comm_quant_multiaxis_selftest)."""
    if isinstance(axes, (tuple, list)) and len(axes) == 1:
        axes = axes[0]
    if quant:
        from ..distributed.collective import quantized_psum_scatter_traced

        return quantized_psum_scatter_traced(axes, nranks, quant)(flat)
    return lax.psum_scatter(flat, axes, scatter_dimension=flat.ndim - 1,
                            tiled=True)


def gather_flat(shard, axes, axis, quant=""):
    """Inverse of `scatter_flat`'s split: tiled all_gather over the same
    (possibly flattened) axes. `quant` routes the compressed gather leg
    (collective.quantized_all_gather_traced — the sharded-param-storage
    gather-on-use wire format, lossy and therefore opt-in via
    FLAGS_comm_quant like the scatter leg)."""
    if isinstance(axes, (tuple, list)) and len(axes) == 1:
        axes = axes[0]
    if quant:
        from ..distributed.collective import quantized_all_gather_traced

        return quantized_all_gather_traced(axes, quant,
                                           gather_axis=axis)(shard)
    return lax.all_gather(shard, axes, axis=axis, tiled=True)


# ---------------------------------------------------------------------------
# sharded parameter storage (ISSUE 11): params live as 1/N flat shards
# ---------------------------------------------------------------------------
# Between steps the ONLY param bytes on the devices are the per-bucket
# flat shards (the same __scan_shard_*__ layout the optimizer state
# uses); the full per-leaf arrays the rest of the framework reads
# (eval, checkpointing, tests) are materialized LAZILY on first access
# and dropped again after every step. The mechanics: each trainable
# Parameter of a sharded-storage step has its class swapped to a thin
# subclass whose `_data` property (shadowing the Tensor slot) gathers
# its bucket on a stale read and marks the bucket dirty on an external
# write — so `p._data = ...` (checkpoint restore, test poking, user
# init) transparently flows back into the shards at the next step.

_STALE = object()          # sentinel living in the Tensor._data slot
_TENSOR_DATA_SLOT = None   # resolved lazily (framework import order)
_RAW_DATA = [0]            # >0: passthrough reads/writes (inside a step)
_LAZY_CLS_CACHE = {}


def _data_slot():
    global _TENSOR_DATA_SLOT
    if _TENSOR_DATA_SLOT is None:
        from ..framework.tensor import Tensor

        _TENSOR_DATA_SLOT = Tensor.__dict__["_data"]
    return _TENSOR_DATA_SLOT


class _raw_param_access:
    """Context: Parameter._data reads/writes bypass the lazy-shard
    machinery (used around the compiled step call and its trace, where
    `_bind` shuffles tracers through the live Parameter objects)."""

    def __enter__(self):
        _RAW_DATA[0] += 1

    def __exit__(self, *exc):
        _RAW_DATA[0] -= 1


def _lazy_param_class(cls):
    lazy = _LAZY_CLS_CACHE.get(cls)
    if lazy is not None:
        return lazy
    slot = _data_slot()

    def _get(self):
        d = slot.__get__(self)
        if d is _STALE and not _RAW_DATA[0]:
            ref = self.__dict__.get("_shard_ref")
            if ref is not None:
                ref[0]._materialize_bucket_params(ref[1], ref[2])
                d = slot.__get__(self)
            if d is _STALE:
                raise RuntimeError(
                    f"parameter {getattr(self, 'name', '?')} is stored "
                    "as 1/N shards but its owning sharded-storage step "
                    "is gone; keep the train step alive or use "
                    "param_storage='replicated'")
        return d

    def _set(self, v):
        slot.__set__(self, v)
        if not _RAW_DATA[0] and v is not _STALE:
            ref = self.__dict__.get("_shard_ref")
            if ref is not None:
                ref[0]._dirty_param_buckets.add((ref[1], ref[2]))

    lazy = type(f"_ShardStored{cls.__name__}", (cls,),
                {"_data": property(_get, _set), "__module__": __name__,
                 "_shard_backed": True})
    _LAZY_CLS_CACHE[cls] = lazy
    return lazy


def _unwrap_layers(model):
    """Follow wrapper chains (GroupShardedStage2, fleet MetaParallelBase,
    DataParallel) to the Layer that owns the parameters."""
    seen = set()
    while hasattr(model, "_layers") and id(model) not in seen:
        seen.add(id(model))
        model = model._layers
    return model


def _vec_or_scalar(values, entries, numel, pad_value=0.0):
    """Per-entry hyperparameters as ONE flat [numel] fp32 vector — or a
    python float when uniform (padding entries update to zero regardless
    of the hyperparameter, so a uniform scalar is exact)."""
    uniq = set(values)
    if len(uniq) == 1:
        return float(values[0])
    vec = np.full((numel,), pad_value, np.float32)
    for e, v in zip(entries, values):
        vec[e.offset:e.offset + e.numel] = v
    return jnp.asarray(vec)


class ShardedFusedScanTrainStep(FusedScanTrainStep):
    """Multi-chip FusedScanTrainStep over a dp/sharding mesh axis —
    and, with ``mp_axis``, a 2-D dp×mp mesh with Megatron tensor
    parallelism inside the scan body.

    Usage (directly, or via fleet distributed_model /
    jit.select_train_step which resolve mesh+axes)::

        mesh = dist.env.build_mesh({"sharding": 8}); dist.env.set_mesh(mesh)
        step = ShardedFusedScanTrainStep(model, opt)   # scan_layers model
        loss = step(ids, labels)       # ids [global_batch, seq]

        mesh = dist.env.build_mesh({"dp": 4, "mp": 2})  # dp×mp hybrid
        step = ShardedFusedScanTrainStep(model, opt, mesh=mesh,
                                         axis="dp", mp_axis="mp")

    Optimizer state (moments + masters) lives as flat bucket-packed
    arrays sharded 1/N over the FLATTENED reduction axes (N = dp·mp;
    inspect `opt._accumulators["moment1"]["__scan_shard_s0__"]` etc.);
    ClipGradByGlobalNorm costs one scalar all-reduce, ClipGradByValue is
    elementwise on the shard, and dropout is rank-folded per layer.
    Under mp the block compute runs head-/column-/row-sliced per rank
    with one psum per row-parallel projection, and the LM head is the
    vocab-parallel sharded fused CE (see _setup_mp / _head_fn).

    ``param_storage="sharded"`` (the default; "replicated" restores the
    pre-ISSUE-11 layout, FLAGS_param_storage overrides globally) stores
    the PARAMETERS the same way: 1/N flat bucket shards between steps,
    gathered on use inside the scans with a double-buffered prefetch
    slot — bit-parity with replicated storage, param_bytes×(1−1/N)
    less steady-state HBM per device. Between steps, reads of a
    shard-stored `p._data` gather lazily (eval/checkpoints just work)
    and external writes repack at the next step.
    """

    def __init__(self, model, optimizer, criterion=None, fused_head=False,
                 compute_dtype=None, layer_chunk=1, scan_unroll=1,
                 mesh=None, axis=None, mp_axis=None, ep_axis=None,
                 group=None, comm_bucket_mb=None, comm_quant=None,
                 scaler=None, guard_nonfinite=None, param_storage=None,
                 numerics=None):
        model = _unwrap_layers(model)
        super().__init__(model, optimizer, criterion=criterion,
                         fused_head=fused_head,
                         compute_dtype=compute_dtype,
                         layer_chunk=layer_chunk, scan_unroll=scan_unroll,
                         scaler=scaler, guard_nonfinite=guard_nonfinite,
                         numerics=numerics)
        from ..distributed import env as denv

        if group is not None:
            mesh, axis = group.mesh, group.axes[0]
        if mesh is None:
            mesh = denv.get_mesh()
        if axis is None:
            # prefer a >1 data axis; else a PRESENT degree-1 dp/sharding
            # axis (a dp1×pp2 mesh still batches over "dp", not "pp");
            # else the first mesh axis
            axis = next((a for a in ("sharding", "dp")
                         if a in mesh.axis_names and mesh.shape[a] > 1),
                        None) or next(
                (a for a in ("sharding", "dp")
                 if a in mesh.axis_names), mesh.axis_names[0])
        if mp_axis is None:
            mp_axis = next((a for a in ("mp",)
                            if a in mesh.axis_names and a != axis
                            and mesh.shape[a] > 1), None)
        elif mp_axis not in mesh.axis_names or \
                int(mesh.shape[mp_axis]) <= 1:
            mp_axis = None
        if mp_axis is not None and mp_axis == axis:
            raise ValueError(
                f"mp_axis {mp_axis!r} is also the batch/data axis — a "
                "pure-mp mesh has no axis to shard the batch over; "
                "build the mesh with an explicit (degree-1 is fine) "
                "data axis, e.g. build_mesh({'dp': 1, 'mp': N})")
        if axis not in mesh.axis_names:
            raise ValueError(
                f"batch/data axis {axis!r} is not a mesh axis "
                f"(mesh axes: {mesh.axis_names}); include it in the "
                "mesh (degree 1 is fine) or pass axis= explicitly")
        # expert parallelism (ISSUE 9): an ``ep`` axis shards the
        # template's MoE expert stacks 1/ep and splits the batch over
        # the FLATTENED (dp, ep) product — every (dp, ep) rank sees
        # distinct rows, and the MoE dispatch/combine become explicit
        # ep-axis all_to_alls inside the scan body (moe_layer's EP
        # path). Auto-detected from the mesh for MoE templates only.
        moe_template = bool(self._aux_layers)
        if ep_axis is None:
            ep_axis = next(
                (a for a in ("ep",) if a in mesh.axis_names
                 and int(mesh.shape[a]) > 1 and a != axis), None)
            if ep_axis is not None and not moe_template:
                ep_axis = None      # dense model: ep replicates
        elif ep_axis not in mesh.axis_names:
            # an explicit but unknown axis name is a config typo — the
            # silent fallback would train with experts fully replicated
            # while the user believes EP is active
            raise ValueError(
                f"ep_axis {ep_axis!r} is not a mesh axis (mesh axes: "
                f"{mesh.axis_names}); include it in the mesh or drop "
                "ep_axis")
        elif int(mesh.shape[ep_axis]) <= 1:
            ep_axis = None
        if ep_axis is not None:
            if not moe_template:
                raise ValueError(
                    f"ep_axis {ep_axis!r} given but the block template "
                    "has no MoE layers to expert-shard; build the model "
                    "with GPTConfig(num_experts=...) or drop ep_axis")
            if ep_axis == axis:
                raise ValueError(
                    f"ep_axis {ep_axis!r} is also the batch/data axis; "
                    "build the mesh with distinct dp and ep axes, e.g. "
                    "build_mesh({'dp': N, 'ep': E})")
            if mp_axis is not None:
                raise NotImplementedError(
                    "mp×ep composition is not supported: the Megatron "
                    "block slicing and the expert all_to_all dispatch "
                    "have not been validated together — use dp×ep or "
                    "dp×mp")
        self._mesh, self._axis = mesh, axis
        self._mp_axis = mp_axis
        self._ep_axis = ep_axis
        self._dp_degree = int(mesh.shape[axis])
        self._mp_degree = int(mesh.shape[mp_axis]) if mp_axis else 1
        self._ep_degree = int(mesh.shape[ep_axis]) if ep_axis else 1
        # grad-reduction axes, FIRST AXIS MAJOR: every flat bucket
        # scatters/gathers over the flattened product, so optimizer
        # shards are 1/(dp*mp*ep); the flat rank below must match the
        # tuple-collective split order. Subclasses (the pipeline step)
        # append further axes via _extra_reduction_axes.
        self._axes = (axis,)
        if mp_axis is not None:
            self._axes = self._axes + (mp_axis,)
        if ep_axis is not None:
            self._axes = self._axes + (ep_axis,)
        self._degree = (self._dp_degree * self._mp_degree
                        * self._ep_degree)
        # the batch splits over (dp, ep) — under ep every rank holds
        # distinct rows (pure data parallelism everywhere except the
        # expert FFN, where the all_to_all exchanges tokens)
        self._batch_axes = ((axis,) if ep_axis is None
                            else (axis, ep_axis))
        self._batch_degree = self._dp_degree * self._ep_degree
        for a in self._extra_reduction_axes(mesh):
            if a in self._axes:
                raise ValueError(
                    f"reduction axis {a!r} doubles as the batch/data "
                    f"axis (resolved axes {self._axes}) — a pp-only "
                    "mesh has no axis to shard the batch over; build "
                    "the mesh with an explicit (degree-1 is fine) data "
                    "axis, e.g. build_mesh({'dp': 1, 'pp': N})")
            self._axes = self._axes + (a,)
            self._degree *= int(mesh.shape[a])
        if self._degree <= 1 and not getattr(
                self, "_allow_degree_one", False):
            raise ValueError(
                f"axes {self._axes!r} have total degree {self._degree}; "
                "weight-update sharding needs a >1 dp/sharding (or mp) "
                "axis — use FusedScanTrainStep on one chip")
        # dp-rank folded into the per-layer dropout offsets. mp ranks
        # MUST draw identical masks (they jointly compute the same batch
        # rows; divergent hidden-dropout masks would desynchronize the
        # replicated residual stream), so only the dp index folds in —
        # but ep ranks hold DISTINCT rows, so under ep the flattened
        # (dp, ep) batch rank folds in instead.
        self._rng_nranks = self._batch_degree
        if mp_axis is not None:
            self._setup_mp()
        if ep_axis is not None:
            self._setup_ep()
        if comm_quant is None:
            comm_quant = _flags.get_flag("FLAGS_comm_quant") or ""
        # since ISSUE 11 the int8/bf16 wire format covers flattened axis
        # tuples (first-axis-major all_to_all split, verified by
        # comm_quant_multiaxis_selftest) — the PR-8 warn-off/reject for
        # multi-axis steps is gone
        self._comm_quant = comm_quant
        # sharded parameter storage (ISSUE 11): params live as 1/N flat
        # bucket shards (gathered on use inside the scans) instead of
        # replicated per-leaf stacks; default ON for the sharded steps —
        # the compiled step is bit-parity with replicated storage
        if param_storage is None:
            param_storage = (_flags.get_flag("FLAGS_param_storage")
                             or "sharded")
        if param_storage not in ("sharded", "replicated"):
            raise ValueError(
                f"param_storage {param_storage!r} (sharded|replicated)")
        self._param_storage = param_storage
        self._param_shards = {"s": [], "o": []}
        self._dirty_param_buckets = set()
        self._pack_jits = {}       # (grp, bucket idx) -> jitted packer
        self._gather_jit = None    # shard -> replicated resharder
        from ..distributed.collective import QUANT_SCATTER_BLOCK
        from ..distributed.comm_bucketer import MB, build_buckets

        pad = self._degree * (QUANT_SCATTER_BLOCK if comm_quant else 1)
        if comm_bucket_mb is None:
            comm_bucket_mb = int(
                _flags.get_flag("FLAGS_comm_bucket_mb") or 0)
        bucket_bytes = (comm_bucket_mb * MB if comm_bucket_mb > 0
                        else 1 << 62)
        # stacked leaves bucket by their PER-LAYER shard shape (the scan
        # scatters one chunk at a time); outer leaves by full shape
        self._s_train = [(j, p) for j, p in enumerate(self._s_params)
                         if p.trainable]
        self._s_trainable_idx = {j for j, _ in self._s_train}
        self._s_assign = build_buckets(
            [(j, tuple(p.shape[1:]), p._data.dtype)
             for j, p in self._s_train],
            bucket_bytes=bucket_bytes, pad_multiple=pad)
        self._o_assign = build_buckets(
            [(j, tuple(p.shape), p._data.dtype)
             for j, (_, p) in enumerate(self._o_params)],
            bucket_bytes=bucket_bytes, pad_multiple=pad)
        # master-weight use per bucket, resolved NOW (reads p._data
        # dtypes) — after shardification the live Parameters may hold
        # the stale sentinel, and build-time metadata must not trigger
        # a gather
        self._bucket_use_mw = {
            grp: [any(self._opt._use_master(p)
                      for p in self._bucket_params(grp, b))
                  for b in assign.buckets]
            for grp, assign in (("s", self._s_assign),
                                ("o", self._o_assign))}

    def _cost_axis_degrees(self):
        return {a: int(self._mesh.shape[a])
                for a in self._mesh.axis_names}

    def _publish_comm_gauges(self):
        """Static comm-budget gauges (ISSUE 12): global payload bytes
        per step of the grad reduce-scatter leg (every bucket, every
        chunk) and — under sharded parameter storage — the param
        all-gather leg, labeled with the reduction-axis tuple."""
        from ..observability import registry as _oreg

        s_bytes = sum(b.nbytes for b in self._s_assign.buckets) \
            * self.model.config.num_layers
        o_bytes = sum(b.nbytes for b in self._o_assign.buckets)
        reg = _oreg()
        axes = "+".join(self._axes)
        reg.gauge("comm.grad_scatter_bytes_per_step").set(
            s_bytes + o_bytes)
        reg.gauge("comm.reduction_axes").set(axes)
        if self._param_storage == "sharded":
            # forward gather + backward re-gather + outer gather ≈ 2x
            # the stacked payload + outer (update writes shards back)
            reg.gauge("comm.param_gather_bytes_per_step").set(
                2 * s_bytes + o_bytes)

    def _rng_rank(self):
        r = lax.axis_index(self._axis)
        if self._ep_axis is not None:
            r = r * self._ep_degree + lax.axis_index(self._ep_axis)
        return r

    def _extra_reduction_axes(self, mesh):
        """Hook: further mesh axes the grad scatter / optimizer shard
        should flatten in (the pipeline step adds its pp axis)."""
        return ()

    def _flat_rank(self):
        """Flattened rank over the grad-reduction axes, first axis
        major — the split order of tuple-axis psum_scatter/all_gather
        (verified against jax's flattened-product layout)."""
        r = lax.axis_index(self._axes[0])
        for a in self._axes[1:]:
            r = r * int(self._mesh.shape[a]) + lax.axis_index(a)
        return r

    # -- Megatron tensor parallelism over the mp axis --------------------
    # COMPUTE is tensor-parallel (storage is flat-sharded 1/N by default
    # since ISSUE 11 — the mp slicers below operate on the gathered full
    # leaves either way): each mp rank binds head-/column-sliced views of
    # qkv+fc1 and row-sliced views of out_proj+fc2 into the block
    # template, and the two row-parallel outputs psum over mp inside the
    # block — the Megatron layout the SPMD rule table
    # (distributed/auto_parallel/spmd_rules.py) assigns, realized as
    # manual collectives inside the scan body.
    def _setup_mp(self):
        from ..distributed.auto_parallel.spmd_rules import (
            _assign_roles, _is_fused_proj,
        )

        mp = self._mp_degree
        tmpl = self._template
        cfg = self.model.config
        if cfg.num_attention_heads % mp:
            raise ValueError(
                f"num_attention_heads {cfg.num_attention_heads} not "
                f"divisible by mp degree {mp}")
        if cfg.vocab_size % mp:
            raise ValueError(
                f"vocab_size {cfg.vocab_size} not divisible by mp "
                f"degree {mp} (vocab-parallel LM head)")
        if getattr(cfg, "attention_dropout_prob", 0.0):
            raise ValueError(
                "attention dropout under mp>1 would draw the same mask "
                "stream for every rank's head slice; train with "
                "attention_dropout_prob=0 (hidden dropout is fine)")
        from ..models.gpt import GPTPretrainingCriterion

        if not isinstance(self._crit, GPTPretrainingCriterion):
            raise ValueError(
                "mp>1 routes the LM head through the vocab-parallel "
                "sharded fused CE; custom criteria are not representable "
                "there — use the default GPTPretrainingCriterion")
        # sublayer path -> object, for role/ownership lookups
        subs = dict(tmpl.named_sublayers(include_self=True))
        roles = _assign_roles(tmpl)

        def owner_of(pname):
            path = pname.rsplit(".", 1)[0] if "." in pname else ""
            return subs.get(path), path

        def head_slicer(nh, hd, dim):
            """Head-interleaved slice of a fused multi-projection dim
            (qkv [.., 3*nh*hd]): view [.., 3, nh, hd], slice nh."""
            nh_loc = nh // mp

            def fn(d, r):
                lead = d.shape[:dim]
                k = d.shape[dim] // (nh * hd)
                v = d.reshape(lead + (k, nh, hd))
                v = lax.dynamic_slice_in_dim(v, r * nh_loc, nh_loc,
                                             dim + 1)
                return v.reshape(lead + (k * nh_loc * hd,))

            return fn

        def dim_slicer(dim, degree=mp):
            def fn(d, r):
                loc = d.shape[dim] // degree
                return lax.dynamic_slice_in_dim(d, r * loc, loc, dim)

            return fn

        slicers = []
        row_parallel = []          # (parent path, attr name)
        for pname, p in tmpl.named_parameters():
            sub, path = owner_of(pname)
            role = roles.get(id(sub)) if sub is not None else None
            tname = type(sub).__name__ if sub is not None else ""
            leaf = pname.rsplit(".", 1)[-1]
            if tname == "Linear" and role == "column":
                parent_path = path.rsplit(".", 1)[0] if "." in path \
                    else ""
                parent = subs.get(parent_path)
                nh = getattr(parent, "num_heads", None)
                hd = getattr(parent, "head_dim", None)
                fused = _is_fused_proj(sub, attr_name=path.rsplit(
                    ".", 1)[-1])
                if fused and not (nh and hd):
                    raise ValueError(
                        f"{pname}: fused multi-projection column layer "
                        "needs a parent exposing num_heads/head_dim for "
                        "the head-interleaved mp slice (a contiguous "
                        "column slice would split q|k|v wrongly)")
                if fused:
                    slicers.append(head_slicer(nh, hd,
                                               0 if leaf == "bias"
                                               else 1))
                elif leaf == "weight":
                    if sub.weight.shape[1] % mp:
                        raise ValueError(
                            f"{pname}: out dim {sub.weight.shape[1]} "
                            f"not divisible by mp {mp}")
                    slicers.append(dim_slicer(1))
                else:
                    slicers.append(dim_slicer(0))
            elif tname == "Linear" and role == "row":
                if leaf == "weight":
                    slicers.append(dim_slicer(0))
                else:
                    # row-parallel bias: every rank adds bias/mp, the
                    # in-block psum reconstructs it once (exact in real
                    # arithmetic; fp noise is far under the parity bar)
                    inv = 1.0 / mp
                    slicers.append(lambda d, r, inv=inv: d * inv)
                if leaf == "weight":
                    parent_path = path.rsplit(".", 1)[0] if "." in path \
                        else ""
                    row_parallel.append((subs.get(parent_path),
                                         path.rsplit(".", 1)[-1]))
            else:
                slicers.append(None)       # replicated (norms etc.)
        self._mp_slicers = slicers
        self._mp_row_parallel = [(o, a) for o, a in row_parallel
                                 if o is not None]
        # attention modules whose head count narrows to nh/mp while the
        # local views are bound
        self._mp_heads = [
            (s, int(s.num_heads)) for _, s in subs.items()
            if hasattr(s, "num_heads") and hasattr(s, "head_dim")
            and isinstance(getattr(s, "num_heads"), int)
            and s.num_heads % mp == 0
        ]

    # -- expert parallelism over the ep axis -----------------------------
    # COMPUTE is expert-parallel (storage flat-sharded 1/N by default,
    # like mp above): each ep rank binds the 1/ep slice
    # of every MoE expert stack into the template, and the MoE layer —
    # seeing sliced stacks inside a shard_map that binds the axis —
    # dispatches tokens to expert owners with explicit capacity-padded
    # lax.all_to_alls (moe_layer.py's EP path). Per-rank expert grads
    # are zero outside the rank's slice, so the (dp, ep) axis-tuple
    # scatter is simultaneously the data-parallel reduction and the
    # expert-parallel gradient assembly.
    def _setup_ep(self):
        from ..incubate.distributed.models.moe.moe_layer import MoELayer

        ep = self._ep_degree
        tmpl = self._template
        subs = dict(tmpl.named_sublayers(include_self=True))
        for path, sub in subs.items():
            if isinstance(sub, MoELayer):
                if sub.num_experts % ep:
                    raise ValueError(
                        f"{path or 'moe'}: num_experts "
                        f"{sub.num_experts} not divisible by ep degree "
                        f"{ep}")
                if sub.ep_degree not in (None, ep):
                    raise ValueError(
                        f"{path or 'moe'}: MoELayer(ep_degree="
                        f"{sub.ep_degree}) disagrees with the mesh's "
                        f"ep degree {ep}")
        def expert_slicer(degree):
            def fn(d, r):
                loc = d.shape[0] // degree
                return lax.dynamic_slice_in_dim(d, r * loc, loc, 0)

            return fn

        slicers = []
        for pname, p in tmpl.named_parameters():
            path = pname.rsplit(".", 1)[0] if "." in pname else ""
            leaf = pname.rsplit(".", 1)[-1]
            owner = subs.get(path)
            if isinstance(owner, MoELayer) and \
                    leaf.startswith("experts__"):
                slicers.append(expert_slicer(ep))
            else:
                slicers.append(None)     # gate weight, attention, norms
        if not any(s is not None for s in slicers):
            raise ValueError(
                "ep axis active but no expert-stacked parameters found "
                "in the block template")
        self._ep_slicers = slicers

    class _RowParallelPsum:
        """Call-through shim over a row-parallel Linear: local partial
        matmul (+ bias/mp), then one psum over the mp axis — the
        Megatron g-operator, inserted at trace time."""

        __slots__ = ("_inner", "_axis")

        def __init__(self, inner, axis):
            self._inner, self._axis = inner, axis

        def __call__(self, x):
            from ..framework.tensor import Tensor

            y = self._inner(x)
            return Tensor._wrap(lax.psum(y._data, self._axis))

    def _block_fn(self, leaf_datas, x, rng_off=None):
        if self._ep_axis is not None:
            r = lax.axis_index(self._ep_axis)
            local = [d if fn is None else fn(d, r)
                     for fn, d in zip(self._ep_slicers, leaf_datas)]
            # the bound 1/ep expert slices + the bound ep axis are what
            # flip MoELayer.forward onto its all_to_all dispatch path
            return super()._block_fn(local, x, rng_off=rng_off)
        if self._mp_axis is None:
            return super()._block_fn(leaf_datas, x, rng_off=rng_off)
        r = lax.axis_index(self._mp_axis)
        local = [d if fn is None else fn(d, r)
                 for fn, d in zip(self._mp_slicers, leaf_datas)]
        mp = self._mp_degree
        patched = []
        try:
            for obj, attr in self._mp_row_parallel:
                inner = getattr(obj, attr)
                object.__setattr__(
                    obj, attr, self._RowParallelPsum(inner,
                                                     self._mp_axis))
                patched.append((obj, attr))
            for obj, nh in self._mp_heads:
                object.__setattr__(obj, "num_heads", nh // mp)
            return super()._block_fn(local, x, rng_off=rng_off)
        finally:
            for obj, attr in patched:
                object.__delattr__(obj, attr)
            for obj, nh in self._mp_heads:
                object.__setattr__(obj, "num_heads", nh)

    def _head_fn(self, o_datas, xL, labels):
        """Vocab-parallel LM head under mp: ln_f on the replicated
        hiddens, then the PR-7 vocab-tiled fused CE over THIS rank's
        [vocab/mp, H] row shard of the head — per-rank losses are
        identical (the shard stats combine over mp inside the kernel's
        custom vjp), and the head grads each rank produces cover exactly
        its shard rows (zero-padded elsewhere), which is what lets the
        ordinary (dp, mp) grad scatter reassemble them with no full
        [vocab, H] gradient ever built."""
        if self._mp_axis is None:
            return super()._head_fn(o_datas, xL, labels)
        import jax.numpy as jnp

        from ..framework.autograd import no_grad
        from ..framework.tensor import Tensor
        from ..ops.pallas.fused_cross_entropy import (
            sharded_fused_cross_entropy,
        )

        m = self.model
        with no_grad():
            saved = self._bind([p for _, p in self._o_params],
                               self._cc(o_datas))
            try:
                h = m.gpt.ln_f(Tensor._wrap(xL))._data
                if m.lm_head is None:
                    w = m.gpt.wte.weight._data           # [V, H]
                else:
                    w = m.lm_head.weight._data.T         # [H, V] -> [V, H]
                vloc = w.shape[0] // self._mp_degree
                r = lax.axis_index(self._mp_axis)
                wl = lax.dynamic_slice_in_dim(w, r * vloc, vloc, 0)
                hid = h.reshape(-1, h.shape[-1])
                lbl = labels.reshape(-1)
                losses = sharded_fused_cross_entropy(
                    hid, wl, lbl, r * vloc, self._mp_axis)
                mask = (lbl != -100).astype(losses.dtype)
                return jnp.sum(losses * mask) / jnp.clip(
                    jnp.sum(mask), 1.0, None)
            finally:
                self._bind([p for _, p in self._o_params], saved)

    def input_sharding(self):
        """Batches stage dim-0-sharded 1/N over the batch axes (dp, or
        the flattened dp×ep product under expert parallelism) — each
        device receives only its shard of the global batch (the
        weight-update sharding lesson applied to ingestion), and the
        placement matches the step's shard_map batch spec so jit never
        reshards."""
        ba = (self._batch_axes if len(self._batch_axes) > 1
              else self._axis)
        return NamedSharding(self._mesh, P(ba))

    # -- flat sharded optimizer state -----------------------------------
    def _flat_key(self, grp, index):
        return f"__scan_shard_{grp}{index}__"

    def _bucket_params(self, grp, bucket):
        src = (dict(self._s_train) if grp == "s"
               else {j: p for j, (_, p) in enumerate(self._o_params)})
        return [src[e.key] for e in bucket.entries]

    def _bucket_uses_master(self, grp, bucket):
        return self._bucket_use_mw[grp][bucket.index]

    def _materialize_flat_state(self):
        """Build (or repack) the optimizer state as per-bucket flat
        arrays sharded 1/N over the axis. Fresh state is created
        SHARDED from the start (jit with out_shardings — zeros for
        moments, fp32 casts of the params for masters), so the first
        build never materializes the full replicated optimizer state
        the sharding exists to avoid; a continuation from per-param
        state (prior TrainStep run, old checkpoint) packs the existing
        full-shape entries once. Idempotent: an existing flat entry
        (second build, checkpoint restore) is reused as-is."""
        opt = self._opt
        mesh = self._mesh
        ax = self._axes if len(self._axes) > 1 else self._axis
        n_layers = self.model.config.num_layers
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            stacked = grp == "s"
            sharding = NamedSharding(
                mesh, P(None, ax) if stacked else P(ax))
            lead = (n_layers,) if stacked else ()
            for bucket in assign.buckets:
                fkey = self._flat_key(grp, bucket.index)
                params = dict(zip([e.key for e in bucket.entries],
                                  self._bucket_params(grp, bucket)))
                use_mw = self._bucket_uses_master(grp, bucket)
                md = self._moment_dtype(bucket, use_mw)

                def packed(leaves, dtype):
                    return jax.jit(
                        lambda lv: pack_flat(lambda k: lv[k], bucket,
                                             lead=lead, dtype=dtype),
                        out_shardings=sharding)(leaves)

                for name in ("moment1", "moment2"):
                    store = opt._accumulators.setdefault(name, {})
                    if fkey not in store:
                        if all(_key(p) in store
                               for p in params.values()):
                            store[fkey] = packed(
                                {k: store[_key(p)]
                                 for k, p in params.items()}, md)
                        else:
                            shape = lead + (bucket.numel,)
                            store[fkey] = jax.jit(
                                lambda s=shape, d=md: jnp.zeros(s, d),
                                out_shardings=sharding)()
                    for p in params.values():
                        store.pop(_key(p), None)
                if use_mw:
                    if fkey not in opt._master_weights:
                        opt._master_weights[fkey] = packed(
                            {k: opt._master_weights.get(_key(p),
                                                        p._data)
                             for k, p in params.items()},
                            jnp.float32)
                    for p in params.values():
                        opt._master_weights.pop(_key(p), None)

    def _moment_dtype(self, bucket, use_mw):
        md = self._opt._moment_dtype
        if md is not None:
            return md
        return jnp.float32 if use_mw else bucket.dtype

    # -- sharded parameter storage (ISSUE 11) ---------------------------
    def _shard_sharding(self, grp):
        ax = self._axes if len(self._axes) > 1 else self._axis
        return NamedSharding(self._mesh,
                             P(None, ax) if grp == "s" else P(ax))

    def _shard_stored_params(self, grp, bucket):
        """The live Parameter objects whose storage the (grp, bucket)
        flat shard owns."""
        if grp == "s":
            by_j = dict(self._s_train)
            return [by_j[e.key] for e in bucket.entries]
        return [self._o_params[e.key][1] for e in bucket.entries]

    def _pack_param_bucket(self, grp, bucket):
        """Pack the bucket's params from their CURRENT full `_data` into
        one flat array sharded 1/N over the reduction axes (the same
        layout/jit-out_shardings pattern `_materialize_flat_state`
        uses). Reads materialize stale entries first, so a partial
        external write (checkpoint restore touching one leaf) composes
        with shard-resident neighbours. The jitted packer is cached per
        (grp, bucket) — repack is a steady-state path (every restore /
        external write), and a fresh jit per call would recompile."""
        n_layers = self.model.config.num_layers
        lead = (n_layers,) if grp == "s" else ()
        params = self._shard_stored_params(grp, bucket)
        leaves = {e.key: p._data
                  for e, p in zip(bucket.entries, params)}
        fn = self._pack_jits.get((grp, bucket.index))
        if fn is None:
            fn = jax.jit(
                lambda lv: pack_flat(lambda k: lv[k], bucket,
                                     lead=lead),
                out_shardings=self._shard_sharding(grp))
            self._pack_jits[(grp, bucket.index)] = fn
        return fn(leaves)

    def _materialize_param_shards(self):
        """Flip parameter STORAGE to 1/N flat bucket shards: pack every
        trainable leaf once, swap the live Parameters to the lazy
        shard-backed class, and drop the full arrays (the stale
        sentinel) — from here on no full replicated parameter pytree
        exists between steps; reads gather on demand, external writes
        repack at the next step."""
        if self._param_storage != "sharded" or self._param_shards["s"] \
                or self._param_shards["o"]:
            if self._param_storage == "sharded":
                self._repack_dirty_param_buckets()
            return
        slot = _data_slot()
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            for bucket in assign.buckets:
                # NOTE: _pack_param_bucket reads p._data through the
                # lazy property, so a param still shard-backed by a
                # PREVIOUS step (rebuild-the-step workflow: new
                # optimizer, phase-2 fine-tune) materializes its
                # current values from the old step's shards first —
                # the takeover below then rebinds it to this step.
                # (Two steps training one model CONCURRENTLY remains
                # undefined, exactly as with replicated storage.)
                self._param_shards[grp].append(
                    self._pack_param_bucket(grp, bucket))
                for p in self._shard_stored_params(grp, bucket):
                    if not getattr(type(p), "_shard_backed", False):
                        p.__class__ = _lazy_param_class(type(p))
                    p.__dict__["_shard_ref"] = (self, grp, bucket.index)
                    slot.__set__(p, _STALE)
        self._dirty_param_buckets.clear()

    def _materialize_bucket_params(self, grp, bucket_index):
        """Lazy-read path: gather ONE bucket's flat shard back to a
        replicated array and fill the full `_data` of every entry that
        is still stale (an externally written entry keeps its new
        value). Called by the lazy Parameter's `_data` getter."""
        bucket = (self._s_assign if grp == "s"
                  else self._o_assign).buckets[bucket_index]
        flat = self._param_shards[grp][bucket_index]
        # one cached resharder for every bucket read: materialization is
        # a steady-state path (eval / checkpoint save between steps)
        if self._gather_jit is None:
            self._gather_jit = jax.jit(
                lambda v: v,
                out_shardings=NamedSharding(self._mesh, P()))
        full = self._gather_jit(flat)
        slot = _data_slot()
        n_layers = self.model.config.num_layers
        for e, p in zip(bucket.entries,
                        self._shard_stored_params(grp, bucket)):
            if slot.__get__(p) is not _STALE:
                continue
            leaf = full[..., e.offset:e.offset + e.numel]
            shape = ((n_layers,) + tuple(e.shape) if grp == "s"
                     else tuple(e.shape))
            slot.__set__(p, leaf.reshape(shape))

    def _invalidate_param_caches(self):
        """Post-step: drop any materialized full arrays so the shards
        stay the only live parameter bytes between steps."""
        slot = _data_slot()
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            for bucket in assign.buckets:
                for p in self._shard_stored_params(grp, bucket):
                    slot.__set__(p, _STALE)

    def _repack_dirty_param_buckets(self):
        """Pre-step: fold external `p._data` writes (checkpoint restore,
        test poking) back into the authoritative flat shards."""
        if not self._dirty_param_buckets:
            return
        for grp, bi in sorted(self._dirty_param_buckets):
            assign = self._s_assign if grp == "s" else self._o_assign
            self._param_shards[grp][bi] = self._pack_param_bucket(
                grp, assign.buckets[bi])
        self._dirty_param_buckets.clear()
        self._invalidate_param_caches()

    def _mem_owners(self):
        """Live-buffer attribution (ISSUE 14): under sharded storage
        the trainable params live as ``__scan_shard_*__`` 1/N flat
        bucket shards — claimed as ``params.scan_shards`` — and a
        scrape must NOT materialize them, so shard-backed leaves are
        read through the raw data slot (stale entries simply are not
        resident and claim nothing). Replicated storage falls through
        to the base attribution."""
        if self._param_storage != "sharded":
            return super()._mem_owners()
        owners = {"params.scan_shards":
                  [a for a in (self._param_shards["s"]
                               + self._param_shards["o"])
                   if a is not None],
                  "buffers": [b._data for b in self._buffers]}
        slot = _data_slot()
        live_full = []
        with _raw_param_access():
            for grp, assign in (("s", self._s_assign),
                                ("o", self._o_assign)):
                for bucket in assign.buckets:
                    for p in self._shard_stored_params(grp, bucket):
                        d = slot.__get__(p)
                        if d is not _STALE and d is not None:
                            live_full.append(d)
        # non-shard-stored leaves (non-trainable stacked params) keep
        # ordinary storage
        live_full.extend(p._data for j, p in enumerate(self._s_params)
                         if j not in self._s_trainable_idx)
        owners["params"] = live_full
        owners["opt_state"] = self._opt_state_arrays()
        return owners

    def full_params(self):
        """Materialize every shard-stored parameter's full `_data`
        (eval/export convenience; the next step drops the copies
        again). No-op under replicated storage."""
        if self._param_storage == "sharded":
            for _, p in self._s_train:
                _ = p._data
            for _, p in self._o_params:
                _ = p._data

    def ensure_built(self):
        if self._jitted is not None:
            return
        self._materialize_flat_state()
        self._materialize_param_shards()
        # canonicalize replicated-state layouts BEFORE the first trace:
        # the step's outputs come back mesh-committed, so an uncommitted
        # single-device param on call 1 would key a SECOND executable on
        # call 2 (the TrainStep._build layout lesson — one extra compile
        # is minutes of axon program load at 1.3b)
        rep = NamedSharding(self._mesh, P())
        shard_stored = (self._s_trainable_idx
                        if self._param_storage == "sharded" else set())
        for j, p in enumerate(self._s_params):
            if j not in shard_stored:
                p._data = jax.device_put(p._data, rep)
        if self._param_storage != "sharded":
            for _, p in self._o_params:
                p._data = jax.device_put(p._data, rep)
        for b in self._buffers:
            b._data = jax.device_put(b._data, rep)
        self._step_count = jax.device_put(
            jnp.asarray(int(self._opt._step_count), jnp.int32), rep)
        self._opt._step_count = self._step_count
        if self._guard is not None and self._guard.scaler is not None:
            # the scaler's traced mirrors must start mesh-committed too,
            # or call 2 (committed jit outputs) keys a second executable
            self._guard.writeback(jax.tree_util.tree_map(
                lambda v: jax.device_put(v, rep),
                self._guard.init_state()))
        self._build()
        self._publish_comm_gauges()
        # live-buffer attribution (ISSUE 14): weakly tracked provider
        from ..observability.memory import live_registry

        live_registry().track(self)

    def _extract_state(self):
        opt = self._opt
        self._step_count = opt._step_count   # restore-aware (base class)
        if self._param_storage == "sharded":
            st = {
                "s": {"p": [None if j in self._s_trainable_idx
                            else p._data
                            for j, p in enumerate(self._s_params)],
                      "fp": list(self._param_shards["s"])},
                "o": {"p": [None] * len(self._o_params),
                      "fp": list(self._param_shards["o"])},
                "buf": [b._data for b in self._buffers],
                "step": jnp.asarray(self._step_count, jnp.int32),
            }
        else:
            st = {
                "s": {"p": [p._data for p in self._s_params]},
                "o": {"p": [p._data for _, p in self._o_params]},
                "buf": [b._data for b in self._buffers],
                "step": jnp.asarray(self._step_count, jnp.int32),
            }
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            st[grp]["m"] = [opt._accumulators["moment1"]
                            [self._flat_key(grp, b.index)]
                            for b in assign.buckets]
            st[grp]["v"] = [opt._accumulators["moment2"]
                            [self._flat_key(grp, b.index)]
                            for b in assign.buckets]
            st[grp]["mw"] = [opt._master_weights.get(
                self._flat_key(grp, b.index)) for b in assign.buckets]
        if self._guard is not None:
            st["guard"] = self._guard.init_state()
        return st

    def _inject_state(self, state):
        opt = self._opt
        if self._param_storage == "sharded":
            self._param_shards["s"] = list(state["s"]["fp"])
            self._param_shards["o"] = list(state["o"]["fp"])
            for j, (p, d) in enumerate(zip(self._s_params,
                                           state["s"]["p"])):
                if j not in self._s_trainable_idx:
                    p._data = d
            # full-param caches are stale now (and their device buffers
            # must die): the shards are the only live parameter bytes
            self._invalidate_param_caches()
        else:
            for p, d in zip(self._s_params, state["s"]["p"]):
                p._data = d
            for (_, p), d in zip(self._o_params, state["o"]["p"]):
                p._data = d
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            for b in assign.buckets:
                fkey = self._flat_key(grp, b.index)
                opt._accumulators["moment1"][fkey] = \
                    state[grp]["m"][b.index]
                opt._accumulators["moment2"][fkey] = \
                    state[grp]["v"][b.index]
                mw = state[grp]["mw"][b.index]
                if mw is not None:
                    opt._master_weights[fkey] = mw
        for b, d in zip(self._buffers, state["buf"]):
            b._data = d
        opt._step_count = state["step"]
        self._step_count = state["step"]
        if self._guard is not None and "guard" in state:
            self._guard.writeback(state["guard"])

    def _state_specs(self):
        ax = self._axes if len(self._axes) > 1 else self._axis
        rep = P()
        if self._param_storage == "sharded":
            specs = {
                "s": {"p": [None if j in self._s_trainable_idx else rep
                            for j in range(len(self._s_params))],
                      "fp": [P(None, ax)] * len(self._s_assign.buckets)},
                "o": {"p": [None] * len(self._o_params),
                      "fp": [P(ax)] * len(self._o_assign.buckets)},
                "buf": [rep] * len(self._buffers),
                "step": rep,
            }
        else:
            specs = {
                "s": {"p": [rep] * len(self._s_params)},
                "o": {"p": [rep] * len(self._o_params)},
                "buf": [rep] * len(self._buffers),
                "step": rep,
            }
        if self._guard is not None:
            specs["guard"] = {"scale": rep, "good": rep, "bad": rep,
                              "found": rep, "skipped": rep}
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            sp = P(None, ax) if grp == "s" else P(ax)
            nb = len(assign.buckets)
            specs[grp]["m"] = [sp] * nb
            specs[grp]["v"] = [sp] * nb
            specs[grp]["mw"] = [
                sp if self._bucket_uses_master(grp, b) else None
                for b in assign.buckets]
        return specs

    # -- the compiled sharded step --------------------------------------
    def _build_prologue(self):
        """Host-side per-bucket hyperparameter tables shared by the
        grads pass and the update scan (built once per _build)."""
        opt = self._opt

        def hyper(p):
            return (float(opt._decoupled_wd(p)), float(opt._l2_coeff(p)),
                    float(opt._param_lr_scale(p)))

        def bucket_hp(grp, bucket):
            params = self._bucket_params(grp, bucket)
            hs = [hyper(p) for p in params]
            ent = bucket.entries
            wd = _vec_or_scalar([h[0] for h in hs], ent, bucket.numel)
            l2 = _vec_or_scalar([h[1] for h in hs], ent, bucket.numel)
            lrs = _vec_or_scalar([h[2] for h in hs], ent, bucket.numel,
                                 pad_value=1.0)
            ncs = [1.0 if getattr(p, "need_clip", True) else 0.0
                   for p in params]
            # None = "everything clips" (the common case, no masking);
            # a uniform 0.0 or a mixed vector masks the clip per entry
            nc = (None if all(v == 1.0 for v in ncs)
                  else _vec_or_scalar(ncs, ent, bucket.numel))
            return wd, l2, lrs, nc

        self._s_hp = [bucket_hp("s", b) for b in self._s_assign.buckets]
        self._o_hp = [bucket_hp("o", b) for b in self._o_assign.buckets]
        self._t_idx = {j: tj for tj, (j, _)
                       in enumerate(self._s_train)}

    @staticmethod
    def _shard_of(vec, rank, shard_len):
        """Own-rank slice of a replicated flat [F] constant (no-op for
        uniform scalars)."""
        if vec is None or isinstance(vec, float):
            return vec
        return lax.dynamic_slice_in_dim(vec, rank * shard_len,
                                        shard_len, 0)

    def _sq_of(self, gs, nc_shard):
        g32 = gs.astype(jnp.float32) * (1.0 / self._degree)
        if nc_shard is not None:
            g32 = g32 * nc_shard
        return jnp.sum(jnp.square(g32))

    def _clip_monitor_sq(self, gs, nc, clip_on, mon_on):
        """ONE shard reduction feeding BOTH the clip's norm carry and
        the monitor's grad sq-norm row (ISSUE 15 dedup — the single
        implementation behind every grads path, replicated / sharded-
        storage / pipeline). Returns ``(clip_term, monitor_term)``:
        ``clip_term`` is None with clipping off; ``monitor_term`` is
        None with the monitor off, reads the clip's sum when both are
        on, and only a need_clip mask (``nc``) forces a second,
        differently-masked sum — the monitor's row must be the
        UNMASKED norm."""
        s_b = self._sq_of(gs, nc if clip_on else None)
        mon = None
        if mon_on:
            mon = (s_b if nc is None or not clip_on
                   else self._sq_of(gs, None))
        return (s_b if clip_on else None), mon

    # -- gather-on-use plumbing (sharded parameter storage) --------------
    def _stacked_nontrainable(self, s_state):
        """[(leaf index j, data)] for the frozen stacked leaves riding
        `state['s']['p']` beside the shard-stored trainable ones."""
        return [(j, d) for j, d in enumerate(s_state["p"])
                if j not in self._s_trainable_idx]

    def _leaves_of(self, trainable, nontrainable):
        """Compose the full per-chunk leaf list (template order) from
        the gathered trainable tuple (ordered like `_s_train`) and the
        scanned non-trainable chunk slices."""
        lv = [None] * len(self._s_params)
        for (j, _), d in zip(self._s_train, trainable):
            lv[j] = d
        for (j, _), d in nontrainable:
            lv[j] = d
        return lv

    def _gather_outer_full(self, o_state):
        """Gather the outer params' flat shards back to full leaf
        arrays (ordered like `_o_params`) — once per step, at the top
        of the traced body; the full set dies with the step."""
        quant = self._comm_quant
        full = [None] * len(self._o_params)
        for bkt in self._o_assign.buckets:
            fb = gather_flat(o_state["fp"][bkt.index], self._axes,
                             axis=0, quant=quant)
            for key, leaf in unpack_flat(fb, bkt).items():
                full[key] = leaf
        return full

    def _gather_stacked_chunk(self, fp_c, i):
        """All-gather chunk ``i``'s params from the [C, K, F/N] flat
        shard stacks: one (optionally quantized) tiled all_gather per
        bucket over the flattened reduction axes, unpacked to the
        per-leaf [K, ...] views the block template binds. Returns a
        tuple ordered like `_s_train`."""
        quant = self._comm_quant
        out = {}
        for bkt in self._s_assign.buckets:
            fs = lax.dynamic_index_in_dim(fp_c[bkt.index], i,
                                          keepdims=False)     # [K, F/N]
            fb = gather_flat(fs, self._axes, axis=1, quant=quant)
            out.update(unpack_flat(fb, bkt))                  # [K, F]
        return tuple(out[j] for j, _ in self._s_train)

    def _grads(self, state, ids, labels, t32, ct):
        """Forward + backward producing the SCATTERED gradient shards:
        returns (loss, G, o_gs, sq, fin) where G[bucket] is [C, K, F/N]
        (this rank's 1/N shard per layer chunk), o_gs[bucket] is [F/N],
        sq the local shard's squared-norm contribution and fin the local
        finiteness fold. Default implementation is the in-scan
        reduce-scatter backward; the pipeline step overrides this with
        the ring schedule while reusing everything downstream. Under
        ``param_storage='sharded'`` the forward/backward scans gather
        each chunk's params on use (double-buffered prefetch) instead of
        reading replicated stacks."""
        if self._param_storage == "sharded":
            return self._grads_sharded_storage(state, ids, labels, t32,
                                               ct)
        from .fused_scan_step import _act_stats
        from .nonfinite_guard import all_finite

        s, o = state["s"], state["o"]
        axes, N = self._axes, self._degree
        K = self._layer_chunk
        n_layers = self.model.config.num_layers
        C = n_layers // K
        quant = self._comm_quant
        s_assign, o_assign = self._s_assign, self._o_assign
        clip_norm = self._clip_global
        guard = self._guard
        nm = self._numerics is not None
        rank = self._flat_rank()
        chunk_apply = self._chunk_apply
        b, seq = ids.shape          # LOCAL batch rows
        pos = jnp.arange(seq, dtype=ids.dtype)[None, :]

        aux_active = self._aux_active
        aux_w = self._aux_weight / n_layers

        # ---- forward (replicated params, local batch shard)
        x0 = self._embed_fn(o["p"], ids, pos,
                            rng_off=self._rng_base(t32, n_layers))
        sp_c = tuple(a.reshape((C, K) + tuple(a.shape[1:]))
                     for a in s["p"])

        def fwd_body(carry, scanned):
            h, h_fin = carry if nm else (carry, None)
            p_chunk, i = scanned
            rng0 = self._rng_chunk_base(t32, i)
            if aux_active:
                h2, aux = chunk_apply(p_chunk, h, rng0)
            else:
                h2, aux = chunk_apply(p_chunk, h, rng0), None
            ys = {"x": h}
            if aux_active:
                ys["aux"] = aux
            if not nm:
                return h2, ys
            ys["act"], out_fin = _act_stats(h_fin, h2)  # local rows:
            return (h2, out_fin), ys          # rank partials sum at host

        fwd0 = ((x0, jnp.isfinite(x0).all()) if nm else x0)
        fwd_c, ys = lax.scan(fwd_body, fwd0, (sp_c, jnp.arange(C)),
                             unroll=self._scan_unroll)
        xL = fwd_c[0] if nm else fwd_c
        xs, auxs = ys["x"], ys.get("aux")
        act_cols = ys.get("act")

        loss, head_vjp = jax.vjp(
            lambda od, x: self._head_fn(od, x, labels),
            o["p"], xL)
        d_o_head, dxL = head_vjp(ct.astype(loss.dtype))
        aux_ct = None
        if aux_active:
            # total per-rank loss = CE + (w/L)*sum(aux); the chunk vjps
            # get the matching loss-scaled cotangent
            loss = loss + jnp.float32(aux_w) * jnp.sum(auxs)
            aux_ct = jnp.float32(aux_w) * ct.astype(jnp.float32)

        # ---- backward scan: vjp one chunk, reduce-scatter its
        # bucket-packed grads over the FLATTENED reduction axes (dp, or
        # dp×mp); ONLY the 1/N shard, the running squared norm, and the
        # finiteness fold survive the iteration. Under mp the per-rank
        # dp covers only the rank's head/column slice (zero-padded
        # elsewhere), so the axis-tuple sum is simultaneously the
        # data-parallel reduction AND the tensor-parallel grad
        # assembly — no full-gradient gather exists at any point.
        G0 = tuple(jnp.zeros((C, K, bkt.numel // N), bkt.dtype)
                   for bkt in s_assign.buckets)

        def bwd_body(carry, scanned):
            dy, sq, fin, G = carry
            x_i, i = scanned
            p_i = tuple(
                lax.dynamic_index_in_dim(a, i, keepdims=False)
                for a in sp_c)
            rng0 = self._rng_chunk_base(t32, i)
            _, vjp = jax.vjp(
                lambda pl, xx: chunk_apply(pl, xx, rng0),
                p_i, x_i)
            dp, dx = vjp((dy, aux_ct) if aux_active else dy)
            newG = []
            c_sq = jnp.float32(0.0)
            c_fin = jnp.bool_(True)
            for bkt in s_assign.buckets:
                flat = pack_flat(lambda j: dp[j], bkt, lead=(K,))
                gs = scatter_flat(flat, axes, N, quant)  # [K,F/N]
                # one set of shard reductions feeds BOTH the clip's
                # norm carry and the monitor's per-chunk row (ISSUE 15
                # dedup); only a need_clip mask forces a second,
                # differently-masked sum
                if clip_norm is not None or nm:
                    nc = self._shard_of(self._s_hp[bkt.index][3], rank,
                                        bkt.numel // N)
                    ct_b, mt_b = self._clip_monitor_sq(
                        gs, nc, clip_norm is not None, nm)
                    if ct_b is not None:
                        sq = sq + ct_b
                    if nm:
                        c_sq = c_sq + mt_b
                if guard is not None:
                    # exact isfinite for the guard's skip decision
                    b_fin = all_finite([gs])
                    c_fin = c_fin & b_fin
                    fin = fin & b_fin
                newG.append(lax.dynamic_update_index_in_dim(
                    G[bkt.index], gs, i, 0))
            row = None
            if nm:
                if guard is None:
                    c_fin = jnp.isfinite(c_sq)   # no extra grad pass
                row = jnp.stack([
                    c_sq, (~c_fin).astype(jnp.float32),
                    jnp.float32(0.0)])
            return (dx, sq, fin, tuple(newG)), row

        (dx0, sq, fin, G), grad_cols = lax.scan(
            bwd_body,
            (dxL, jnp.float32(0.0), jnp.bool_(True), G0),
            (xs, jnp.arange(C)), reverse=True,
            unroll=self._scan_unroll)

        # ---- outer grads: same pack + reduce-scatter
        _, emb_vjp = jax.vjp(
            lambda od: self._embed_fn(
                od, ids, pos,
                rng_off=self._rng_base(t32, n_layers)), o["p"])
        (d_o_emb,) = emb_vjp(dx0)
        o_gs = []
        o_sq = jnp.float32(0.0)
        o_fin = jnp.bool_(True)
        for bkt in o_assign.buckets:
            flat = pack_flat(
                lambda j: (d_o_head[j].astype(jnp.float32)
                           + d_o_emb[j].astype(jnp.float32)),
                bkt)
            gs = scatter_flat(flat, axes, N, quant)      # [F/N]
            if clip_norm is not None or nm:
                nc = self._shard_of(self._o_hp[bkt.index][3], rank,
                                    bkt.numel // N)
                ct_b, mt_b = self._clip_monitor_sq(
                    gs, nc, clip_norm is not None, nm)
                if ct_b is not None:
                    sq = sq + ct_b
                if nm:
                    o_sq = o_sq + mt_b
            if guard is not None:
                b_fin = all_finite([gs])
                o_fin = o_fin & b_fin
                fin = fin & b_fin
            o_gs.append(gs)
        nrows = None
        if nm:
            if guard is None:
                o_fin = jnp.isfinite(o_sq)       # no extra grad pass
            nrows = {"grad": grad_cols, "act": act_cols,
                     "outer": jnp.stack([
                         o_sq, (~o_fin).astype(jnp.float32)])}
        return loss, G, o_gs, sq, fin, nrows

    def _grads_sharded_storage(self, state, ids, labels, t32, ct):
        """The gather-on-use form of `_grads` (ISSUE 11): params enter
        as 1/N flat bucket shards. The forward scan carries chunk i's
        GATHERED params while issuing the gather for chunk i+1 — a
        double-buffered prefetch slot, so the (independent) all_gather
        and the block compute land in the same while-body for XLA's
        latency-hiding scheduler at any scan_unroll (>=2 additionally
        interleaves adjacent chunks, mirroring the update-scan
        overlap). The backward scan re-gathers each chunk the same way
        (reverse direction, same double buffer) for its vjp recompute,
        so at most TWO chunks' full params are ever live and no full
        parameter set exists at any point. Outer params gather once at
        the top and die with the step. Values are bit-identical to the
        replicated-storage step: the shards hold exactly the bytes the
        replicated stacks would (pack/gather is concat/slice), unless
        FLAGS_comm_quant compresses the gather leg (opt-in, lossy)."""
        from .fused_scan_step import _act_stats
        from .nonfinite_guard import all_finite

        s, o = state["s"], state["o"]
        axes, N = self._axes, self._degree
        K = self._layer_chunk
        n_layers = self.model.config.num_layers
        C = n_layers // K
        quant = self._comm_quant
        s_assign, o_assign = self._s_assign, self._o_assign
        clip_norm = self._clip_global
        guard = self._guard
        nm = self._numerics is not None
        rank = self._flat_rank()
        chunk_apply = self._chunk_apply
        b, seq = ids.shape          # LOCAL batch rows
        pos = jnp.arange(seq, dtype=ids.dtype)[None, :]
        aux_active = self._aux_active
        aux_w = self._aux_weight / n_layers

        o_full = self._gather_outer_full(o)
        fp_c = [a.reshape((C, K, -1)) for a in s["fp"]]
        nt = self._stacked_nontrainable(s)
        nt_c = tuple(d.reshape((C, K) + tuple(d.shape[1:]))
                     for _, d in nt)

        def gather_chunk(i):
            return self._gather_stacked_chunk(fp_c, i)

        def leaves_of(tr, nt_i):
            return self._leaves_of(tr, list(zip([j for j, _ in nt],
                                                nt_i)))

        # ---- forward: double-buffered gather-on-use over the chunks
        x0 = self._embed_fn(o_full, ids, pos,
                            rng_off=self._rng_base(t32, n_layers))

        def fwd_body(carry, scanned):
            if nm:
                h, cur, h_fin = carry
            else:
                (h, cur), h_fin = carry, None
            nt_i, i = scanned
            # prefetch: chunk i+1's gather is data-independent of chunk
            # i's compute below (the wrap at i=C-1 re-gathers chunk 0 —
            # one wasted gather per scan, 1/C of the param traffic)
            nxt = gather_chunk(jnp.remainder(i + 1, C))
            rng0 = self._rng_chunk_base(t32, i)
            if aux_active:
                h2, aux = chunk_apply(leaves_of(cur, nt_i), h, rng0)
            else:
                h2 = chunk_apply(leaves_of(cur, nt_i), h, rng0)
                aux = None
            ys = {"x": h}
            if aux_active:
                ys["aux"] = aux
            if not nm:
                return (h2, nxt), ys
            ys["act"], out_fin = _act_stats(h_fin, h2)
            return (h2, nxt, out_fin), ys

        g0 = gather_chunk(jnp.int32(0))
        fwd0 = ((x0, g0, jnp.isfinite(x0).all()) if nm else (x0, g0))
        fwd_c, ys = lax.scan(
            fwd_body, fwd0,
            (nt_c, jnp.arange(C)), unroll=self._scan_unroll)
        xL = fwd_c[0]
        xs, auxs = ys["x"], ys.get("aux")
        act_cols = ys.get("act")

        loss, head_vjp = jax.vjp(
            lambda od, x: self._head_fn(od, x, labels), o_full, xL)
        d_o_head, dxL = head_vjp(ct.astype(loss.dtype))
        aux_ct = None
        if aux_active:
            loss = loss + jnp.float32(aux_w) * jnp.sum(auxs)
            aux_ct = jnp.float32(aux_w) * ct.astype(jnp.float32)

        # ---- backward: re-gather each chunk (reverse double buffer)
        # for the vjp recompute; only the scattered 1/N grad shards,
        # the norm scalar and the finiteness fold survive an iteration
        G0 = tuple(jnp.zeros((C, K, bkt.numel // N), bkt.dtype)
                   for bkt in s_assign.buckets)

        def bwd_body(carry, scanned):
            dy, sq, fin, G, cur = carry
            x_i, nt_i, i = scanned
            prv = gather_chunk(jnp.remainder(i - 1 + C, C))
            rng0 = self._rng_chunk_base(t32, i)
            p_i = tuple(leaves_of(cur, nt_i))
            _, vjp = jax.vjp(
                lambda pl, xx: chunk_apply(pl, xx, rng0), p_i, x_i)
            dp, dx = vjp((dy, aux_ct) if aux_active else dy)
            newG = []
            c_sq = jnp.float32(0.0)
            c_fin = jnp.bool_(True)
            for bkt in s_assign.buckets:
                flat = pack_flat(lambda j: dp[j], bkt, lead=(K,))
                gs = scatter_flat(flat, axes, N, quant)  # [K, F/N]
                # clip carry + monitor row share one shard reduction
                # (ISSUE 15 dedup; see the replicated _grads)
                if clip_norm is not None or nm:
                    nc = self._shard_of(self._s_hp[bkt.index][3], rank,
                                        bkt.numel // N)
                    ct_b, mt_b = self._clip_monitor_sq(
                        gs, nc, clip_norm is not None, nm)
                    if ct_b is not None:
                        sq = sq + ct_b
                    if nm:
                        c_sq = c_sq + mt_b
                if guard is not None:
                    # exact isfinite for the guard's skip decision
                    b_fin = all_finite([gs])
                    c_fin = c_fin & b_fin
                    fin = fin & b_fin
                newG.append(lax.dynamic_update_index_in_dim(
                    G[bkt.index], gs, i, 0))
            row = None
            if nm:
                if guard is None:
                    c_fin = jnp.isfinite(c_sq)   # no extra grad pass
                row = jnp.stack([
                    c_sq, (~c_fin).astype(jnp.float32),
                    jnp.float32(0.0)])
            return (dx, sq, fin, tuple(newG), prv), row

        (dx0, sq, fin, G, _), grad_cols = lax.scan(
            bwd_body,
            (dxL, jnp.float32(0.0), jnp.bool_(True), G0,
             gather_chunk(jnp.int32(C - 1))),
            (xs, nt_c, jnp.arange(C)), reverse=True,
            unroll=self._scan_unroll)

        # ---- outer grads: same pack + reduce-scatter as replicated
        _, emb_vjp = jax.vjp(
            lambda od: self._embed_fn(
                od, ids, pos,
                rng_off=self._rng_base(t32, n_layers)), o_full)
        (d_o_emb,) = emb_vjp(dx0)
        o_gs = []
        o_sq = jnp.float32(0.0)
        o_fin = jnp.bool_(True)
        for bkt in o_assign.buckets:
            flat = pack_flat(
                lambda j: (d_o_head[j].astype(jnp.float32)
                           + d_o_emb[j].astype(jnp.float32)),
                bkt)
            gs = scatter_flat(flat, axes, N, quant)      # [F/N]
            if clip_norm is not None or nm:
                nc = self._shard_of(self._o_hp[bkt.index][3], rank,
                                    bkt.numel // N)
                ct_b, mt_b = self._clip_monitor_sq(
                    gs, nc, clip_norm is not None, nm)
                if ct_b is not None:
                    sq = sq + ct_b
                if nm:
                    o_sq = o_sq + mt_b
            if guard is not None:
                b_fin = all_finite([gs])
                o_fin = o_fin & b_fin
                fin = fin & b_fin
            o_gs.append(gs)
        nrows = None
        if nm:
            if guard is None:
                o_fin = jnp.isfinite(o_sq)       # no extra grad pass
            nrows = {"grad": grad_cols, "act": act_cols,
                     "outer": jnp.stack([
                         o_sq, (~o_fin).astype(jnp.float32)])}
        return loss, G, o_gs, sq, fin, nrows

    def _build(self):
        opt = self._opt
        mesh, N = self._mesh, self._degree
        axes = self._axes
        K = self._layer_chunk
        n_layers = self.model.config.num_layers
        C = n_layers // K
        s_assign, o_assign = self._s_assign, self._o_assign
        inv_n = 1.0 / N
        self._build_prologue()
        s_hp, o_hp = self._s_hp, self._o_hp
        t_idx = self._t_idx
        cv = self._clip_value
        clip_norm = self._clip_global
        guard = self._guard
        scaling = guard is not None and guard.scaling
        nm = self._numerics is not None
        shard_of = self._shard_of

        def g_shard_f32(gs, nc_shard, scale, inv_s=None):
            """Scatter output -> the fp32 gradient the update consumes:
            1/N for the data-parallel mean (and the uniform replication
            factor the joint mp/pp vjp carries), loss-scale unscale,
            value clip, global-norm scale (need_clip-masked)."""
            g32 = gs.astype(jnp.float32) * inv_n
            if inv_s is not None:
                g32 = g32 * inv_s
            if cv is not None:
                clipped = jnp.clip(g32, cv[0], cv[1])
                g32 = (clipped if nc_shard is None
                       else nc_shard * clipped + (1 - nc_shard) * g32)
            if scale is not None:
                eff = (scale if nc_shard is None
                       else nc_shard * scale + (1 - nc_shard))
                g32 = g32 * eff
            return g32

        def adam_shard(pv, g32, m, v, lr_lrs, tf, wd, l2):
            if not (isinstance(l2, float) and l2 == 0.0):
                g32 = g32 + l2 * pv.astype(jnp.float32)
            return opt._adam_math(pv, g32, m, v, None, lr_lrs, tf, wd)

        from ..nn.functional.flash_attention import attention_segments

        def _assemble_stats(nrows, pu_cols, o_p_sq, o_u_sq, inv_s):
            """The [1, C+1, NFIELDS] per-rank numerics partial
            (ISSUE 15): the leading length-1 axis carries the
            reduction-axis out_spec, so the mesh STACKS rank partials
            (no collective) and the host fold sums them."""
            from ..observability import numerics as _num

            g_cols, act, og = nrows["grad"], nrows["act"], nrows["outer"]
            g_sq, og_sq = g_cols[:, 0], og[0]
            if inv_s is not None:
                s2 = inv_s * inv_s    # shard grads carried the scale
                g_sq = g_sq * s2
                og_sq = og_sq * s2
            # sums are per-rank partials: every sq/count/flag field
            # folds by addition at readback time
            return _num.assemble_stats(
                g_sq, pu_cols[:, 0], pu_cols[:, 1], act[:, 0],
                act[:, 1], g_cols[:, 1], act[:, 2], g_cols[:, 2],
                outer=_num.outer_row(og_sq, o_p_sq, o_u_sq,
                                     og[1]))[None]

        def step_fn(state, lr, ids, labels, seg=None):
            s, o = state["s"], state["o"]
            saved_buf = self._bind(self._buffers, state["buf"])
            # packed-sequence segment ids (local batch rows, sharded
            # like ids) published to the in-scan attention layers
            seg_ctx = attention_segments(seg)
            seg_ctx.__enter__()
            try:
                gst = state.get("guard")
                inv_s = (1.0 / gst["scale"]) if scaling else None
                t = state["step"] + 1
                tf = t.astype(jnp.float32)
                t32 = t.astype(jnp.int32)
                rank = self._flat_rank()
                ct = (gst["scale"] if scaling
                      else jnp.ones((), jnp.float32))

                loss, G, o_gs, sq, fin, nrows = self._grads(
                    state, ids, labels, t32, ct)
                sharded_storage = self._param_storage == "sharded"
                if not sharded_storage:
                    sp_c = tuple(a.reshape((C, K) + tuple(a.shape[1:]))
                                 for a in s["p"])

                # ---- the fused global-norm clip + cross-rank found_inf:
                # still ONE scalar all-reduce (a length-2 psum when the
                # guard is on — norm and finiteness ride together)
                scale = None
                found = None
                if clip_norm is not None or guard is not None:
                    bad_local = (jnp.float32(0.0) if guard is None
                                 else (~fin).astype(jnp.float32))
                    tot = lax.psum(jnp.stack([sq, bad_local]), axes)
                    if guard is not None:
                        found = tot[1] > 0
                    if clip_norm is not None:
                        # shard grads carry the loss scale: true norm is
                        # sqrt(psum(sq))/loss_scale
                        gnorm = jnp.sqrt(tot[0])
                        if inv_s is not None:
                            gnorm = gnorm * inv_s
                        scale = jnp.minimum(
                            jnp.float32(clip_norm)
                            / jnp.maximum(gnorm, 1e-12), 1.0)

                # ---- update scan: sharded Adam on each chunk's grad
                # shard. Replicated storage then all_gathers the updated
                # shard back into the replicated param stacks (bucket
                # b's gather is independent of bucket b+1's math — the
                # overlap the HLO probe checks for); sharded storage
                # just WRITES the shard back — the gather moved to the
                # next step's forward (gather-on-use).
                sM = [m.reshape((C, K, -1)) for m in s["m"]]
                sV = [v.reshape((C, K, -1)) for v in s["v"]]
                sMW = [mw.reshape((C, K, -1)) if mw is not None else None
                       for mw in s["mw"]]
                if sharded_storage:
                    FP0 = [a.reshape((C, K, -1)) for a in s["fp"]]

                    def upd_body_sharded(carry, i):
                        FP, M, V, MW = carry
                        p_sq = u_sq = jnp.float32(0.0)
                        for bkt in s_assign.buckets:
                            bi = bkt.index
                            shard_len = bkt.numel // N
                            wd, l2, lrs, nc = (
                                shard_of(h, rank, shard_len)
                                for h in s_hp[bi])
                            g32 = g_shard_f32(
                                lax.dynamic_index_in_dim(
                                    G[bi], i, keepdims=False),
                                nc, scale, inv_s)
                            m_i = lax.dynamic_index_in_dim(
                                M[bi], i, keepdims=False)
                            v_i = lax.dynamic_index_in_dim(
                                V[bi], i, keepdims=False)
                            if MW[bi] is not None:
                                pv = lax.dynamic_index_in_dim(
                                    MW[bi], i, keepdims=False)
                            else:
                                # fp32-stored params ARE the master, and
                                # the stored shard IS this rank's slice
                                pv = lax.dynamic_index_in_dim(
                                    FP[bi], i, keepdims=False)
                            out32, mn, vn, _ = adam_shard(
                                pv, g32, m_i, v_i, lr * lrs, tf, wd, l2)
                            if found is not None:
                                # bad step: the stored shard passes
                                # through bit-identical (no rebuild
                                # needed — storage IS the shard)
                                out32 = jnp.where(found, pv, out32)
                                mn = jnp.where(found, m_i, mn)
                                vn = jnp.where(found, v_i, vn)
                            if nm:
                                pv32 = pv.astype(jnp.float32)
                                p_sq = p_sq + jnp.sum(jnp.square(pv32))
                                u_sq = u_sq + jnp.sum(jnp.square(
                                    out32.astype(jnp.float32) - pv32))
                            M[bi] = lax.dynamic_update_index_in_dim(
                                M[bi], mn.astype(M[bi].dtype), i, 0)
                            V[bi] = lax.dynamic_update_index_in_dim(
                                V[bi], vn.astype(V[bi].dtype), i, 0)
                            if MW[bi] is not None:
                                MW[bi] = lax.dynamic_update_index_in_dim(
                                    MW[bi], out32, i, 0)
                            FP[bi] = lax.dynamic_update_index_in_dim(
                                FP[bi], out32.astype(bkt.dtype), i, 0)
                        return (FP, M, V, MW), (
                            jnp.stack([p_sq, u_sq]) if nm else {})

                    (FP, sM, sV, sMW), pu_cols = lax.scan(
                        upd_body_sharded,
                        (list(FP0), list(sM), list(sV), list(sMW)),
                        jnp.arange(C), unroll=self._scan_unroll)
                    new_sp = list(s["p"])
                    new_s_fp = [a.reshape((n_layers, -1)) for a in FP]

                    # ---- outer update (no scan): shard in, shard out
                    new_op = list(o["p"])
                    new_o_fp = []
                    new_om, new_ov, new_omw = [], [], []
                    o_p_sq = o_u_sq = jnp.float32(0.0)
                    for bkt in o_assign.buckets:
                        bi = bkt.index
                        shard_len = bkt.numel // N
                        wd, l2, lrs, nc = (shard_of(h, rank, shard_len)
                                           for h in o_hp[bi])
                        g32 = g_shard_f32(o_gs[bi], nc, scale, inv_s)
                        m_i, v_i = o["m"][bi], o["v"][bi]
                        pv = (o["mw"][bi] if o["mw"][bi] is not None
                              else o["fp"][bi])
                        out32, mn, vn, _ = adam_shard(
                            pv, g32, m_i, v_i, lr * lrs, tf, wd, l2)
                        if found is not None:
                            out32 = jnp.where(found, pv, out32)
                            mn = jnp.where(found, m_i, mn)
                            vn = jnp.where(found, v_i, vn)
                        if nm:
                            pv32 = pv.astype(jnp.float32)
                            o_p_sq = o_p_sq + jnp.sum(jnp.square(pv32))
                            o_u_sq = o_u_sq + jnp.sum(jnp.square(
                                out32.astype(jnp.float32) - pv32))
                        new_om.append(mn.astype(m_i.dtype))
                        new_ov.append(vn.astype(v_i.dtype))
                        new_omw.append(out32 if o["mw"][bi] is not None
                                       else None)
                        new_o_fp.append(out32.astype(bkt.dtype))

                    new_state = {
                        "s": {"p": new_sp, "fp": new_s_fp,
                              "m": [m.reshape((n_layers, -1))
                                    for m in sM],
                              "v": [v.reshape((n_layers, -1))
                                    for v in sV],
                              "mw": [mw.reshape((n_layers, -1))
                                     if mw is not None else None
                                     for mw in sMW]},
                        "o": {"p": new_op, "fp": new_o_fp,
                              "m": new_om, "v": new_ov, "mw": new_omw},
                        "buf": state["buf"],
                        "step": (t if found is None
                                 else jnp.where(found, state["step"],
                                                t)),
                    }
                    if guard is not None:
                        new_state["guard"] = guard.update(gst, found)
                    loss_out = lax.psum(loss, axes) * inv_n
                    if not nm:
                        return loss_out, new_state
                    return loss_out, new_state, _assemble_stats(
                        nrows, pu_cols, o_p_sq, o_u_sq, inv_s)

                P_tr0 = tuple(sp_c[j] for j, _ in self._s_train)

                def upd_body(carry, i):
                    P_tr, M, V, MW = carry
                    p_sq = u_sq = jnp.float32(0.0)
                    for bkt in s_assign.buckets:
                        bi = bkt.index
                        shard_len = bkt.numel // N
                        wd, l2, lrs, nc = (shard_of(h, rank, shard_len)
                                           for h in s_hp[bi])
                        g32 = g_shard_f32(
                            lax.dynamic_index_in_dim(G[bi], i,
                                                     keepdims=False),
                            nc, scale, inv_s)
                        m_i = lax.dynamic_index_in_dim(M[bi], i,
                                                       keepdims=False)
                        v_i = lax.dynamic_index_in_dim(V[bi], i,
                                                       keepdims=False)
                        if MW[bi] is not None:
                            pv = lax.dynamic_index_in_dim(
                                MW[bi], i, keepdims=False)
                        else:
                            # fp32-stored params ARE the master: slice
                            # this rank's shard out of the replicated
                            # chunk (bit-exact round trip via the
                            # gather below)
                            flat_p = pack_flat(
                                lambda j: lax.dynamic_index_in_dim(
                                    P_tr[t_idx[j]], i, keepdims=False),
                                bkt, lead=(K,))
                            pv = lax.dynamic_slice_in_dim(
                                flat_p, rank * shard_len, shard_len, 1)
                        out32, mn, vn, _ = adam_shard(
                            pv, g32, m_i, v_i, lr * lrs, tf, wd, l2)
                        if found is not None:
                            # bad step: shard passes through bit-
                            # identical; the gather below then rebuilds
                            # the OLD params exactly (astype(master) is
                            # the same deterministic cast that produced
                            # them)
                            out32 = jnp.where(found, pv, out32)
                            mn = jnp.where(found, m_i, mn)
                            vn = jnp.where(found, v_i, vn)
                        if nm:
                            pv32 = pv.astype(jnp.float32)
                            p_sq = p_sq + jnp.sum(jnp.square(pv32))
                            u_sq = u_sq + jnp.sum(jnp.square(
                                out32.astype(jnp.float32) - pv32))
                        M[bi] = lax.dynamic_update_index_in_dim(
                            M[bi], mn.astype(M[bi].dtype), i, 0)
                        V[bi] = lax.dynamic_update_index_in_dim(
                            V[bi], vn.astype(V[bi].dtype), i, 0)
                        if MW[bi] is not None:
                            MW[bi] = lax.dynamic_update_index_in_dim(
                                MW[bi], out32, i, 0)
                        full = gather_flat(
                            out32.astype(bkt.dtype), axes,
                            axis=1)                         # [K, F]
                        for e_key, leaf in unpack_flat(full, bkt).items():
                            tj = t_idx[e_key]
                            P_tr = P_tr[:tj] + (
                                lax.dynamic_update_index_in_dim(
                                    P_tr[tj],
                                    leaf.astype(P_tr[tj].dtype), i, 0),
                            ) + P_tr[tj + 1:]
                    return (P_tr, M, V, MW), (
                        jnp.stack([p_sq, u_sq]) if nm else {})

                (P_tr, sM, sV, sMW), pu_cols = lax.scan(
                    upd_body, (P_tr0, list(sM), list(sV), list(sMW)),
                    jnp.arange(C), unroll=self._scan_unroll)

                new_sp = list(s["p"])
                for tj, (j, _) in enumerate(self._s_train):
                    new_sp[j] = P_tr[tj].reshape(
                        (-1,) + tuple(P_tr[tj].shape[2:]))

                # ---- outer update (no scan)
                new_op = list(o["p"])
                new_om, new_ov, new_omw = [], [], []
                o_p_sq = o_u_sq = jnp.float32(0.0)
                for bkt in o_assign.buckets:
                    bi = bkt.index
                    shard_len = bkt.numel // N
                    wd, l2, lrs, nc = (shard_of(h, rank, shard_len)
                                       for h in o_hp[bi])
                    g32 = g_shard_f32(o_gs[bi], nc, scale, inv_s)
                    m_i, v_i = o["m"][bi], o["v"][bi]
                    if o["mw"][bi] is not None:
                        pv = o["mw"][bi]
                    else:
                        flat_p = pack_flat(lambda j: o["p"][j], bkt)
                        pv = lax.dynamic_slice_in_dim(
                            flat_p, rank * shard_len, shard_len, 0)
                    out32, mn, vn, _ = adam_shard(
                        pv, g32, m_i, v_i, lr * lrs, tf, wd, l2)
                    if found is not None:
                        out32 = jnp.where(found, pv, out32)
                        mn = jnp.where(found, m_i, mn)
                        vn = jnp.where(found, v_i, vn)
                    if nm:
                        pv32 = pv.astype(jnp.float32)
                        o_p_sq = o_p_sq + jnp.sum(jnp.square(pv32))
                        o_u_sq = o_u_sq + jnp.sum(jnp.square(
                            out32.astype(jnp.float32) - pv32))
                    new_om.append(mn.astype(m_i.dtype))
                    new_ov.append(vn.astype(v_i.dtype))
                    new_omw.append(out32 if o["mw"][bi] is not None
                                   else None)
                    full = gather_flat(out32.astype(bkt.dtype), axes,
                                       axis=0)
                    for e_key, leaf in unpack_flat(full, bkt).items():
                        new_op[e_key] = leaf.astype(
                            o["p"][e_key].dtype)

                new_state = {
                    "s": {"p": new_sp,
                          "m": [m.reshape((n_layers, -1)) for m in sM],
                          "v": [v.reshape((n_layers, -1)) for v in sV],
                          "mw": [mw.reshape((n_layers, -1))
                                 if mw is not None else None
                                 for mw in sMW]},
                    "o": {"p": new_op, "m": new_om, "v": new_ov,
                          "mw": new_omw},
                    "buf": state["buf"],
                    "step": (t if found is None
                             else jnp.where(found, state["step"], t)),
                }
                if guard is not None:
                    new_state["guard"] = guard.update(gst, found)
                # loss identical across mp/pp ranks -> the axis-tuple
                # psum over-counts by exactly the replication factor the
                # inv_n (= 1/(dp*mp)) divides back out: a dp-mean
                loss_out = lax.psum(loss, axes) * inv_n
                if not nm:
                    return loss_out, new_state
                return loss_out, new_state, _assemble_stats(
                    nrows, pu_cols, o_p_sq, o_u_sq, inv_s)
            finally:
                seg_ctx.__exit__(None, None, None)
                self._bind(self._buffers, saved_buf)

        specs = self._state_specs()
        batch_spec = P(self._batch_axes if len(self._batch_axes) > 1
                       else self._axis, None)
        # numerics partials stack over the FLATTENED reduction axes
        # (ISSUE 15: stats never psum — the host fold sums rank
        # partials, so the monitor adds zero collectives)
        stats_ax = self._axes if len(self._axes) > 1 else self._axis
        out_specs = ((P(), specs) if not nm
                     else (P(), specs, P(stats_ax)))
        # the trailing batch_spec covers the optional segment-id arg —
        # a None there is an empty pytree, so the spec binds no leaves
        wrapped = jax.shard_map(
            step_fn, mesh=mesh,
            in_specs=(specs, P(), batch_spec, batch_spec, batch_spec),
            out_specs=out_specs, check_vma=False)
        from .compile_cache import cached_jit

        self._jitted = cached_jit(wrapped,
                                  donate_argnums=_donate_argnums(),
                                  label=type(self).__name__)

    def grads_probe(self, ids, labels):
        """Test/debug surface: run ONLY the grads pass and return
        (loss, stacked_grads, outer_grads) as FULL (gathered, 1/N-
        normalized = dp-mean) fp32 flat buckets — stacked_grads[b] is
        [C, K, bucket.numel], outer_grads[b] is [bucket.numel]. Lets
        tests compare gradient content across mesh layouts without
        reverse-engineering shard layouts. Not used by training."""
        from ..framework.tensor import Tensor

        self.ensure_built()
        self._pre_step()
        state = self._extract_state()
        ids_d = ids._data if isinstance(ids, Tensor) else ids
        lab_d = labels._data if isinstance(labels, Tensor) else labels
        specs = self._state_specs()
        axes = self._axes
        inv = 1.0 / self._degree
        ns = len(self._s_assign.buckets)
        no = len(self._o_assign.buckets)

        def fn(state, ids, labels):
            saved_buf = self._bind(self._buffers, state["buf"])
            try:
                t32 = state["step"].astype(jnp.int32) + 1
                ct = jnp.ones((), jnp.float32)
                loss, G, o_gs, _, _, _ = self._grads(state, ids,
                                                     labels, t32, ct)
                Gf = tuple(
                    gather_flat(g.astype(jnp.float32) * inv, axes,
                                axis=g.ndim - 1) for g in G)
                of = tuple(
                    gather_flat(g.astype(jnp.float32) * inv, axes,
                                axis=0) for g in o_gs)
                return lax.psum(loss, axes) * inv, Gf, of
            finally:
                self._bind(self._buffers, saved_buf)

        batch_spec = P(self._batch_axes if len(self._batch_axes) > 1
                       else self._axis, None)
        wrapped = jax.shard_map(
            fn, mesh=self._mesh,
            in_specs=(specs, batch_spec, batch_spec),
            out_specs=(P(), (P(),) * ns, (P(),) * no),
            check_vma=False)
        with self._step_guard():
            return jax.jit(wrapped)(state, ids_d, lab_d)

    def _pre_step(self):
        if self._param_storage == "sharded":
            self._repack_dirty_param_buckets()

    def _step_guard(self):
        if self._param_storage == "sharded":
            return _raw_param_access()
        return super()._step_guard()

    def __call__(self, ids, labels, segment_ids=None):
        shape = getattr(ids, "shape", None)
        if shape and shape[0] % self._batch_degree:
            raise ValueError(
                f"global batch {shape[0]} is not divisible by the "
                f"batch-axis degree {self._batch_degree} "
                f"(axes {self._batch_axes})")
        # host-side fault points (ISSUE 19): a scripted straggler /
        # crash fires BEFORE the compiled step dispatches, so an
        # injected failure never leaves donated buffers half-consumed
        from ..observability import faults

        faults.maybe_delay("train.step.straggler")
        faults.maybe_raise("train.step.crash")
        return super().__call__(ids, labels, segment_ids=segment_ids)


# ---------------------------------------------------------------------------
# selection wiring (group_sharded / fleet distributed_model entry points)
# ---------------------------------------------------------------------------

def select_train_step(model, optimizer, criterion=None, mesh=None,
                      axis=None, auto=False, global_batch=None,
                      hbm_gb=16.0, **kw):
    """The train-step chooser (GroupShardedStage2 / fleet
    ShardingParallel / TensorParallel / PipelineParallel entry point).

    Explicit mesh: scan_layers GPT dispatches by the mesh's active axes
    — a >1 ``pp`` axis -> `PipelineScanTrainStep`, a >1 ``mp`` axis ->
    `ShardedFusedScanTrainStep` in dp×mp mode, a >1 dp/sharding axis ->
    the dp-only sharded scan, degree 1 -> `FusedScanTrainStep`;
    non-scan models get the generic `TrainStep`.

    ``auto=True`` promotes the validated cost-model planner to the
    decision-maker (ISSUE 8): given the model + ``global_batch`` and
    the available device count, `auto_tuner.pick_layout` prunes the
    (dp, mp, pp, micro) grid with the reference feasibility rules,
    ranks survivors with `estimate_step_ms` under cached
    backend-calibrated constants, BUILDS the winning mesh (installed
    via `distributed.env.set_mesh`) and returns the matching step with
    the sweep-calibrated scan_unroll/layer_chunk. The
    ``PADDLE_HYBRID_LAYOUT`` env override is honored. The decision
    record lands on ``step.layout_decision``.
    """
    from ..distributed import env as denv
    from ..models.gpt import GPTStackedBlocks

    layers = _unwrap_layers(model)
    blocks = getattr(getattr(layers, "gpt", None), "blocks", None)
    scan = isinstance(blocks, GPTStackedBlocks)

    if auto:
        if not scan:
            raise ValueError(
                "select_train_step(auto=True) plans layouts for "
                "scan_layers GPT models; build with "
                "GPTConfig(scan_layers=True)")
        if global_batch is None:
            raise ValueError(
                "auto layout planning needs global_batch (the pruning "
                "rules and the cost model are batch-dependent)")
        import jax as _jax

        from ..distributed.auto_tuner.select import (
            calibrate_backend_cached, pick_layout, spec_of_model,
        )

        if mesh is not None:
            devices = list(mesh.devices.flat)
        else:
            devices = list(_jax.devices())
            if len(devices) == 1:
                cpus = _jax.devices("cpu")
                if len(cpus) > 1:
                    devices = cpus
        spec = spec_of_model(layers.config, global_batch=global_batch)
        backend = calibrate_backend_cached(devices)
        decision = pick_layout(spec, len(devices), hbm_gb=hbm_gb,
                               backend=backend)
        cand = decision["candidate"]
        mesh = denv.build_mesh(decision["mesh_degrees"], devices=devices)
        denv.set_mesh(mesh)
        step_kw = dict(kw)
        step_kw.setdefault("scan_unroll", decision["scan_unroll"])
        step_kw.setdefault("layer_chunk", decision["layer_chunk"])
        step_kw.setdefault("comm_bucket_mb", decision["comm_bucket_mb"])
        if cand.pp > 1:
            from .pipeline_step import PipelineScanTrainStep

            step = PipelineScanTrainStep(
                layers, optimizer, criterion=criterion, mesh=mesh,
                axis="dp", pp_axis="pp",
                num_micro=decision["num_micro"], **step_kw)
        elif cand.degree > 1:
            step = ShardedFusedScanTrainStep(
                layers, optimizer, criterion=criterion, mesh=mesh,
                axis="dp", mp_axis="mp" if cand.mp > 1 else None,
                ep_axis="ep" if getattr(cand, "ep", 1) > 1 else None,
                **step_kw)
        else:
            step = FusedScanTrainStep(
                layers, optimizer, criterion=criterion,
                **{k: v for k, v in step_kw.items()
                   if k in ("fused_head", "compute_dtype",
                            "layer_chunk", "scan_unroll",
                            "numerics")})
        step.layout_decision = decision
        return step

    if mesh is None and denv.is_initialized():
        mesh = denv.get_mesh()
    degree = mp_degree = pp_degree = ep_degree = 1
    mp_axis = pp_axis = ep_axis = None
    if mesh is not None:
        if axis is None:
            axis = next((a for a in ("sharding", "dp")
                         if a in mesh.axis_names and mesh.shape[a] > 1),
                        None)
        if axis is not None:
            degree = int(mesh.shape[axis])
        if "mp" in mesh.axis_names and int(mesh.shape["mp"]) > 1 \
                and axis != "mp":
            mp_axis, mp_degree = "mp", int(mesh.shape["mp"])
        if "pp" in mesh.axis_names and int(mesh.shape["pp"]) > 1 \
                and axis != "pp":
            pp_axis, pp_degree = "pp", int(mesh.shape["pp"])
        if "ep" in mesh.axis_names and int(mesh.shape["ep"]) > 1 \
                and axis != "ep" \
                and getattr(getattr(layers, "config", None),
                            "num_experts", 0):
            ep_axis, ep_degree = "ep", int(mesh.shape["ep"])
    if scan and pp_degree > 1:
        from .pipeline_step import PipelineScanTrainStep

        if axis is None:
            # a degree-1 dp/sharding axis still names the batch axis; a
            # mesh with NEITHER cannot place the batch — say so rather
            # than let the constructor trip over a duplicate-axis error
            axis = next((a for a in ("sharding", "dp")
                         if a in mesh.axis_names), None)
            if axis is None:
                raise ValueError(
                    f"pp mesh {mesh.axis_names} has no dp/sharding "
                    "axis to place the batch on; build it with one "
                    "(degree 1 is fine): build_mesh({'dp': 1, "
                    "'pp': N})")
        return PipelineScanTrainStep(layers, optimizer,
                                     criterion=criterion, mesh=mesh,
                                     axis=axis, pp_axis=pp_axis,
                                     **kw)
    if scan and (degree > 1 or mp_degree > 1 or ep_degree > 1):
        if ep_degree > 1 and axis is None:
            # a dp1×epN mesh still batches over "dp" — the constructor
            # needs the (degree-1) data axis named
            axis = next((a for a in ("sharding", "dp")
                         if a in mesh.axis_names), None)
            if axis is None:
                raise ValueError(
                    f"ep mesh {mesh.axis_names} has no dp/sharding "
                    "axis to place the batch on; build it with one "
                    "(degree 1 is fine): build_mesh({'dp': 1, "
                    "'ep': N})")
        return ShardedFusedScanTrainStep(layers, optimizer,
                                         criterion=criterion, mesh=mesh,
                                         axis=axis, mp_axis=mp_axis,
                                         ep_axis=ep_axis, **kw)
    if scan:
        return FusedScanTrainStep(layers, optimizer, criterion=criterion,
                                  **{k: v for k, v in kw.items()
                                     if k in ("fused_head",
                                              "compute_dtype",
                                              "layer_chunk",
                                              "scan_unroll",
                                              "numerics")})
    from .train_step import TrainStep

    if criterion is not None:
        return TrainStep(model, lambda m, a, b: criterion(m(a), b),
                         optimizer)
    return TrainStep(model, lambda m, a, b: m.loss(a, b), optimizer)


# ---------------------------------------------------------------------------
# HLO probe program (tools/hlo_overlap.py --probe, bench --multichip)
# ---------------------------------------------------------------------------

def build_probe_lowered(n_devices=8, scan_unroll=2, layer_chunk=1,
                        mp=1, pp=1, num_micro=2, ep=1,
                        param_storage=None):
    """Lower (not run) the sharded step for a tiny scan GPT on an
    n-device host mesh — the program the overlap checker inspects.
    ``mp``/``pp``/``ep`` > 1 build the hybrid variants (dp×mp Megatron
    sharding / the dp×pp ring pipeline / the dp×ep expert-parallel MoE
    step) instead of the dp-only step. ``param_storage`` selects the
    storage format (None = the step default, i.e. sharded)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as popt
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    devs = jax.devices("cpu")[:n_devices] if jax.default_backend() == \
        "cpu" else jax.devices()[:n_devices]
    if len(devs) < n_devices:
        raise RuntimeError(
            f"{len(devs)} devices < {n_devices} "
            "(set --xla_force_host_platform_device_count)")
    from jax.sharding import Mesh

    if sum(int(d) > 1 for d in (mp, pp, ep)) > 1:
        raise NotImplementedError("combined mp×pp×ep probe")
    if mp > 1:
        dp = n_devices // mp
        mesh = Mesh(np.asarray(devs).reshape(dp, mp), ("dp", "mp"))
    elif pp > 1:
        dp = n_devices // pp
        mesh = denv.build_mesh({"dp": dp, "pp": pp}, devices=devs)
    elif ep > 1:
        dp = n_devices // ep
        mesh = Mesh(np.asarray(devs).reshape(dp, ep), ("dp", "ep"))
    else:
        mesh = Mesh(np.asarray(devs), ("sharding",))
    denv.set_mesh(mesh)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_attention_heads=2, max_position_embeddings=32,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    scan_layers=True,
                    num_experts=(2 * ep if ep > 1 else 0))
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-3, parameters=model.parameters(),
                     grad_clip=nn.ClipGradByGlobalNorm(1.0))
    if pp > 1:
        from .pipeline_step import PipelineScanTrainStep

        step = PipelineScanTrainStep(model, opt, mesh=mesh, axis="dp",
                                     pp_axis="pp", num_micro=num_micro,
                                     scan_unroll=scan_unroll,
                                     layer_chunk=layer_chunk,
                                     param_storage=param_storage)
    else:
        step = ShardedFusedScanTrainStep(
            model, opt, mesh=mesh,
            axis="dp" if (mp > 1 or ep > 1) else "sharding",
            mp_axis="mp" if mp > 1 else None,
            ep_axis="ep" if ep > 1 else None,
            scan_unroll=scan_unroll, layer_chunk=layer_chunk,
            param_storage=param_storage)
    step.ensure_built()
    state = step._extract_state()
    lr = jnp.float32(1e-3)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (n_devices, 16)),
                      jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                      (n_devices, 16)), jnp.int32)
    with step._step_guard():
        return step._jitted.lower(state, lr, ids, labels, None)
