"""Loss functionals (python/paddle/nn/functional/loss.py parity;
reference kernels cross_entropy (softmax_with_cross_entropy), bce, mse...).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops._dispatch import unary, binary, nary, ensure_tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """softmax_with_cross_entropy parity. Computed in fp32 via log_softmax
    (numerically-stable fused form — XLA fuses the exp/sum/sub chain)."""
    if soft_label and ignore_index != -100:
        # reference cross_entropy raises here (python/paddle/nn/functional/
        # loss.py): with soft labels there is no integer class to compare
        # against ignore_index, silently ignoring it would hide a bug
        raise ValueError(
            "When soft_label == True, the value of ignore_index should "
            f"be -100 (got {ignore_index}): ignore_index is only usable "
            "with hard (integer) labels")
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def f(logits, lbl, *maybe_w):
        is_soft = soft_label or (
            lbl.ndim == logits.ndim and lbl.shape[axis] == logits.shape[axis]
            and jnp.issubdtype(lbl.dtype, jnp.floating))
        # hard-label fast path: loss = logsumexp - picked_logit. Unlike the
        # log_softmax form this never materializes (or stores as a vjp
        # residual) an fp32 [tokens, vocab] tensor — the fp32 upcast fuses
        # into the reduction and backward recomputes softmax from the
        # native-dtype logits. Same numbers, ~2x less LM-head HBM traffic
        # in bf16 training.
        if (use_softmax and not is_soft and label_smoothing == 0.0
                and not maybe_w):
            idx = lbl.astype(jnp.int32)
            if idx.ndim == logits.ndim:
                idx = jnp.squeeze(idx, axis=axis)
            safe_idx = jnp.where(idx == ignore_index, 0, idx)
            lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=axis)
            picked = jnp.take_along_axis(
                jnp.moveaxis(logits, axis, -1), safe_idx[..., None], axis=-1,
            )[..., 0].astype(jnp.float32)
            valid = idx != ignore_index
            loss = jnp.where(valid, lse - picked, 0.0)
            if reduction == "mean":
                denom = jnp.sum(valid.astype(jnp.float32))
                return jnp.sum(loss) / jnp.maximum(denom, 1.0)
            return _reduce(loss, reduction)
        x32 = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(x32, axis=axis) if use_softmax else jnp.log(jnp.maximum(x32, 1e-30))
        if is_soft:
            soft = lbl.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                soft = soft * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            idx = lbl.astype(jnp.int32)
            if idx.ndim == logits.ndim:
                idx = jnp.squeeze(idx, axis=axis)
            k = logits.shape[axis]
            safe_idx = jnp.where(idx == ignore_index, 0, idx)
            picked = jnp.take_along_axis(
                jnp.moveaxis(logp, axis, -1),
                safe_idx[..., None],
                axis=-1,
            )[..., 0]
            if label_smoothing > 0:
                smooth_term = jnp.mean(logp, axis=axis)
                picked = (1 - label_smoothing) * picked + label_smoothing * smooth_term
            loss = -picked
            valid = idx != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if maybe_w:
                w = maybe_w[0].astype(jnp.float32)[safe_idx]
                loss = loss * jnp.where(valid, w, 0.0)
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(valid, w, 0.0))
                    return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
            if reduction == "mean":
                denom = jnp.sum(valid.astype(jnp.float32))
                return jnp.sum(loss) / jnp.maximum(denom, 1.0)
        return _reduce(loss, reduction)

    inputs = [input, label]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    return nary(f, inputs, "cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax

        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    return _nll(input, label, weight, ignore_index, reduction)


def _nll(input, label, weight, ignore_index, reduction):
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    def f(logp, lbl, *maybe_w):
        idx = lbl.astype(jnp.int32)
        safe_idx = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, safe_idx[..., None], axis=-1)[..., 0]
        loss = -picked
        valid = idx != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if maybe_w:
            w = maybe_w[0][safe_idx]
            loss = loss * jnp.where(valid, w, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    inputs = [input, label]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    return nary(f, inputs, "nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return binary(lambda a, b: _reduce(jnp.square(a - b), reduction),
                  ensure_tensor(input), ensure_tensor(label), "mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return binary(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                  ensure_tensor(input), ensure_tensor(label), "l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return binary(f, ensure_tensor(input), ensure_tensor(label), "smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *maybe_w):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-7)
        loss = -(y * jnp.log(p32) + (1 - y) * jnp.log(1 - p32))
        if maybe_w:
            loss = loss * maybe_w[0]
        return _reduce(loss, reduction)

    inputs = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    return nary(f, inputs, "bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, y, *rest):
        z32 = z.astype(jnp.float32)
        y32 = y.astype(jnp.float32)
        i = 0
        pw = None
        w = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]; i += 1
        # log(1+exp(-|z|)) stable form
        max_val = jnp.maximum(-z32, 0)
        if pw is not None:
            log_w = (pw - 1) * y32 + 1
            loss = (1 - y32) * z32 + log_w * (jnp.log(jnp.exp(-max_val) + jnp.exp(-z32 - max_val)) + max_val)
        else:
            loss = (1 - y32) * z32 + max_val + jnp.log(jnp.exp(-max_val) + jnp.exp(-z32 - max_val))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    inputs = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    if pos_weight is not None:
        inputs.append(ensure_tensor(pos_weight))
    return nary(f, inputs, "bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(lp, y):
        if log_target:
            loss = jnp.exp(y) * (y - lp)
        else:
            loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return binary(f, ensure_tensor(input), ensure_tensor(label), "kl_div")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
        return _reduce(loss, reduction)

    return binary(f, ensure_tensor(input), ensure_tensor(label), "hinge_embedding")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return nary(
        lambda x1, x2, y: _reduce(jnp.maximum(0.0, -y * (x1 - x2) + margin), reduction),
        [ensure_tensor(input), ensure_tensor(other), ensure_tensor(label)],
        "margin_ranking",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return nary(f, [ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label)],
                "cosine_embedding")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2, eps=1e-6,
                        swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + eps, p), axis=-1), 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + eps, p), axis=-1), 1 / p)
        if swap:
            dn2 = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + eps, p), axis=-1), 1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return nary(f, [ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative)],
                "triplet_margin")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *maybe_norm):
        p = jax.nn.sigmoid(z.astype(jnp.float32))
        ce = binary_ce_logits_raw(z.astype(jnp.float32), y.astype(jnp.float32))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if maybe_norm:
            loss = loss / maybe_norm[0]
        return _reduce(loss, reduction)

    inputs = [ensure_tensor(logit), ensure_tensor(label)]
    if normalizer is not None:
        inputs.append(ensure_tensor(normalizer))
    return nary(f, inputs, "sigmoid_focal")


def binary_ce_logits_raw(z, y):
    max_val = jnp.maximum(-z, 0)
    return (1 - y) * z + max_val + jnp.log(jnp.exp(-max_val) + jnp.exp(-z - max_val))


def square_error_cost(input, label):
    return binary(lambda a, b: jnp.square(a - b), ensure_tensor(input), ensure_tensor(label),
                  "square_error_cost")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference nn/functional/loss.py ctc_loss over the warpctc
    kernel). TPU-first: the standard log-semiring forward algorithm as a
    `lax.scan` over time — static shapes, jit/grad-friendly; per-sample
    lengths are handled by freezing alpha past input_lengths and gathering
    the final states at 2*label_lengths.

    log_probs: [max_T, batch, num_classes] logits (log_softmax is applied
    internally, matching warpctc's built-in softmax); labels: [batch,
    max_label_len] int; reduction "mean" divides each loss by its
    label_length then averages (reference semantics).
    """
    from jax import lax

    if norm_by_times:
        raise NotImplementedError("ctc_loss norm_by_times")
    if reduction not in ("mean", "sum", "none"):
        raise ValueError(
            f"reduction should be 'mean', 'sum' or 'none', got {reduction!r}")
    log_probs = ensure_tensor(log_probs)
    labels = ensure_tensor(labels)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)

    def f(lp, lbl, ilen, llen):
        T, B, C = lp.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        lbl = lbl.astype(jnp.int32)
        ilen = ilen.astype(jnp.int32)
        llen = llen.astype(jnp.int32)
        neg_inf = jnp.float32(-1e30)

        # extended sequence z = [blank, l1, blank, l2, ..., blank]: [B, S]
        z = jnp.full((B, S), blank, jnp.int32)
        z = z.at[:, 1::2].set(lbl)
        s_idx = jnp.arange(S)
        in_seq = s_idx[None, :] < (2 * llen[:, None] + 1)
        # skip transition allowed into odd (label) states whose label
        # differs from the one two back
        z_m2 = jnp.concatenate([jnp.full((B, 2), blank, jnp.int32),
                                z[:, :-2]], axis=1)
        allow_skip = (s_idx[None, :] >= 2) & (z != blank) & (z != z_m2)

        def emit(lp_t):
            # lp_t: [B, C] -> [B, S] log-prob of each extended state's symbol
            return jnp.take_along_axis(lp_t, z, axis=1)

        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        if L > 0:
            first_lbl = jnp.take_along_axis(lp[0], z[:, 1:2], axis=1)[:, 0]
            alpha0 = alpha0.at[:, 1].set(
                jnp.where(llen > 0, first_lbl, neg_inf))

        def step(alpha, inp):
            lp_t, t = inp
            a0 = alpha
            a1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a2 = jnp.where(allow_skip, a2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(a0, a1), a2)
            new = merged + emit(lp_t)
            new = jnp.where(in_seq, new, neg_inf)
            # freeze finished sequences (t >= their input length)
            active = (t < ilen)[:, None]
            new = jnp.where(active, new, alpha)
            return new, None

        alpha, _ = lax.scan(step, alpha0,
                            (lp[1:], jnp.arange(1, T)))
        # final: logaddexp(alpha[2*llen], alpha[2*llen - 1])
        e0 = 2 * llen
        e1 = jnp.maximum(e0 - 1, 0)
        a_end0 = jnp.take_along_axis(alpha, e0[:, None], axis=1)[:, 0]
        a_end1 = jnp.take_along_axis(alpha, e1[:, None], axis=1)[:, 0]
        a_end1 = jnp.where(llen > 0, a_end1, neg_inf)
        loss = -jnp.logaddexp(a_end0, a_end1)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(llen.astype(jnp.float32),
                                               1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return nary(f, [log_probs, labels, input_lengths, label_lengths],
                "ctc_loss")


# ---------------------------------------------------------------------------
# Fused LM head: linear projection + softmax cross entropy without ever
# materializing the [tokens, vocab] logits matrix.
#
# Reference parity: the role of Paddle's fused CE stack —
# c_softmax_with_cross_entropy (paddle/phi/kernels/gpu/
# c_softmax_with_cross_entropy_kernel.cu) and fused_softmax_mask — which fuse
# the softmax/CE chain to avoid logits round-trips. TPU-first: at a 50k vocab
# the fp32 logits tensor (batch*seq x vocab) dominates the LM-head HBM traffic
# and is held across the whole backward as a vjp residual; instead we scan
# over token chunks, computing each chunk's logits on the MXU, reducing to
# logsumexp + the picked logit, and discarding the chunk. The custom VJP
# recomputes per-chunk logits in backward (flash-attention-style
# recompute-over-store) and accumulates the weight gradient in fp32.
# ---------------------------------------------------------------------------

from functools import partial as _partial

import numpy as _np
from jax import lax as _lax


def _chunk_logits(hc, w, transpose_y):
    # hc [C, H]; w [V, H] when transpose_y (embedding layout) else [H, V].
    if transpose_y:
        return jnp.dot(hc, w.T, preferred_element_type=jnp.float32)
    return jnp.dot(hc, w, preferred_element_type=jnp.float32)


def _pad_chunks(x, n_chunks, pad_value):
    n = x.shape[0]
    c = -(-n // n_chunks)
    pad = c * n_chunks - n
    if pad:
        cfg = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        x = jnp.pad(x, cfg, constant_values=pad_value)
    return x.reshape((n_chunks, c) + x.shape[1:])


@_partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_linear_ce(h, w, labels, transpose_y, ignore_index, n_chunks):
    losses, _ = _fused_linear_ce_fwd(h, w, labels, transpose_y, ignore_index,
                                     n_chunks)
    return losses


def _fused_linear_ce_fwd(h, w, labels, transpose_y, ignore_index, n_chunks):
    n = h.shape[0]
    hr = _pad_chunks(h, n_chunks, 0)
    lr = _pad_chunks(labels, n_chunks, ignore_index)

    def body(_, hl):
        hc, lc = hl
        logits = _chunk_logits(hc, w, transpose_y)
        lse = jax.nn.logsumexp(logits, axis=-1)
        valid = lc != ignore_index
        safe = jnp.where(valid, lc, 0).astype(jnp.int32)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        return None, jnp.where(valid, lse - picked, 0.0)

    _, losses = _lax.scan(body, None, (hr, lr))
    return losses.reshape(-1)[:n], (h, w, labels)


def _fused_linear_ce_bwd(transpose_y, ignore_index, n_chunks, res, g):
    h, w, labels = res
    n, hidden = h.shape
    hr = _pad_chunks(h, n_chunks, 0)
    lr = _pad_chunks(labels, n_chunks, ignore_index)
    gr = _pad_chunks(g, n_chunks, 0)

    def body(dw, hlg):
        hc, lc, gc = hlg
        c = hc.shape[0]
        logits = _chunk_logits(hc, w, transpose_y)
        p = jax.nn.softmax(logits, axis=-1)
        valid = lc != ignore_index
        safe = jnp.where(valid, lc, 0).astype(jnp.int32)
        d = p.at[jnp.arange(c), safe].add(-1.0)
        d = d * jnp.where(valid, gc, 0.0).astype(jnp.float32)[:, None]
        dlow = d.astype(h.dtype)  # grads ride the MXU in the param dtype
        if transpose_y:           # w [V, H]
            dh = jnp.dot(dlow, w, preferred_element_type=jnp.float32)
            dwc = jnp.dot(dlow.T, hc, preferred_element_type=jnp.float32)
        else:                     # w [H, V]
            dh = jnp.dot(dlow, w.T, preferred_element_type=jnp.float32)
            dwc = jnp.dot(hc.T, dlow, preferred_element_type=jnp.float32)
        return dw + dwc, dh.astype(h.dtype)

    dw, dh = _lax.scan(body, jnp.zeros(w.shape, jnp.float32), (hr, lr, gr))
    dh = dh.reshape(-1, hidden)[:n]
    ct_labels = _np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dh, dw.astype(w.dtype), ct_labels


_fused_linear_ce.defvjp(_fused_linear_ce_fwd, _fused_linear_ce_bwd)


def fused_linear_cross_entropy(hidden, weight, labels, transpose_y=True,
                               ignore_index=-100, reduction="mean",
                               n_chunks=None, vocab_tiled=None,
                               name=None):
    """Cross entropy of `softmax(hidden @ weight)` with the full logits
    matrix never hitting HBM. Two fused implementations:

    * **vocab-tiled streaming** (default, `FLAGS_fused_ce`): logits
      stream through vocab tiles — online logsumexp + gathered label
      logit in forward, d_logits folded into dhidden/dweight per tile in
      backward (ops/pallas/fused_cross_entropy.py — Pallas kernel on
      TPU, lax.scan tiles elsewhere). No [tokens, vocab] array exists in
      either pass.
    * **token-chunked logsumexp** (flag off, or `vocab_tiled=False`):
      the round-4 scheme — full-vocab logits per token chunk, discarded
      after reduction (see module comment above; FLAGS_fused_ce_chunks).

    hidden: [..., H] activations; weight: [V, H] (transpose_y=True — the
    tied-embedding layout) or [H, V]; labels: int [...] matching hidden's
    leading dims. reduction "mean" averages over non-ignored tokens.
    """
    from ...utils import flags as _flags

    hidden = ensure_tensor(hidden)
    weight = ensure_tensor(weight)
    labels = ensure_tensor(labels)
    if n_chunks is None:
        n_chunks = int(_flags.get_flags(["FLAGS_fused_ce_chunks"])
                       ["FLAGS_fused_ce_chunks"])
    n_chunks = max(1, int(n_chunks))
    if vocab_tiled is None:
        vocab_tiled = bool(_flags.get_flag("FLAGS_fused_ce"))
    force_interp = bool(_flags.get_flag("FLAGS_pallas_force_interpret"))

    def f(h, w, lbl):
        hsz = h.shape[-1]
        flat_h = h.reshape(-1, hsz)
        flat_l = lbl.reshape(-1).astype(jnp.int32)
        if vocab_tiled:
            from ...ops.pallas import fused_cross_entropy as _fce

            # kernel layout is [vocab, hidden]; an [H, V] head transposes
            # outside (AD routes dweight back through the transpose)
            w_vh = w if transpose_y else w.T
            losses = _fce.fused_cross_entropy(
                flat_h, w_vh, flat_l, ignore_index=ignore_index,
                interpret=True if force_interp else None)
        else:
            losses = _fused_linear_ce(flat_h, w, flat_l, transpose_y,
                                      ignore_index, n_chunks)
        if reduction == "none":
            return losses.reshape(lbl.shape)
        if reduction == "sum":
            return jnp.sum(losses)
        valid = (lbl.reshape(-1) != ignore_index).astype(jnp.float32)
        return jnp.sum(losses) / jnp.maximum(jnp.sum(valid), 1.0)

    return nary(f, [hidden, weight, labels], "fused_linear_cross_entropy")


def log_loss(input, label, epsilon=1e-4, name=None):
    """Negative log likelihood of probabilities (reference log_loss_kernel.h):
    -label*log(p+eps) - (1-label)*log(1-p+eps)."""
    from ...ops._dispatch import nary

    def f(p, y):
        return (-y * jnp.log(p + epsilon)
                - (1.0 - y) * jnp.log(1.0 - p + epsilon))

    return nary(f, [input, label], "log_loss")


def identity_loss(x, reduction="none"):
    """Marks a value as the loss for IPU-style pipelines (reference
    identity_loss_kernel.h); numerically the reduction of x."""
    from ...ops._dispatch import unary

    red = {0: "sum", 1: "mean", 2: "none", "sum": "sum", "mean": "mean",
           "none": "none"}[reduction]

    def f(v):
        if red == "sum":
            return jnp.sum(v)
        if red == "mean":
            return jnp.mean(v)
        return v

    return unary(f, x, "identity_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference hsigmoid_loss_kernel.h),
    default complete-binary-tree coding: num_classes-1 internal nodes;
    class c's path/code derive from the tree layout the reference uses
    (node ids from (c + num_classes) walking to the root)."""
    import numpy as np

    from ...ops._dispatch import nary

    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "custom-tree hsigmoid (path_table/path_code) is descoped — "
            "default complete-binary-tree mode only")
    # precompute per-class paths host-side (static num_classes)
    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    paths = np.zeros((num_classes, depth), np.int32)
    codes = np.zeros((num_classes, depth), np.float32)
    valid = np.zeros((num_classes, depth), np.float32)
    for c in range(num_classes):
        node = c + num_classes          # leaf id in the implicit heap
        d = 0
        while node > 1 and d < depth:
            codes[c, d] = float(node % 2)
            node //= 2
            paths[c, d] = node - 1      # internal node row in weight
            valid[c, d] = 1.0
            d += 1
    pathsj = jnp.asarray(paths)
    codesj = jnp.asarray(codes)
    validj = jnp.asarray(valid)

    def f(x, y, w, *rest):
        b = rest[0] if bias is not None else None
        y = y.reshape(-1).astype(jnp.int32)   # accept [N, 1] labels
        yp = pathsj[y]                  # [N, depth]
        yc = codesj[y]
        yv = validj[y]
        wsel = w[yp]                    # [N, depth, D]
        logits = jnp.einsum("nd,nkd->nk", x.astype(jnp.float32),
                            wsel.astype(jnp.float32))
        if b is not None:
            logits = logits + b[yp].astype(jnp.float32)
        # sigmoid CE per node with target = code
        per = jnp.maximum(logits, 0) - logits * yc \
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        return jnp.sum(per * yv, axis=1, keepdims=True)

    args = [input, label, weight] + ([bias] if bias is not None else [])
    return nary(f, args, name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace/CosFace-family margin softmax CE (reference
    margin_cross_entropy_kernel.h): cos(m1*θ + m2) - m3 on the target
    logit, then scaled softmax CE. Single-group (non-model-parallel)
    path; logits are cosines in [-1, 1]."""
    from ...ops._dispatch import nary

    def f(lg, y):
        lf = lg.astype(jnp.float32)
        n = lf.shape[0]
        y = y.reshape(-1).astype(jnp.int32)   # accept [N, 1] labels
        tgt = jnp.take_along_axis(lf, y[:, None], 1)[:, 0]
        theta = jnp.arccos(jnp.clip(tgt, -1.0 + 1e-7, 1.0 - 1e-7))
        tgt_m = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(y, lf.shape[1], dtype=lf.dtype)
        adj = lf + onehot * (tgt_m - tgt)[:, None]
        adj = adj * scale
        lse = jax.scipy.special.logsumexp(adj, axis=1)
        loss = lse - jnp.take_along_axis(adj, y[:, None], 1)[:, 0]
        sm = jnp.exp(adj - lse[:, None])
        return loss[:, None], sm

    import jax

    loss, sm = nary(f, [logits, label], name="margin_cross_entropy")
    # Tensor-level reduction (the jnp-level _reduce would break the tape
    # — and broke "mean" outright when this fn moved here in r4)
    if reduction == "mean":
        loss = loss.mean()
    elif reduction == "sum":
        loss = loss.sum()
    if return_softmax:
        return loss, sm
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference
    class_center_sample_kernel.h / PartialFC): returns remapped labels +
    the sampled class index set (positives first, padded with uniformly
    sampled negatives to num_samples)."""
    import numpy as np

    from ...framework import random as _random
    from ...framework.tensor import Tensor
    from ...ops._dispatch import ensure_tensor

    y = np.asarray(ensure_tensor(label)._data).astype(np.int64)
    pos = np.unique(y)
    rng = np.random.default_rng(int(_random.default_generator().seed_) + 1
                                if hasattr(_random.default_generator(),
                                           "seed_") else 0)
    neg_pool = np.setdiff1d(np.arange(num_classes), pos)
    n_neg = max(0, num_samples - len(pos))
    neg = (rng.choice(neg_pool, size=n_neg, replace=False)
           if n_neg <= len(neg_pool) else neg_pool)
    sampled = np.concatenate([pos, neg])
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor._wrap(jnp.asarray(remap[y])),
            Tensor._wrap(jnp.asarray(sampled)))


