"""paddle.device.xpu (reference device/xpu/__init__.py): Kunlun-XPU
introspection. The TPU build has no XPU runtime — counts are zero and
device-requiring calls raise."""
from __future__ import annotations

__all__ = ["synchronize", "device_count", "set_debug_level"]


def device_count():
    return 0


def synchronize(device=None):
    raise RuntimeError("no XPU devices in the TPU build")


def set_debug_level(level=1):
    raise RuntimeError("no XPU runtime in the TPU build")
