"""Tensor-parallel RNG state tracking.

Reference parity: RNGStatesTracker (fleet/layers/mpu/random.py:34) and
get_rng_state_tracker (:99) — separate RNG streams so that dropout inside TP
regions is either identical across mp ranks (replicated activations) or
distinct (sharded activations), and reproducible under recompute.

TPU-first: streams are independent Generators (counter-based fold_in keys,
framework/random.py); under the jitted train step the offsets are traced
state, so recompute replays the same keys without explicit save/restore.
"""
from __future__ import annotations

import contextlib

from .....framework.random import Generator

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return {name: gen.get_state() for name, gen in self.states_.items()}

    def set_states_tracker(self, states):
        for name, st in states.items():
            if name in self.states_:
                self.states_[name].set_state(st)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        from .....framework import random as _random

        gen = self.states_[name]
        prev = _random._default_generator
        _random._default_generator = gen
        try:
            yield
        finally:
            _random._default_generator = prev


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    """Reference random.py — derive distinct seeds per mp rank. In the
    single-controller world one tracker serves all ranks; sharded dropout
    masks differ per device because the key folds in traced positions."""
    import random as pyrandom

    seed = seed if seed is not None else pyrandom.randint(0, 2 ** 31 - 1)
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, seed + 1024)
