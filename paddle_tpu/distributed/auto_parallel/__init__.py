"""Auto-parallel DTensor API.

Reference parity: python/paddle/distributed/auto_parallel/ — ProcessMesh
(process_mesh.py), shard_tensor/reshard/shard_layer/dtensor_from_local
(api.py:179,675,776,589), DistAttr placements (Shard/Replicate/Partial,
paddle/phi/core/distributed/auto_parallel/placement_types.h).

TPU-first: a DistTensor IS a jax.Array with a NamedSharding — placement and
layout are native to the runtime, and "reshard" is a device_put with a new
sharding (XLA emits the collective-permute/all-gather/all-to-all under the
hood, replacing the reference's 15 hand-written reshard transition functions
in phi/core/distributed/auto_parallel/reshard/).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...framework.tensor import Tensor
from ...framework.autograd import apply_op
from .. import env


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))


class Partial(Placement):
    """Pending-reduction placement. XLA tracks partials internally during
    propagation; materializing a Partial DTensor eagerly reduces it."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial)

    def __hash__(self):
        return hash("Partial")


class ProcessMesh:
    """Reference process_mesh.py — N-D logical mesh with dim names."""

    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._shape = list(arr.shape)
        self._dim_names = list(dim_names)
        self._process_ids = list(arr.flatten())
        self._jax_mesh = None

    @property
    def shape(self):
        return self._shape

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def ndim(self):
        return len(self._shape)

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, name, index=None):
        axis = self._dim_names.index(name)
        arr = np.asarray(self._process_ids).reshape(self._shape)
        if index is None:
            order = [axis] + [i for i in range(self.ndim) if i != axis]
            return ProcessMesh(arr.transpose(order),
                               [self._dim_names[i] for i in order])
        taken = np.take(arr, index, axis=axis)
        names = [n for i, n in enumerate(self._dim_names) if i != axis]
        return ProcessMesh(taken, names or ["d0"])

    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = jax.devices()
            total = int(np.prod(self._shape))
            if len(devs) < total:
                cpus = jax.devices("cpu")
                if len(cpus) >= total:
                    devs = cpus
            chosen = np.asarray([devs[pid % len(devs)]
                                 for pid in self._process_ids])
            self._jax_mesh = Mesh(chosen.reshape(self._shape),
                                  tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._shape == other._shape
                and self._process_ids == other._process_ids)

    def __repr__(self):
        return (f"ProcessMesh(shape={self._shape}, "
                f"dim_names={self._dim_names})")


def _placements_to_spec(placements, ndim, mesh: ProcessMesh) -> P:
    axes = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            name = mesh.dim_names[mesh_dim]
            if axes[pl.dim] is None:
                axes[pl.dim] = name
            elif isinstance(axes[pl.dim], tuple):
                axes[pl.dim] = axes[pl.dim] + (name,)
            else:
                axes[pl.dim] = (axes[pl.dim], name)
    return P(*axes)


def _spec_to_placements(spec: P, mesh: Mesh) -> list:
    placements = [Replicate() for _ in mesh.axis_names]
    if spec is None:
        return placements
    for tdim, entry in enumerate(spec):
        if entry is None:
            continue
        for name in (entry if isinstance(entry, tuple) else (entry,)):
            placements[mesh.axis_names.index(name)] = Shard(tdim)
    return placements


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 stop_gradient=None):
    """Reference api.py:179 — place a tensor on the mesh per placements.
    Differentiable: recorded on the tape as a device_put."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    jmesh = mesh.jax_mesh()
    spec = _placements_to_spec(placements, t.ndim, mesh)
    sharding = NamedSharding(jmesh, spec)
    out = apply_op(lambda x: jax.device_put(x, sharding), [t],
                   name="shard_tensor")
    if stop_gradient is not None:
        out.stop_gradient = stop_gradient
    else:
        out.stop_gradient = t.stop_gradient
    # keep Parameter-ness by rebinding storage in place for leaf params
    if t is data and getattr(t, "is_leaf", True) and t.stop_gradient is False:
        pass
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def dtensor_from_local(local_tensor, mesh: ProcessMesh, placements):
    """Reference api.py:589 — single-controller: the 'local' tensor is the
    global value; apply the placements."""
    return shard_tensor(local_tensor, mesh, placements)


def dtensor_to_local(dist_tensor, mesh=None, placements=None):
    t = dist_tensor if isinstance(dist_tensor, Tensor) else Tensor(dist_tensor)
    return apply_op(lambda x: jax.device_put(
        x, NamedSharding(env.get_mesh(), P())), [t], name="dtensor_to_local")


def reshard(dist_tensor, mesh: ProcessMesh, placements):
    """Reference api.py:675 + the reshard function registry
    (reshard_function_registry.cc): any placement transition. XLA emits the
    transfer; differentiable."""
    return shard_tensor(dist_tensor, mesh, placements)


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Reference api.py:776 — apply shard_fn(name, layer, mesh) to every
    sublayer's params (default: replicate all)."""
    def default_shard(name, sublayer, mesh):
        for pname, param in list(sublayer._parameters.items()):
            if param is None:
                continue
            nd = param.ndim
            out = shard_tensor(param, mesh,
                               [Replicate() for _ in mesh.dim_names])
            param._data = out._data

    fn = shard_fn or default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    return layer


def get_placements(tensor) -> list:
    t = tensor if isinstance(tensor, Tensor) else tensor
    sh = getattr(t._data, "sharding", None)
    if isinstance(sh, NamedSharding):
        return _spec_to_placements(sh.spec, sh.mesh)
    return [Replicate()]


def moe_global_mesh_tensor(local_tensor_list, mesh=None, placements=None,
                           local_mesh_dim=-1):
    """Reference api.py moe_global_mesh_tensor — assemble per-EP-rank
    expert tensors into ONE global dist tensor sharded over the
    expert-parallel mesh dim (ISSUE 9 satellite; the EP module's storage
    convention: expert params stacked on a leading num_experts dim,
    sharded 1/ep).

    ``local_tensor_list``: each EP rank's slice of the stacked expert
    tensor (e.g. [E/ep, ...]); ``local_mesh_dim`` names (index or dim
    name) the mesh dim the experts split over — its placement must be a
    ``Shard`` giving the concat dim. The result is the concatenated
    global tensor placed per ``placements`` (expert dim sharded over the
    ep axis, everything else as given), so GSPMD sees exactly the
    1/ep-expert layout `MoELayer` computes with.
    """
    if not local_tensor_list:
        raise ValueError("moe_global_mesh_tensor needs a non-empty "
                         "local_tensor_list")
    if mesh is None:
        jm = env.get_mesh()
        mesh = ProcessMesh(
            np.arange(jm.devices.size).reshape(jm.devices.shape),
            list(jm.axis_names))
    if isinstance(local_mesh_dim, str):
        local_mesh_dim = mesh.dim_names.index(local_mesh_dim)
    local_mesh_dim = local_mesh_dim % mesh.ndim
    if placements is None:
        # default EP layout: experts split on dim 0 over the local mesh
        # dim, replicated elsewhere
        placements = [Replicate()] * mesh.ndim
        placements[local_mesh_dim] = Shard(0)
    pl = placements[local_mesh_dim]
    if not isinstance(pl, Shard):
        raise ValueError(
            f"the expert-parallel mesh dim "
            f"{mesh.dim_names[local_mesh_dim]!r} must carry a Shard "
            f"placement (the expert concat dim); got {pl!r}")
    degree = mesh.shape[local_mesh_dim]
    if len(local_tensor_list) != degree:
        raise ValueError(
            f"{len(local_tensor_list)} local tensors for an ep degree "
            f"of {degree} (one slice per EP rank)")
    datas = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
             for t in local_tensor_list]
    global_data = jnp.concatenate(datas, axis=pl.dim)
    return shard_tensor(Tensor._wrap(global_data), mesh, placements)


from .engine import DistModel, Strategy, to_static  # noqa: E402,F401
from .planner import Plan, infer_model_spec, plan  # noqa: E402,F401


def apply_sharding_rules(layer, rules, mesh=None):
    """Place every parameter of `layer` per (regex, axis-spec) `rules` —
    the generic per-layer SPMD entry (the role of the reference's 93
    per-op spmd_rules files, applied at the weight level where GSPMD then
    propagates). Axes are dropped per-param when the dim is absent from
    the mesh or not divisible, so one rule set serves any mesh shape.

    rules: list of (pattern, spec) where spec is a tuple of mesh-axis
    names (or None) per dim — the format of gpt/llama_sharding_rules.
    """
    from ...models.gpt import match_sharding

    if mesh is None:
        mesh = env.get_mesh()

    for name, p in layer.named_parameters():
        spec = match_sharding(name, rules) or ()
        axes = [a if (a and a in mesh.axis_names
                      and p._data.shape[i] % mesh.shape[a] == 0) else None
                for i, a in enumerate(spec)]
        p._data = jax.device_put(
            p._data, NamedSharding(mesh, P(*axes) if axes else P()))
    return layer

from .spmd_rules import (  # noqa: E402,F401
    auto_shard_layer, plan_layer_specs, register_layer_rule, LAYER_RULES,
)
