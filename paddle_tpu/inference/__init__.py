"""paddle.inference — minimal Predictor over the jit servable.

Reference parity surface: paddle/fluid/inference (Config:
paddle.inference.Config, create_predictor, Predictor.run). The 92k-LoC
deployment stack (pass pipelines, TensorRT) is explicitly descoped
(docs/DECISIONS.md §4); what ships is the piece a ported serving script
needs: load a `paddle.jit.save` artifact and run it as a compiled XLA
executable with the reference's handle-style API.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorPool",
           "get_version"]


def get_version():
    return "paddle-tpu-inference (XLA)"


class Config:
    """reference paddle.inference.Config(prog_file?) — here: the
    jit.save path prefix."""

    def __init__(self, model_path=None, params_path=None):
        self._model_path = model_path
        self._use_gpu = False
        self._ir_optim = True

    def model_path(self):
        return self._model_path

    # accepted-for-parity toggles: XLA owns optimization/placement
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True

    def disable_gpu(self):
        self._use_gpu = False

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def set_model(self, prefix, params_path=None):
        """reference Config.set_model — late-bind the artifact path."""
        self._model_path = prefix

    def enable_shape_bucketing(self, buckets=(1, 2, 4, 8, 16, 32, 64)):
        """TPU-first serving lever: XLA compiles one executable per input
        shape, so free-form batch sizes each pay a compile. With
        bucketing on, Predictor.run pads every input's dim 0 up to the
        nearest bucket (and trims outputs back), bounding the number of
        compiled programs to len(buckets)."""
        self._buckets = tuple(sorted(int(b) for b in buckets))

    def summary(self):
        return (f"Config(model={self._model_path!r}, "
                f"buckets={getattr(self, '_buckets', None)})")


class _Handle:
    """Input/output handle (reference ZeroCopyTensor surface)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)


class Predictor:
    def __init__(self, config: Config, _shared_layer=None):
        from ..jit import load as jit_load

        if _shared_layer is not None:
            self._layer = _shared_layer
        else:
            if config.model_path() is None:
                raise ValueError("Config needs the jit.save path prefix")
            self._layer = jit_load(config.model_path())
        self._inputs = {}
        self._outputs = []
        self._buckets = getattr(config, "_buckets", None)

    def get_input_names(self):
        # arity from the saved artifact (jit.save records it), so the
        # reference workflow — get_input_names() first, then bind each —
        # works for multi-input servables; fall back to bound handles
        # for pre-arity artifacts
        n = getattr(self._layer, "num_inputs", None)
        return [f"x{i}" for i in range(n or max(1, len(self._inputs)))]

    def get_input_handle(self, name):
        return self._inputs.setdefault(name, _Handle())

    def get_output_names(self):
        n = getattr(self._layer, "num_outputs", None)
        return [f"out{i}" for i in range(n or max(1, len(self._outputs)))]

    def get_output_handle(self, name):
        idx = int(name[3:]) if name.startswith("out") else 0
        while len(self._outputs) <= idx:
            self._outputs.append(_Handle())
        return self._outputs[idx]

    def run(self):
        import paddle_tpu as paddle

        def _key(item):
            name = item[0]
            digits = "".join(c for c in name if c.isdigit())
            return (int(digits) if digits else 0, name)

        raw = [h._value
               for _, h in sorted(self._inputs.items(), key=_key)]
        true_b = bucket = None
        if self._buckets and raw and raw[0].ndim > 0:
            true_b = raw[0].shape[0]
            bucket = next((b for b in self._buckets if b >= true_b),
                          None)
            if bucket is not None and bucket != true_b:
                raw = [np.concatenate(
                    [a, np.zeros((bucket - true_b,) + a.shape[1:],
                                 a.dtype)], 0)
                    if a.ndim > 0 and a.shape[0] == true_b else a
                    for a in raw]
            else:
                true_b = bucket = None  # exact fit / over largest: as-is
        args = [paddle.to_tensor(a) for a in raw]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for i, o in enumerate(outs):
            while len(self._outputs) <= i:
                self._outputs.append(_Handle())
            val = np.asarray(o._data)
            # trim ONLY outputs whose leading dim is exactly the padded
            # bucket (an output whose dim 0 is not batch stays whole)
            if true_b is not None and val.ndim > 0 \
                    and val.shape[0] == bucket:
                val = val[:true_b]
            self._outputs[i]._value = val
        return True


class PredictorPool:
    """reference paddle.inference.PredictorPool: N predictors sharing
    ONE loaded artifact (one deserialization, one on-device weight copy,
    one compiled executable — per-predictor state is just the I/O
    handles)."""

    def __init__(self, config: Config, size: int = 1):
        first = Predictor(config)
        self._predictors = [first] + [
            Predictor(config, _shared_layer=first._layer)
            for _ in range(size - 1)]

    def retrieve(self, idx):
        return self._predictors[idx]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
