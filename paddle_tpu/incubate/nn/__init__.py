"""paddle.incubate.nn — the "fused" transformer building blocks.

Reference parity: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention:213, FusedFeedForward:534,
FusedBiasDropoutResidualLayerNorm:94, FusedTransformerEncoderLayer:750)
and layer/fused_linear.py. The reference backs these with hand-fused
CUDA megakernels; on TPU the SAME fusion comes from XLA (elementwise
chains into matmuls) plus the pallas flash-attention path behind
F.scaled_dot_product_attention — so these layers are thin, keep the
reference's parameter layout (single packed qkv weight
[3, heads, head_dim, embed] etc.), and compile into fused programs
through TrainStep like everything else.
"""
from __future__ import annotations

import jax.numpy as jnp

from ... import nn
from ...framework.tensor import Tensor
from ...nn import functional as F
from ...ops._dispatch import ensure_tensor

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedBiasDropoutResidualLayerNorm",
           "FusedTransformerEncoderLayer", "FusedLinear",
           "FusedDropoutAdd", "FusedDropout", "FusedEcMoe",
           "FusedMultiTransformer"]


class FusedMultiHeadAttention(nn.Layer):
    """reference fused_transformer.py:213 — pre/post-LN attention block
    with packed qkv weight [3, num_heads, head_dim, embed_dim]."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("num_heads must divide embed_dim")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self._transpose_qkv_wb = transpose_qkv_wb
        if transpose_qkv_wb:
            qkv_shape = [embed_dim, 3 * embed_dim]
            bias_shape = [3 * embed_dim]
        else:
            qkv_shape = [3, num_heads, self.head_dim, embed_dim]
            bias_shape = [3, num_heads, self.head_dim]
        self.qkv_weight = self.create_parameter(qkv_shape,
                                                attr=qkv_weight_attr)
        self.qkv_bias = (None if qkv_bias_attr is False else
                         self.create_parameter(bias_shape,
                                               attr=qkv_bias_attr,
                                               is_bias=True))
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = (None if linear_bias_attr is False else
                            self.create_parameter([embed_dim],
                                                  attr=linear_bias_attr,
                                                  is_bias=True))
        self.pre_ln = nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.post_ln = nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        x = ensure_tensor(query)
        residual = x
        if self.normalize_before:
            x = self.pre_ln(x)
        b, s, _ = x.shape
        # all reshapes/slices go through taped Tensor ops so grads flow
        # back to the packed qkv parameters
        if self._transpose_qkv_wb:
            qkv = x.matmul(self.qkv_weight)            # [b, s, 3e]
            if self.qkv_bias is not None:
                qkv = qkv + self.qkv_bias
        else:
            w = self.qkv_weight.reshape(
                [3 * self.num_heads * self.head_dim, self.embed_dim])
            qkv = x.matmul(w, transpose_y=True)        # [b, s, 3e]
            if self.qkv_bias is not None:
                qkv = qkv + self.qkv_bias.reshape([-1])
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]                               # [b, s, h, d]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, is_causal=False,
            training=self.training)
        out = out.reshape([b, s, self.embed_dim])
        out = out.matmul(self.linear_weight)
        if self.linear_bias is not None:
            out = out + self.linear_bias
        if self.dropout_rate:
            out = F.dropout(out, p=self.dropout_rate,
                            training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = self.post_ln(out)
        return out


class FusedFeedForward(nn.Layer):
    """reference fused_transformer.py:534 — LN + fc1 + act + fc2 +
    dropout + residual in one compiled block."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 weight_attr=linear1_weight_attr,
                                 bias_attr=linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 weight_attr=linear2_weight_attr,
                                 bias_attr=linear2_bias_attr)
        self.norm = nn.LayerNorm(d_model, epsilon=epsilon)

    def forward(self, src, cache=None):
        x = ensure_tensor(src)
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        x = getattr(F, self.activation)(self.linear1(x))
        if self.act_dropout_rate:
            x = F.dropout(x, p=self.act_dropout_rate,
                          training=self.training)
        x = self.linear2(x)
        if self.dropout_rate:
            x = F.dropout(x, p=self.dropout_rate, training=self.training)
        out = residual + x
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """reference fused_transformer.py:94 — y = LN(residual + dropout(x
    + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, bias_attr=None,
                 epsilon=1e-5, name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.linear_bias = (None if bias_attr is False else
                            self.create_parameter([embed_dim],
                                                  attr=bias_attr,
                                                  is_bias=True))
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, residual):
        x = ensure_tensor(x)
        if self.linear_bias is not None:
            x = x + self.linear_bias
        if self.dropout_rate:
            x = F.dropout(x, p=self.dropout_rate, training=self.training)
        return self.norm(ensure_tensor(residual) + x)


class FusedTransformerEncoderLayer(nn.Layer):
    """reference fused_transformer.py:750 — attention block + FFN block."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_drop = (dropout_rate if attn_dropout_rate is None
                     else attn_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_drop,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedLinear(nn.Linear):
    """reference layer/fused_linear.py — on TPU a Linear already compiles
    to one fused matmul+bias kernel; kept for API parity."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__(in_features, out_features,
                         weight_attr=weight_attr, bias_attr=bias_attr)
        self._transpose_weight = transpose_weight


class FusedDropoutAdd(nn.Layer):
    """reference layer/fused_dropout_add.py — dropout(x) + y as one
    layer (XLA fuses the pair; kept for API parity)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from .functional import fused_dropout_add

        return fused_dropout_add(x, y, p=self.p, training=self.training,
                                 mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedDropout(nn.Dropout):
    """reference layer/fused_dropout_nd.py — Dropout with an axis arg
    (row/column dropout); the TPU dropout already fuses."""

    def __init__(self, p=0.5, axis=None, mode="upscale_in_train",
                 name=None):
        super().__init__(p=p, axis=axis, mode=mode)


class FusedEcMoe(nn.Layer):
    """reference layer/fused_ec_moe.py — expert-choice MoE FFN over the
    fused_ec_moe functional (each expert picks its top-capacity tokens)."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError("act_type must be 'gelu' or 'relu'")
        self.act_type = act_type
        e, d, f = num_experts, hidden_size, inter_size
        if bias_attr is False:
            raise ValueError(
                "FusedEcMoe requires biases (the fused kernel contract "
                "has [e, 1, *] bias operands); pass zeros instead")
        self.bmm_weight0 = self.create_parameter(
            (e, d, f), attr=weight_attr)
        self.bmm_bias0 = self.create_parameter((e, 1, f), attr=bias_attr,
                                               is_bias=True)
        self.bmm_weight1 = self.create_parameter(
            (e, f, d), attr=weight_attr)
        self.bmm_bias1 = self.create_parameter((e, 1, d), attr=bias_attr,
                                               is_bias=True)

    def forward(self, x, gate):
        from .functional import fused_ec_moe

        return fused_ec_moe(x, gate, self.bmm_weight0, self.bmm_bias0,
                            self.bmm_weight1, self.bmm_bias1,
                            self.act_type)


class FusedMultiTransformer(nn.Layer):
    """reference incubate/nn/layer/fused_transformer.py
    FusedMultiTransformer — the N-layer fused DECODE kernel of the
    inference deployment stack. Descoped with the rest of that stack
    (docs/DECISIONS.md §4): construction raises with guidance; training
    uses the per-layer Fused* blocks / nn.TransformerEncoder."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "FusedMultiTransformer is the inference deployment stack's "
            "decode engine (descoped, docs/DECISIONS.md §4); compose "
            "FusedMultiHeadAttention + FusedFeedForward or "
            "nn.TransformerEncoder for training/eval")


from . import functional  # noqa: E402,F401
