"""Serving metrics: counters + per-request latency aggregation.

One ``ServingMetrics`` lives on the engine; the scheduler and the step
loop feed it events, and ``snapshot()`` renders the surface the bench
lane records (queue depth, running/waiting, per-request TTFT and
inter-token latency percentiles, aggregate tok/s, preemption and
page-reclaim counters). Everything is host-side and O(1) per event —
no device sync is ever added for metrics.
"""
from __future__ import annotations

import time

__all__ = ["ServingMetrics", "percentile"]


def percentile(values, q):
    """Nearest-rank percentile (q in [0, 100]) of a list, None if empty."""
    if not values:
        return None
    xs = sorted(values)
    k = max(0, min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


class ServingMetrics:
    def __init__(self, clock=time.perf_counter):
        self.clock = clock
        self.start_time = clock()
        # counters
        self.submitted = 0
        self.admitted = 0
        self.resumed = 0          # re-admissions of preempted requests
        self.finished = 0
        self.preemptions = 0
        self.evicted_pages = 0    # pages reclaimed by preemption
        self.prefill_chunks = 0
        self.decode_steps = 0
        self.generated_tokens = 0
        # gauges (refreshed every engine step)
        self.queue_depth = 0
        self.running = 0
        # per-request latency samples (appended at finish)
        self.ttft_s: list[float] = []
        self.itl_s: list[float] = []      # all inter-token gaps
        self.request_preemptions: list[int] = []

    # -- event feeds ------------------------------------------------------
    def on_submit(self):
        self.submitted += 1

    def on_admit(self, resumed: bool):
        self.admitted += 1
        if resumed:
            self.resumed += 1

    def on_preempt(self, pages_reclaimed: int):
        self.preemptions += 1
        self.evicted_pages += int(pages_reclaimed)

    def on_token(self):
        self.generated_tokens += 1

    def on_finish(self, handle):
        self.finished += 1
        if handle.ttft is not None:
            self.ttft_s.append(handle.ttft)
        self.itl_s.extend(handle.inter_token_latencies)
        self.request_preemptions.append(handle.preemptions)

    def observe(self, queue_depth: int, running: int):
        self.queue_depth = queue_depth
        self.running = running

    # -- surface ----------------------------------------------------------
    def snapshot(self) -> dict:
        elapsed = max(self.clock() - self.start_time, 1e-9)
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "resumed": self.resumed,
            "finished": self.finished,
            "preemptions": self.preemptions,
            "evicted_pages": self.evicted_pages,
            "prefill_chunks": self.prefill_chunks,
            "decode_steps": self.decode_steps,
            "generated_tokens": self.generated_tokens,
            "queue_depth": self.queue_depth,
            "running": self.running,
            "elapsed_s": round(elapsed, 4),
            "tok_s": round(self.generated_tokens / elapsed, 2),
            "ttft_p50_s": percentile(self.ttft_s, 50),
            "ttft_p99_s": percentile(self.ttft_s, 99),
            "itl_p50_s": percentile(self.itl_s, 50),
            "itl_p99_s": percentile(self.itl_s, 99),
        }
