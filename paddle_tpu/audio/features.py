"""paddle.audio.features parity (reference audio/features/layers.py):
Spectrogram:45, MelSpectrogram:130, LogMelSpectrogram:237, MFCC:344 —
nn.Layers over the framework stft, fully traceable."""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..framework.tensor import Tensor
from ..ops._dispatch import ensure_tensor
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(nn.Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = F.get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        from ..signal import stft

        spec = stft(ensure_tensor(x), self.n_fft, self.hop_length,
                    self.win_length, window=self.window, center=self.center,
                    pad_mode=self.pad_mode)
        mag = Tensor._wrap(jnp.abs(spec._data) ** self.power)
        return mag


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.fbank = F.compute_fbank_matrix(
            sr, n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk,
            norm=norm, dtype=dtype)

    def forward(self, x):
        spec = self._spectrogram(x)                # [.., freq, time]
        mel = jnp.einsum("mf,...ft->...mt", self.fbank._data, spec._data)
        return Tensor._wrap(mel)


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return F.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = F.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)       # [.., n_mels, time]
        out = jnp.einsum("mk,...mt->...kt", self.dct_matrix._data,
                         logmel._data)
        return Tensor._wrap(out)
