"""paddle.regularizer parity (python/paddle/regularizer.py): L1Decay /
L2Decay carry their coefficient; the optimizer folds them into the
gradient (optimizer/optimizer.py _apply_decay reads `_coeff`)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L2Decay(WeightDecayRegularizer):
    """grad += coeff * param (reference regularizer.py L2Decay)."""


class L1Decay(WeightDecayRegularizer):
    """grad += coeff * sign(param) (reference regularizer.py L1Decay)."""
