"""Disaggregated multi-replica serving fleet (ISSUE 18).

One ``FleetRouter`` fronts N ``ServingEngine`` replicas — each with
its own registry, tracer, scheduler and KV pool — and owns four
policies the single engine cannot express:

* **Routing** (`router.ReplicaRouter`): sessions stick to a replica by
  rendezvous hashing (add/remove remaps only ~1/N sessions);
  sessionless requests go power-of-two-choices on live queue depth.
* **Prefill/decode disaggregation**: dedicated prefill replicas
  (``prefill_only=True`` engines) run chunked prefill and nothing
  else; every sequence that finishes prefill is harvested —
  ``export_handoff`` on the prefill side, ``adopt_handoff`` on a
  decode replica — so a prefill burst lands on prefill hardware and
  never lumps whole chunk batches into decode replicas' inter-token
  gaps. The first token is emitted by the prefill leg (TTFT is paid
  where the work is); the decode leg continues the stream
  bit-identically (same pages, same per-request seed, same programs).
* **KV eviction to host memory** (`HostKVRing`): decode replicas with
  a ring park preemption victims' pages host-side instead of
  discarding them; re-admission imports the pages back (a ``kv_onload``
  span on the victim's trace) instead of re-prefilling. The ring is
  byte-capped and drops oldest-first — a dropped blob silently falls
  back to the pre-fleet resume-by-re-prefill path.
* **SLO-burn autoscaling** (`SLOBurnAutoscaler`): the decode set
  grows when the worst per-replica SLO burn rate stays hot and shrinks
  when it stays cold — burn rate, not raw QPS, so an over-provisioned
  fleet under heavy-but-meeting-SLO load does NOT flap. Spawned
  replicas record cold-start-to-first-token; with the persistent
  compile cache warm that spin-up is a deserialize.

Self-healing (ISSUE 19): an optional per-replica watchdog walks
HEALTHY -> SUSPECT -> DEAD from heartbeat/progress staleness, replica
errors and dead threads; a DEAD replica is quarantined (removed from
every router, retained for inspection) and its in-flight requests are
re-dispatched to survivors with exactly-once token delivery — resume
re-prefills prompt + already-delivered tokens, the deterministic
per-request RNG regenerates the identical continuation, and an epoch
fence on every handle stops a wedged thread that later unsticks from
emitting duplicates. KV hand-offs become lease/ack transactions (the
exporter retains pages until the adopter acks, so an adopter death
between export and import loses nothing), and a circuit-breaker
brown-out sheds lowest-priority admissions while healthy decode
capacity sits below a watermark of the intended fleet size.

Threading model: one thread per replica (``threaded=True``) or a
cooperative round-robin ``step()``/``run()`` loop (deterministic —
the parity lanes use it). Locks are strictly one-at-a-time: replica
loops hold only their own lock; hand-off dispatch enqueues under the
target's lock AFTER releasing the source's; the autoscaler pauses the
whole fleet (ordered acquisition) only around a spawn's warmup so a
fresh trace never races a live dispatch.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..jit.decode_step import refresh_serving_buffers
from ..observability import faults, merge_histograms
from ..observability import registry as _global_registry
from ..observability import recorder as _recorder
from .engine import ServingEngine
from .request import FinishReason, Request, RequestHandle, RequestState
from .router import ReplicaRouter

__all__ = ["FleetRouter", "HostKVRing", "SLOBurnAutoscaler"]

# host ring default size, MB (0 = off) — overridable per fleet
_RING_FLAG = "PADDLE_TPU_KV_HOST_RING_MB"


class HostKVRing:
    """Byte-capped host-memory parking lot for evicted KV blobs,
    keyed by rid. LRU-by-insertion: when a put overflows the cap the
    oldest entries drop (their requests fall back to re-prefill).
    Thread-safe — decode replicas share one ring, so fleet-wide host
    memory spent on parked sessions stays bounded by ONE number."""

    def __init__(self, capacity_mb: float = 64.0):
        self.capacity_bytes = max(0, int(float(capacity_mb) * (1 << 20)))
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # rid -> (blob, tok)
        self.bytes = 0
        self.puts = 0
        self.takes = 0
        self.drops = 0

    def put(self, rid: int, blob: dict, last_token: int):
        if faults.should_fire("kv.ring.drop", rid=rid):
            # injected drop: blob discarded before insertion — the
            # request silently falls back to resume-by-re-prefill,
            # exactly like a capacity drop
            with self._lock:
                self.drops += 1
            return
        with self._lock:
            old = self._entries.pop(rid, None)
            if old is not None:
                self.bytes -= old[0]["nbytes"]
            self._entries[rid] = (blob, int(last_token))
            self.bytes += blob["nbytes"]
            self.puts += 1
            while self.bytes > self.capacity_bytes and self._entries:
                _, (dropped, _tok) = self._entries.popitem(last=False)
                self.bytes -= dropped["nbytes"]
                self.drops += 1

    def peek(self, rid: int):
        with self._lock:
            return self._entries.get(rid)

    def take(self, rid: int):
        with self._lock:
            entry = self._entries.pop(rid, None)
            if entry is not None:
                self.bytes -= entry[0]["nbytes"]
                self.takes += 1
            return entry

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self.bytes,
                    "capacity_bytes": self.capacity_bytes,
                    "puts": self.puts, "takes": self.takes,
                    "drops": self.drops}


class _Replica:
    """One engine + its thread/lock/hand-off inbox."""

    def __init__(self, name: str, role: str, engine):
        self.name = name
        self.role = role                    # "decode" | "prefill"
        self.engine = engine
        self.lock = threading.RLock()
        self.thread = None
        self.stop = False
        self.draining = False
        self.error = None
        self.pending_imports: deque = deque()  # (handle, blob, token)
        self.spawn_report = None
        # self-healing state (ISSUE 19)
        self.health = "healthy"             # healthy | suspect | dead
        self.heartbeat = None               # clock() at last loop top
        self.progress = 0                   # worked-step counter
        self.suspect_since = None
        self.cause = None                   # why quarantined
        self.harvest_safe = None            # lock taken during harvest?
        self.pending_acks: deque = deque()  # lease ids awaiting release

    @property
    def load(self) -> int:
        s = self.engine.scheduler
        return (len(s.waiting) + len(s.running)
                + len(self.pending_imports))


class FleetRouter:
    def __init__(self, model=None, model_factory=None,
                 decode_replicas=1, prefill_replicas=0, engine_kw=None,
                 threaded=False, seed=0, host_ring_mb=None,
                 autoscale=None, engine_cls=ServingEngine,
                 clock=time.perf_counter, watchdog=None, brownout=None,
                 handoff_lease=True, join_timeout_s=30.0):
        if model is None and model_factory is None:
            raise ValueError("pass a model or a model_factory")
        # a shared model is safe because replicas only ever BIND the
        # same param objects (identical references); a model_factory
        # gives each replica its own instance instead
        self._model_factory = (model_factory if model_factory is not None
                               else (lambda: model))
        self.engine_cls = engine_cls
        self.engine_kw = dict(engine_kw or {})
        self.threaded = bool(threaded)
        self.clock = clock
        if host_ring_mb is None:
            host_ring_mb = float(os.environ.get(_RING_FLAG, "0") or 0)
        self.host_ring = (HostKVRing(host_ring_mb)
                          if host_ring_mb and host_ring_mb > 0 else None)
        self.router = ReplicaRouter(seed=seed)          # decode set
        self.prefill_router = ReplicaRouter(seed=seed + 1)
        self._replicas: list[_Replica] = []
        self._retired: list[_Replica] = []
        self._by_name: dict[str, _Replica] = {}
        self._spawned = {"decode": 0, "prefill": 0}
        self._requests: dict[int, dict] = {}    # rid -> routing entry
        self._rid = 0
        self._submit_lock = threading.Lock()
        # exported-but-not-yet-enqueued hand-offs: counted so has_work
        # (and therefore drain) can never observe "idle" while a
        # sequence is in flight between a prefill replica's harvest and
        # its decode replica's inbox
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # adoptions per replica loop pass: one by default, so a wave of
        # hand-offs smears its import cost across many inter-token gaps
        # instead of landing the whole batch inside one (the thing the
        # disaggregation exists to prevent)
        self.adopt_batch = 1
        # threaded mode: a prefill replica sleeps this long after every
        # worked step. Prefill is the throughput role and decode the
        # latency role — without the yield the prefill thread convoys
        # the GIL through back-to-back chunk batches and decode's
        # inter-token gaps eat SEVERAL chunks instead of at most one
        # (measured 12ms vs 5ms p99 on the CPU lane)
        self.prefill_yield_s = 2e-4
        self._started = False
        self.events: list[dict] = []    # spawn/drain/autoscale log
        # self-healing config (ISSUE 19). watchdog=None keeps the
        # pre-fleet behavior exactly: replica errors propagate out of
        # step()/drain() instead of quarantining.
        self.join_timeout_s = float(join_timeout_s)
        self._hung: list[str] = []          # replicas whose join timed out
        self._quarantined: list[_Replica] = []
        if watchdog is not None:
            wd = dict(suspect_after_s=0.5, dead_after_s=2.0)
            wd.update(watchdog if isinstance(watchdog, dict) else {})
            self.watchdog = wd
        else:
            self.watchdog = None
        if brownout is not None:
            bo = dict(watermark=0.75, priority_floor=1)
            bo.update(brownout if isinstance(brownout, dict) else {})
            self.brownout = bo
        else:
            self.brownout = None
        self.handoff_lease = bool(handoff_lease)
        self.recoveries: list[dict] = []    # one record per quarantine
        # leases whose adopter died mid-import: (exporter, lease_id,
        # handle, tok) tuples waiting for a re-export from the
        # exporter's retained pages
        self._relets: deque = deque()
        # intended decode-set size: brown-out sheds against THIS, so a
        # quarantine (unlike a deliberate scale_down) counts as lost
        # capacity
        self._nominal_decode = 0
        for _ in range(int(prefill_replicas)):
            self._add_replica(self._spawn_replica("prefill", warm=False))
        for _ in range(int(decode_replicas)):
            self._add_replica(self._spawn_replica("decode", warm=False))
        self.autoscaler = None
        if autoscale is not None:
            if isinstance(autoscale, SLOBurnAutoscaler):
                self.autoscaler = autoscale
            else:
                self.autoscaler = SLOBurnAutoscaler(
                    self, **(autoscale if isinstance(autoscale, dict)
                             else {}))
        self._bind_gauges()

    # -- construction -----------------------------------------------------
    def _spawn_replica(self, role: str, warm: bool) -> _Replica:
        idx = self._spawned[role]
        self._spawned[role] += 1
        name = f"{'p' if role == 'prefill' else 'd'}{idx}"
        t0 = self.clock()
        kw = dict(self.engine_kw)
        kw.setdefault("clock", self.clock)
        eng = self.engine_cls(
            self._model_factory(), prefill_only=(role == "prefill"),
            host_kv_ring=(self.host_ring if role == "decode" else None),
            **kw)
        eng.name = name
        r = _Replica(name, role, eng)
        if warm:
            # cold-start-to-first-token receipt: a tiny probe through
            # the fresh engine times the first prefill+decode programs
            # (compiles, or deserializes from the persistent cache),
            # then warmup covers the remaining chunk buckets
            probe = eng.submit(np.ones((4,), np.int32),
                               1 if role == "prefill" else 2)
            eng.run()
            first_ms = (probe.first_token_time - t0) * 1e3
            eng.warmup()
            if self._migration_enabled():
                self._warm_migration(eng)
            r.spawn_report = {
                "cold_start_to_first_token_ms": round(first_ms, 3),
                "spawn_ms": round((self.clock() - t0) * 1e3, 3),
                **eng.warmup_report,
            }
        return r

    def _add_replica(self, r: _Replica):
        self._replicas.append(r)
        self._by_name[r.name] = r
        (self.router if r.role == "decode"
         else self.prefill_router).add(r.name)
        if r.role == "decode":
            self._nominal_decode = max(self._nominal_decode,
                                       len(self.decode_replicas()))
        if self.threaded and self._started:
            self._start_thread(r)

    def _bind_gauges(self):
        g = _global_registry()
        g.gauge("fleet.replicas").set_fn(
            lambda: len(self._replicas))
        g.gauge("fleet.decode_replicas").set_fn(
            lambda: len(self.decode_replicas()))
        g.gauge("fleet.queue_depth").set_fn(
            lambda: sum(r.load for r in list(self._replicas)))
        g.gauge("fleet.host_ring_bytes").set_fn(
            lambda: self.host_ring.bytes if self.host_ring else 0)
        g.gauge("fleet.host_ring_entries").set_fn(
            lambda: len(self.host_ring) if self.host_ring else 0)

    # -- replica views ----------------------------------------------------
    def decode_replicas(self) -> list[_Replica]:
        return [r for r in self._replicas
                if r.role == "decode" and not r.draining]

    def prefill_replicas(self) -> list[_Replica]:
        return [r for r in self._replicas
                if r.role == "prefill" and not r.draining]

    def replica(self, name: str) -> _Replica:
        return self._by_name[name]

    def _load_of(self, name: str) -> int:
        r = self._by_name.get(name)
        return r.load if r is not None else 1 << 30

    # -- client surface ---------------------------------------------------
    def submit(self, prompt, max_new_tokens, priority=0,
               eos_token_id=None, seed=None, session=None,
               on_token=None, deadline_s=None):
        """Route one request into the fleet; returns its handle. The
        fleet rid is globally unique (trace legs stitch by it) and
        doubles as the default sampling seed — a request's token
        stream depends only on (prompt, seed), never on which replica
        serves it."""
        with self._submit_lock:
            rid = self._rid
            self._rid += 1
        if seed is None:
            seed = rid
        if (self.brownout is not None and self._brownout_active()
                and priority < self.brownout["priority_floor"]):
            # circuit-breaker brown-out: healthy decode capacity is
            # below the watermark, so low-priority admissions are shed
            # at the door — never routed, never holding pages
            req = Request(rid=rid,
                          prompt=np.asarray(prompt, np.int32),
                          max_new_tokens=int(max_new_tokens),
                          priority=priority, eos_token_id=eos_token_id,
                          seed=seed, deadline_s=deadline_s)
            handle = RequestHandle(req, on_token=on_token)
            handle.submit_time = handle.finish_time = self.clock()
            handle.state = RequestState.FAILED
            handle.finish_reason = FinishReason.SHED
            _global_registry().counter("fleet.brownout.shed").inc()
            _recorder().note("fleet_brownout_shed", rid=rid,
                             priority=priority,
                             healthy=len(self.decode_replicas()),
                             nominal=self._nominal_decode)
            return handle
        dname = self.router.pick(self._load_of, session=session)
        entry = {"decode": dname, "session": session}
        if self.prefill_replicas():
            entry["prefill"] = self.prefill_router.pick(self._load_of)
            target = self._by_name[entry["prefill"]]
        else:
            target = self._by_name[dname]
        with target.lock:
            handle = target.engine.submit(
                prompt, max_new_tokens, priority=priority,
                eos_token_id=eos_token_id, seed=seed,
                on_token=on_token, rid=rid, deadline_s=deadline_s)
        entry["handle"] = handle
        entry["at"] = target.name       # which replica holds it NOW
        self._requests[rid] = entry
        return handle

    def _brownout_active(self) -> bool:
        nominal = max(self._nominal_decode, 1)
        return (len(self.decode_replicas())
                < nominal * self.brownout["watermark"])

    # -- hand-off ---------------------------------------------------------
    def _harvest_locked(self, r: _Replica) -> list:
        """Export every sequence that finished prefill on a prefill
        replica (caller holds r.lock). Requests that FINISHED on the
        prefill leg (max_new_tokens == 1) retire there and are never
        exported."""
        out = []
        eng = r.engine
        cands = [slot for slot in sorted(eng.scheduler.running)
                 if eng.scheduler.running[slot].state
                 is RequestState.RUNNING
                 and not eng.scheduler.running[slot].done]
        if not cands:
            return out
        # count BEFORE exporting: export_handoff pops the handle from
        # the scheduler, so from that instant until dispatch the
        # in-flight counter is the only thing keeping has_work() true
        with self._inflight_lock:
            self._inflight += len(cands)
        done = 0
        try:
            for slot in cands:
                item = eng.export_handoff(slot,
                                          lease=self.handoff_lease)
                if self.handoff_lease:
                    # lease metadata rides in the blob so a harvested
                    # item can always find its exporter
                    item[1]["lease_from"] = r.name
                # fault point: flip one payload byte in transit — the
                # adopter's crc32 check must reject it BEFORE any
                # allocation
                faults.corrupt_blob("kv.handoff.corrupt", item[1],
                                    rid=item[0].request.rid)
                out.append(item)
                done += 1
        finally:
            if done < len(cands):
                with self._inflight_lock:
                    self._inflight -= len(cands) - done
        return out

    def _dispatch_handoff(self, item):
        """Enqueue an exported sequence on its decode replica's inbox
        (no other lock held). A draining/retired target re-routes."""
        handle, blob, _tok = item
        rid = handle.request.rid
        try:
            entry = self._requests.get(rid, {})
            r = self._by_name.get(entry.get("decode"))
            if r is None or r.draining or r.role != "decode":
                entry["decode"] = self.router.pick(
                    self._load_of, session=entry.get("session"))
                r = self._by_name[entry["decode"]]
            with r.lock:
                r.pending_imports.append(item)
            entry["at"] = r.name
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _drain_imports_locked(self, r: _Replica) -> bool:
        moved = False
        adopted = 0
        refresh = False
        while r.pending_imports and adopted < self.adopt_batch:
            handle, blob, tok = r.pending_imports[0]
            if not r.engine.can_adopt(blob):
                break
            # adopt FIRST, pop after: the item must stay visible in the
            # inbox while the import runs, or has_work() (lockless, the
            # drain poll) sees an idle fleet mid-adoption and returns
            # with the sequence in limbo
            try:
                r.engine.adopt_handoff(handle, blob, tok, refresh=False)
            except ValueError:
                # corrupt payload rejected pre-allocation (crc32).
                # Leased: the exporter still holds the pages — ask it
                # to re-export. Unleased: the pages are gone, fall back
                # to resume-by-re-prefill on this replica. Hand off to
                # the next owner FIRST, pop after — same reason as the
                # adopt path: has_work() must never see the sequence
                # in limbo.
                _global_registry().counter("fleet.handoff.corrupt").inc()
                _recorder().note("fleet_handoff_corrupt",
                                 rid=handle.request.rid,
                                 lease=blob.get("lease_id"),
                                 leased=blob.get("lease_id") is not None)
                if (blob.get("lease_id") is not None
                        and blob.get("lease_from") in self._by_name):
                    self._relets.append((blob["lease_from"],
                                         blob["lease_id"], handle, tok))
                else:
                    handle._requeue_for_resume()
                    r.engine.resubmit(handle)
                r.pending_imports.popleft()
                moved = True
                adopted += 1
                continue
            r.pending_imports.popleft()
            if blob.get("lease_id") is not None:
                # exactly-once page release: the pages only die at the
                # exporter once the adopter owns its own copy
                self._queue_ack(blob.get("lease_from"), blob["lease_id"])
            moved = True
            refresh = True
            adopted += 1
        if refresh:
            # one buffer resync for the whole adopted batch
            refresh_serving_buffers(r.engine)
        return moved

    def _queue_ack(self, exporter_name, lease_id):
        """Enqueue a lease release on the exporter's ack inbox (deque
        append is GIL-atomic — no exporter lock taken here; the
        exporter drains under its OWN lock). A vanished exporter's
        lease died with its pools in _recover — drop the ack."""
        p = self._by_name.get(exporter_name)
        if p is not None:
            p.pending_acks.append(lease_id)

    def _drain_acks_locked(self, r: _Replica) -> bool:
        worked = False
        while r.pending_acks:
            try:
                lease_id = r.pending_acks.popleft()
            except IndexError:
                break
            worked |= bool(r.engine.ack_handoff(lease_id))
        return worked

    # -- cooperative loop -------------------------------------------------
    def step(self) -> bool:
        """One round-robin pass over every replica (deterministic —
        single-threaded mode). Returns False when the fleet is idle."""
        worked = False
        exported = []
        for r in list(self._replicas):
            r.heartbeat = self.clock()
            try:
                with r.lock:
                    worked |= self._drain_acks_locked(r)
                    worked |= self._drain_imports_locked(r)
                    if r.engine.scheduler.has_work():
                        worked |= bool(r.engine.step())
                    if r.role == "prefill":
                        exported.extend(self._harvest_locked(r))
            except BaseException as e:
                # with a watchdog the fault is contained per-replica:
                # record it and let the tick below quarantine +
                # re-dispatch. Without one, fail loudly (old behavior).
                if self.watchdog is None:
                    raise
                r.error = e
        for item in exported:
            self._dispatch_handoff(item)
            worked = True
        worked |= self._service_relets()
        if self.watchdog is not None:
            worked |= self._watchdog_tick()
        if self.autoscaler is not None:
            self.autoscaler.tick()
        self._finalize_drained()
        return worked

    def has_work(self) -> bool:
        return (self._inflight > 0 or bool(self._relets)
                or any(r.engine.scheduler.has_work() or r.pending_imports
                       or r.pending_acks or r.engine.leased_count
                       for r in list(self._replicas)))

    def run(self, max_steps=2_000_000) -> dict:
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain in {max_steps} steps")
        return self.metrics_snapshot()

    def warmup(self):
        """Serial warmup of every replica (all tracing up front — the
        threaded loops then only ever dispatch resident programs)."""
        migrate = self._migration_enabled()
        for r in list(self._replicas):
            with r.lock:
                r.engine.warmup()
                if migrate:
                    self._warm_migration(r.engine)
        return self

    def _migration_enabled(self) -> bool:
        return (self.host_ring is not None
                or any(r.role == "prefill" for r in self._replicas)
                or self._spawned["prefill"] > 0)

    @staticmethod
    def _warm_migration(eng):
        """Compile the bucketed export/import executables up front: one
        export gather + one import scatter per migration bucket. The
        page-index shape is bucketed (kv_cache.migration_bucket), so
        this covers EVERY shape a live hand-off, eviction or onload can
        dispatch — without it, the first migration mid-stream pays an
        op-by-op XLA compile inside somebody's inter-token gap (~250ms
        measured on the CPU lane)."""
        cache = eng.cache
        for w in cache.migration_buckets():
            # largest allocatable page count that still rounds up to
            # this bucket: a bucket reachable by live sequences (e.g. a
            # 28-page max_len slot in the 32 bucket) is warmed even when
            # a full-width allocation exceeds the engine's max_len
            lo = w // 2
            n = next((n for n in range(w, lo, -1)
                      if cache.can_allocate((n - 1) * cache.page_size
                                            + 1)), None)
            if n is None:
                continue
            seq_len = (n - 1) * cache.page_size + 1
            slot = cache.allocate(seq_len)
            cache._host("seq_lens")[slot] = seq_len
            blob = cache.export_slot(slot)
            cache.free(slot)
            cache.free(cache.import_slot(blob))
        # the imports rebound the pool arrays — resync the engine's
        # buffer dict at this safe boundary
        refresh_serving_buffers(eng)

    # -- threaded loop ----------------------------------------------------
    def start(self):
        self._started = True
        if self.threaded:
            for r in list(self._replicas):
                self._start_thread(r)
        return self

    def _start_thread(self, r: _Replica):
        if r.thread is not None:
            return
        r.stop = False
        r.thread = threading.Thread(target=self._replica_loop,
                                    args=(r,), daemon=True,
                                    name=f"fleet-{r.name}")
        r.thread.start()

    def _replica_loop(self, r: _Replica):
        while not r.stop:
            worked = False
            exported = ()
            r.heartbeat = self.clock()   # watchdog staleness anchor
            try:
                with r.lock:
                    worked |= self._drain_acks_locked(r)
                    worked |= self._drain_imports_locked(r)
                    if r.engine.scheduler.has_work():
                        worked |= bool(r.engine.step())
                    if r.role == "prefill":
                        exported = self._harvest_locked(r)
            except BaseException as e:    # surfaced by drain()/stop()
                r.error = e
                return
            for item in exported:
                self._dispatch_handoff(item)
                worked = True
            if worked:
                r.progress += 1
            if not worked:
                time.sleep(5e-4)
            elif r.role == "prefill" and self.prefill_yield_s:
                time.sleep(self.prefill_yield_s)

    # -- self-healing (ISSUE 19) ------------------------------------------
    def _watchdog_tick(self) -> bool:
        """One health pass: HEALTHY -> SUSPECT -> DEAD per replica.
        Death has three causes — ``error`` (the replica loop surfaced
        an exception), ``thread_exit`` (the thread died without one),
        ``stuck`` (threaded mode: a busy replica whose heartbeat went
        stale — a wedged step). A dead replica is quarantined and its
        in-flight requests re-dispatched to survivors. Returns True
        when any replica changed state."""
        if self.watchdog is None:
            return False
        acted = False
        now = self.clock()
        for r in list(self._replicas):
            if r.error is not None:
                acted |= self._quarantine(r, "error")
                continue
            if (self.threaded and self._started and r.thread is not None
                    and not r.thread.is_alive() and not r.stop):
                acted |= self._quarantine(r, "thread_exit")
                continue
            # heartbeat staleness is only meaningful when a dedicated
            # thread owns the loop; in cooperative mode a stuck step
            # blocks the caller itself
            if not (self.threaded and self._started
                    and r.heartbeat is not None):
                continue
            busy = bool(r.engine.scheduler.has_work()
                        or r.pending_imports or r.pending_acks)
            if not busy:
                # idle replicas still heartbeat, but never alarm
                if r.health == "suspect":
                    r.health = "healthy"
                    r.suspect_since = None
                continue
            age = now - r.heartbeat
            if age >= self.watchdog["dead_after_s"]:
                acted |= self._quarantine(r, "stuck")
            elif age >= self.watchdog["suspect_after_s"]:
                if r.health != "suspect":
                    r.health = "suspect"
                    r.suspect_since = now
                    _global_registry().counter(
                        "fleet.replica.suspect").inc()
                    _recorder().note("fleet_replica_suspect",
                                     replica=r.name,
                                     heartbeat_age_s=round(age, 4))
                    acted = True
            elif r.health == "suspect":
                r.health = "healthy"
                r.suspect_since = None
        return acted

    def _quarantine(self, r: _Replica, cause: str) -> bool:
        """Remove one dead replica from every routing surface, harvest
        its in-flight requests and re-dispatch them to survivors. The
        replica object is retained in ``_quarantined`` so traces,
        metrics and the leak receipt stay inspectable."""
        if r.health == "dead":
            return False
        t_dead = self.clock()
        r.health = "dead"
        r.cause = cause
        r.stop = True
        _global_registry().counter("fleet.replica.dead").inc()
        _recorder().note("fleet_replica_dead", replica=r.name,
                         cause=cause,
                         error=(repr(r.error) if r.error is not None
                                else None))
        self.router.remove(r.name)
        self.prefill_router.remove(r.name)
        if r in self._replicas:
            self._replicas.remove(r)
        self._by_name.pop(r.name, None)
        self._quarantined.append(r)
        self.events.append({"action": "replica_dead",
                            "replica": r.name, "cause": cause})
        # the lock is only safe to take when nothing can be holding it
        # forever: the loop surfaced an error and returned, there never
        # was a thread (cooperative), or the thread is gone
        safe = (r.error is not None or r.thread is None
                or not r.thread.is_alive())
        r.harvest_safe = bool(safe)
        handles, items = self._harvest_dead(r, safe)
        reqs = [{"rid": h.request.rid,
                 "delivered": len(h.output_tokens)} for h in handles]
        reqs += [{"rid": it[0].request.rid,
                  "delivered": len(it[0].output_tokens),
                  "handoff": True} for it in items]
        n = self._redispatch(handles, items, dead=r.name)
        self.recoveries.append({
            "replica": r.name, "cause": cause, "t_dead": t_dead,
            "safe_harvest": bool(safe), "redispatched": n,
            "requests": reqs})
        return True

    def _harvest_dead(self, r: _Replica, safe: bool):
        """Collect every live request off a dead replica. Safe mode
        (lock taken): drain the scheduler directly, close the dead
        leg's spans, then ``_recover`` the engine so its leak receipt
        reads clean. Stuck mode (wedged thread may hold the lock
        forever): lockless — handles come from the fleet's own routing
        table, inbox items via GIL-atomic popleft, and the dead
        tracer's spans are abandoned (the wedged thread still owns
        them)."""
        # a handle parked in the relet queue is owned by the FLEET
        # right now (its routing entry still names the dead adopter);
        # _service_relets will re-route it — sweeping it here too
        # would dispatch it twice
        relet_ids = {id(t[2]) for t in list(self._relets)}
        if safe:
            with r.lock:
                items = list(r.pending_imports)
                r.pending_imports.clear()
                sched = r.engine.scheduler
                handles = (list(sched.running.values())
                           + list(sched.waiting))
                sched.waiting.clear()
                sched.running.clear()
                # routing-table sweep: a handle the replica died
                # HOLDING outside its scheduler (mid-export limbo —
                # export_handoff pops before dispatch) is still ours
                # to save
                known = ({id(h) for h in handles}
                         | {id(it[0]) for it in items} | relet_ids)
                for entry in list(self._requests.values()):
                    h = entry.get("handle")
                    if (h is not None and not h.done
                            and entry.get("at") == r.name
                            and id(h) not in known):
                        handles.append(h)
                for h in handles:
                    h._epoch += 1
                    h.slot = None
                    if h._span_queue is not None:
                        r.engine.tracer.end(h._span_queue,
                                            dead_replica=True)
                        h._span_queue = None
                    if h._span is not None:
                        r.engine.tracer.end(h._span, dead_replica=True,
                                            finish="replica_dead")
                        h._span = None
                # rebuild the dead engine pristine: open leases die
                # with the pools, pages/slots all return, so the
                # quarantined replica's leak receipt reads CLEAN
                r.engine._recover(exc=r.error)
        else:
            # fence FIRST (GIL-atomic attribute set): if the wedged
            # step ever unsticks, the next statement it reaches bails
            # out instead of emitting tokens for handles a survivor
            # now owns
            r.engine._fenced = True
            items = []
            while True:
                try:
                    items.append(r.pending_imports.popleft())
                except IndexError:
                    break
            item_ids = {id(it[0]) for it in items} | relet_ids
            handles = []
            for entry in list(self._requests.values()):
                h = entry.get("handle")
                if (h is not None and not h.done
                        and entry.get("at") == r.name
                        and id(h) not in item_ids):
                    handles.append(h)
            for h in handles:
                h._epoch += 1
                h._span = None
                h._span_queue = None
        live = []
        for h in handles:
            if h.done:
                continue
            if (h.state is not RequestState.WAITING
                    or h.slot is not None or h.prefill_pos):
                h._requeue_for_resume()
            live.append(h)
        for it in items:
            # epoch fence for the inbox items too: a wedged thread that
            # later unsticks must never act on them
            it[0]._epoch += 1
        return live, items

    def _redispatch(self, handles, items, dead=None) -> int:
        """Exactly-once re-dispatch: every harvested handle resumes by
        re-prefill on a survivor (``pending`` = prompt + everything
        already delivered, so replayed context is never re-emitted and
        the deterministic per-request RNG regenerates the identical
        continuation); harvested hand-off items keep their pages and
        just move inboxes."""
        n = 0
        for h in handles:
            if h.done:
                continue
            rid = h.request.rid
            entry = self._requests.setdefault(rid, {"handle": h})
            try:
                if (dead is not None and entry.get("prefill") == dead
                        and len(self.prefill_router)):
                    entry["prefill"] = self.prefill_router.pick(
                        self._load_of)
                    target = self._by_name[entry["prefill"]]
                else:
                    entry["decode"] = self.router.pick(
                        self._load_of, session=entry.get("session"))
                    target = self._by_name[entry["decode"]]
            except (RuntimeError, KeyError):
                h.state = RequestState.FAILED
                h.finish_reason = FinishReason.ABORTED
                h.finish_time = self.clock()
                _recorder().note("fleet_redispatch_failed", rid=rid)
                continue
            with target.lock:
                target.engine.resubmit(h)
            entry["at"] = target.name
            n += 1
            _global_registry().counter("fleet.redispatched").inc()
            _recorder().note("fleet_redispatch", rid=rid,
                             to=target.name,
                             replayed=len(h.output_tokens))
        for item in items:
            h, blob, tok = item
            rid = h.request.rid
            entry = self._requests.setdefault(rid, {"handle": h})
            try:
                entry["decode"] = self.router.pick(
                    self._load_of, session=entry.get("session"))
                target = self._by_name[entry["decode"]]
            except (RuntimeError, KeyError):
                if blob.get("lease_id") is not None:
                    self._queue_ack(blob.get("lease_from"),
                                    blob["lease_id"])
                h.state = RequestState.FAILED
                h.finish_reason = FinishReason.ABORTED
                h.finish_time = self.clock()
                _recorder().note("fleet_redispatch_failed", rid=rid,
                                 handoff=True)
                continue
            with target.lock:
                target.pending_imports.append(item)
            entry["at"] = target.name
            n += 1
            _global_registry().counter("fleet.redispatched").inc()
            _recorder().note("fleet_redispatch", rid=rid,
                             to=target.name, handoff=True)
        return n

    def _service_relets(self) -> bool:
        """Re-export leased pages whose first copy was lost in transit
        (corrupt blob, adopter died between export and import). The
        exporter retained the pages precisely for this; if the exporter
        itself is gone, fall back to resume-by-re-prefill."""
        worked = False
        while True:
            try:
                pname, lease_id, handle, tok = self._relets.popleft()
            except IndexError:
                break
            p = self._by_name.get(pname)
            blob = None
            if p is not None:
                with p.lock:
                    try:
                        blob = p.engine.reexport_handoff(lease_id)
                    except KeyError:
                        blob = None
            if blob is None:
                _recorder().note("fleet_relet_lost", lease=lease_id,
                                 rid=handle.request.rid,
                                 exporter=pname)
                handle._requeue_for_resume()
                self._redispatch([handle], [])
            else:
                blob["lease_from"] = pname
                _global_registry().counter("fleet.handoff.relet").inc()
                with self._inflight_lock:
                    self._inflight += 1
                self._dispatch_handoff((handle, blob, tok))
            worked = True
        return worked

    def drain(self, timeout_s=300.0, poll_s=0.002) -> dict:
        """Block until every submitted request finished (threaded
        mode), then return the fleet snapshot."""
        deadline = self.clock() + float(timeout_s)
        while self.has_work():
            # the watchdog tick runs BEFORE the error scan: with a
            # watchdog, a failed replica is quarantined (requests
            # re-dispatched) instead of failing the drain; without
            # one the tick no-ops and errors raise as before
            self._watchdog_tick()
            self._raise_replica_errors()
            self._service_relets()
            if self.autoscaler is not None:
                self.autoscaler.tick()
            self._finalize_drained()
            if self.clock() > deadline:
                raise RuntimeError(
                    f"fleet did not drain within {timeout_s}s: "
                    f"{ {r.name: r.load for r in self._replicas} }")
            time.sleep(poll_s)
        self._watchdog_tick()
        self._raise_replica_errors()
        # quiesce before the snapshot: has_work() can go false while a
        # replica thread is still INSIDE the step() that retired the
        # last request (counters/handle flags not yet published —
        # observed as a 47/48 finished reading); every step runs under
        # the replica lock, so taking each lock once guarantees the
        # final step completed before we read
        for r in list(self._replicas):
            with r.lock:
                pass
        self._finalize_drained()
        return self.metrics_snapshot()

    def _raise_replica_errors(self):
        for r in list(self._replicas):
            if r.error is not None:
                raise RuntimeError(
                    f"replica {r.name} failed") from r.error

    def stop(self, strict: bool = False) -> dict:
        """Stop every replica thread. A thread that fails to join
        within ``join_timeout_s`` is RECORDED (``fleet.replica.hung``
        counter, flight-recorder event, event log) instead of silently
        ignored; ``strict=True`` escalates to a raise."""
        for r in list(self._replicas) + list(self._quarantined):
            r.stop = True
        for r in list(self._replicas) + list(self._quarantined):
            self._join_or_record(r)
        self._started = False
        self._finalize_drained()
        hung = list(self._hung)
        if strict and hung:
            raise RuntimeError(
                f"replica thread(s) failed to join within "
                f"{self.join_timeout_s}s: {hung}")
        return {"hung_replicas": hung}

    def _join_or_record(self, r: _Replica) -> bool:
        """Join one replica thread with the configured timeout; a hung
        join is surfaced, never swallowed. True = thread is gone."""
        t = r.thread
        if t is None or t is threading.current_thread():
            return True
        t.join(timeout=self.join_timeout_s)
        if t.is_alive():
            if r.name not in self._hung:
                self._hung.append(r.name)
                _global_registry().counter("fleet.replica.hung").inc()
                _recorder().note("fleet_replica_hung", replica=r.name,
                                 timeout_s=self.join_timeout_s)
                self.events.append({"action": "replica_hung",
                                    "replica": r.name,
                                    "timeout_s": self.join_timeout_s})
            return False
        r.thread = None
        return True

    def _paused(self):
        """Ordered acquisition of every replica lock — quiesces all
        dispatch so a spawn's warmup traces alone. Returns the lock
        list; caller releases in reverse."""
        locks = [r.lock for r in list(self._replicas)]
        for lk in locks:
            lk.acquire()
        return locks

    # -- elasticity -------------------------------------------------------
    def scale_up(self, reason="manual", burn=None) -> _Replica:
        """Spawn, warm and enlist one decode replica. Fleet-paused for
        the warmup in threaded mode (fresh traces never race live
        dispatches); the cold-start receipt lands in the event log."""
        locks = self._paused() if self.threaded else []
        try:
            r = self._spawn_replica("decode", warm=True)
            self._add_replica(r)
        finally:
            for lk in reversed(locks):
                lk.release()
        self.events.append({"action": "scale_up", "replica": r.name,
                            "reason": reason, "burn": burn,
                            "decode_replicas": len(
                                self.decode_replicas()),
                            **(r.spawn_report or {})})
        return r

    def scale_down(self, name=None, reason="manual", burn=None):
        """Mark one decode replica draining: routers stop sending it
        work (rendezvous remaps only its ~1/N sessions), resident
        requests finish in place, and the drained replica retires with
        its leak receipt in the event log."""
        cands = self.decode_replicas()
        if len(cands) <= 1:
            raise RuntimeError("cannot scale below one decode replica")
        if name is None:
            # least loaded, newest first: the cheapest drain
            r = min(reversed(cands), key=lambda c: c.load)
        else:
            r = self._by_name[name]
        r.draining = True
        self.router.remove(r.name)
        # a DELIBERATE shrink lowers the brown-out baseline — only
        # unplanned capacity loss (quarantine) should trip shedding
        self._nominal_decode = max(1, len(self.decode_replicas()))
        self.events.append({"action": "scale_down", "replica": r.name,
                            "reason": reason, "burn": burn,
                            "decode_replicas": len(
                                self.decode_replicas())})
        return r

    def _finalize_drained(self):
        for r in [x for x in self._replicas if x.draining]:
            with r.lock:
                busy = (r.engine.scheduler.has_work()
                        or r.pending_imports or r.pending_acks
                        or r.engine.leased_count)
            if busy:
                continue
            r.stop = True
            if not self._join_or_record(r):
                # hung drain: the replica is NOT silently retired — it
                # stays visible (and recorded) until the thread exits
                continue
            self._replicas.remove(r)
            self._retired.append(r)
            self._by_name.pop(r.name, None)
            self.events.append({
                "action": "retired", "replica": r.name,
                "leak_check": r.engine.leak_check(),
                "open_spans": len(r.engine.tracer.open_spans()),
            })

    # -- observability ----------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """Fleet-level rollup: per-replica snapshots plus MERGED-sample
        percentiles (a fleet p99 is the p99 of the union of samples —
        never an average of per-replica p99s)."""
        reps = (list(self._replicas) + list(self._retired)
                + list(self._quarantined))
        per = {r.name: r.engine.metrics_snapshot() for r in reps}
        ttft = merge_histograms(
            [r.engine.metrics.ttft_s for r in reps], name="fleet.ttft_s")
        itl = merge_histograms(
            [r.engine.metrics.itl_s for r in reps], name="fleet.itl_s")
        out = {
            "replicas": per,
            "decode_replicas": len(self.decode_replicas()),
            "prefill_replicas": len(self.prefill_replicas()),
            "retired_replicas": len(self._retired),
            "quarantined_replicas": [x.name for x in self._quarantined],
            "hung_replicas": list(self._hung),
            "recoveries": list(self.recoveries),
            "fleet_ttft_p50_s": ttft.percentile(50),
            "fleet_ttft_p99_s": ttft.percentile(99),
            "fleet_itl_p50_s": itl.percentile(50),
            "fleet_itl_p99_s": itl.percentile(99),
            "events": list(self.events),
        }
        for key in ("submitted", "finished", "generated_tokens",
                    "preemptions", "kv_evictions", "kv_onloads",
                    "prefill_chunks", "decode_steps"):
            out[f"fleet_{key}"] = sum(p.get(key, 0)
                                      for p in per.values())
        # per-replica KV-pool rollup (ISSUE 20): quant level, true
        # packed bytes/token and the capacity multiple vs bf16 — the
        # capacity story a fleet operator sizes replicas by
        pools = {}
        for r in reps:
            ps = r.engine.cache.pool_stats()
            pools[r.name] = {k: ps[k] for k in
                             ("kv_dtype", "bytes_per_token",
                              "effective_slots_vs_bf16", "occupancy",
                              "free_pages", "total_pages") if k in ps}
        out["replica_pools"] = pools
        if self.host_ring is not None:
            out["host_ring"] = self.host_ring.stats()
        return out

    def request_trace(self, rid: int) -> list:
        """Every replica's completed leg of one request, stitched by
        the shared ``req<rid>`` track and ordered by start time —
        disaggregated requests show a prefill leg (closed with
        ``handoff=True``) followed by a decode leg."""
        legs = []
        for r in (list(self._replicas) + list(self._retired)
                  + list(self._quarantined)):
            root = r.engine.tracer.find_trace(f"req{rid}")
            if root is not None:
                legs.append({"replica": r.name, "role": r.role,
                             "root": root})
        legs.sort(key=lambda leg: leg["root"].t0)
        return legs

    def leak_check(self) -> dict:
        """Fleet-wide invariant surface: pool conservation and span
        hygiene on EVERY replica (live and retired) plus the host
        ring. After a drain, ``clean`` must be True: all pages/slots
        free, no open or orphaned spans, ring empty."""
        out = {"replicas": {}, "clean": True}
        for r in list(self._replicas) + list(self._retired):
            leaks = r.engine.leak_check()
            stats = r.engine.cache.pool_stats()
            rep = {
                **leaks,
                "pool_conserved": (stats["used_pages"]
                                   + stats["free_pages"]
                                   == stats["total_pages"]),
                "open_spans": len(r.engine.tracer.open_spans()),
                "orphan_spans": len(r.engine.tracer.orphans()),
                "pending_imports": len(r.pending_imports),
            }
            rep["clean"] = (
                leaks["free_pages"] == leaks["total_pages"]
                and leaks["free_slots"] == leaks["total_slots"]
                and leaks["resident_slot_pages"] == 0
                and rep["pool_conserved"] and rep["open_spans"] == 0
                and rep["orphan_spans"] == 0
                and rep["pending_imports"] == 0)
            out["replicas"][r.name] = rep
            out["clean"] = out["clean"] and rep["clean"]
        for r in list(self._quarantined):
            leaks = r.engine.leak_check()
            stats = r.engine.cache.pool_stats()
            rep = {
                **leaks,
                "quarantined": True,
                "cause": r.cause,
                "safe_harvest": r.harvest_safe,
                "pool_conserved": (stats["used_pages"]
                                   + stats["free_pages"]
                                   == stats["total_pages"]),
                "open_spans": len(r.engine.tracer.open_spans()),
                "orphan_spans": len(r.engine.tracer.orphans()),
                "pending_imports": len(r.pending_imports),
            }
            if r.harvest_safe:
                # safe harvest ran _recover: the quarantined replica
                # must be as clean as a retired one
                rep["clean"] = (
                    leaks["free_pages"] == leaks["total_pages"]
                    and leaks["free_slots"] == leaks["total_slots"]
                    and leaks["resident_slot_pages"] == 0
                    and leaks.get("leased_slots", 0) == 0
                    and rep["pool_conserved"] and rep["open_spans"] == 0
                    and rep["orphan_spans"] == 0
                    and rep["pending_imports"] == 0)
                out["clean"] = out["clean"] and rep["clean"]
            else:
                # a wedged thread may still hold resources: reported,
                # but exempt from the fleet-wide clean fold (nothing it
                # holds is reachable by live traffic)
                rep["clean"] = None
            out["replicas"][r.name] = rep
        if self.host_ring is not None:
            ring = self.host_ring.stats()
            out["host_ring"] = ring
            out["clean"] = (out["clean"] and ring["entries"] == 0
                            and ring["bytes"] == 0)
        return out

    def retrace_stats(self) -> dict:
        return {r.name: r.engine.retrace_stats()
                for r in (list(self._replicas) + list(self._retired)
                          + list(self._quarantined))}


class SLOBurnAutoscaler:
    """Decode-set elasticity from SLO burn rate (ISSUE 18).

    ``tick()`` samples the WORST burn rate across decode replicas'
    declared SLOs (the fleet's engines carry the ISSUE-13 rolling
    windows). A streak of ``hysteresis`` hot evaluations
    (burn >= burn_up) grows the set; a streak of cold ones
    (burn <= burn_down) shrinks it; anything between resets both
    streaks. After any action the controller holds for ``cooldown_s``.
    Burn rate — violations spent against the error budget — is the
    actuation signal precisely because raw QPS lies in both
    directions: high QPS with met SLOs needs no replica, and low QPS
    with a pathological workload (one giant prompt) still burns."""

    def __init__(self, fleet, min_decode=1, max_decode=4, burn_up=1.0,
                 burn_down=0.25, hysteresis=2, cooldown_s=0.5,
                 interval_s=0.05):
        self.fleet = fleet
        self.min_decode = max(1, int(min_decode))
        self.max_decode = int(max_decode)
        self.burn_up = float(burn_up)
        self.burn_down = float(burn_down)
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._next_eval = None
        self._hold_until = None
        self._up_streak = 0
        self._down_streak = 0
        self.evaluations = 0

    def burn(self) -> float:
        worst = 0.0
        for r in self.fleet.decode_replicas():
            for st in r.engine.slo.snapshot().values():
                worst = max(worst, float(st.get("burn_rate", 0.0)))
        return worst

    def tick(self):
        with self._lock:
            now = self.fleet.clock()
            if self._next_eval is not None and now < self._next_eval:
                return
            self._next_eval = now + self.interval_s
            self.evaluations += 1
            if self._hold_until is not None and now < self._hold_until:
                return
            b = self.burn()
            n = len(self.fleet.decode_replicas())
            if b >= self.burn_up and n < self.max_decode:
                self._up_streak += 1
                self._down_streak = 0
                if self._up_streak >= self.hysteresis:
                    self._up_streak = self._down_streak = 0
                    self._hold_until = now + self.cooldown_s
                    self.fleet.scale_up(reason="slo_burn", burn=b)
            elif b <= self.burn_down and n > self.min_decode:
                self._down_streak += 1
                self._up_streak = 0
                if self._down_streak >= self.hysteresis:
                    self._up_streak = self._down_streak = 0
                    self._hold_until = now + self.cooldown_s
                    self.fleet.scale_down(reason="slo_burn", burn=b)
            else:
                self._up_streak = self._down_streak = 0
