"""Ragged paged decode attention — Pallas TPU kernel + XLA gather fallback.

The decode-step kernel of the serving stack (PAPERS.md "Ragged Paged
Attention"): each sequence's KV history lives in fixed-size pages drawn
from a shared pool, a per-sequence page table maps logical positions to
pages, and per-sequence lengths are ragged — so a mixed batch of short
and long contexts shares one static-shape kernel with no padding to the
longest sequence's history.

Layouts (one transformer layer):

* ``k_pages`` / ``v_pages``: ``[num_kv_heads, num_pages, page_size,
  head_dim]`` — the shared pool. Page 0 is conventionally the trash
  page (ragged writes of padding tokens land there; see
  inference/kv_cache.py).
* ``page_tables``: ``[batch, pages_per_seq] int32`` — pool page ids per
  sequence slot, position ``t`` of slot ``b`` lives in page
  ``page_tables[b, t // page_size]`` at offset ``t % page_size``.
* ``seq_lens``: ``[batch] int32`` — valid keys per slot (ragged).
* ``q``: ``[batch, num_heads, head_dim]`` — ONE new token per slot (the
  decode step). GQA is supported (``num_heads`` a multiple of
  ``num_kv_heads``).

Two paths, one contract:

* **Pallas kernel** (TPU): grid ``(batch, kv_head, page)`` with the page
  table and seq_lens scalar-prefetched, so each grid step DMAs exactly
  one page of K/V picked by the table — the pool itself never streams
  densely. Pages past a slot's length are skipped (``pl.when``), which
  is where the ragged win comes from: compute per slot is proportional
  to its own context length, not the batch max.
* **XLA fallback** (CPU / legacy jax): one gather densifies each slot's
  pages to ``[batch, pages_per_seq * page_size, ...]`` followed by a
  masked attention. Same numerics, used for parity tests and
  non-TPU runs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .flash_attention import (  # noqa: F401  (shared platform probes)
    _HAS_PALLAS, _LANES, _on_tpu, pl, pltpu,
)

__all__ = ["paged_attention", "paged_attention_xla", "supports"]


def supports(num_heads, num_kv_heads, head_dim, page_size) -> bool:
    """Whether the Pallas kernel can take this cache geometry."""
    if not _HAS_PALLAS:
        return False
    if num_heads % num_kv_heads:
        return False
    if head_dim > 256:
        return False
    # Mosaic pads sublane/lane tiles from 8/16 upward; tiny pages would
    # waste most of each tile anyway
    return page_size % 8 == 0


# ---------------------------------------------------------------------------
# XLA gather fallback
# ---------------------------------------------------------------------------

def paged_attention_xla(q, k_pages, v_pages, page_tables, seq_lens,
                        scale=None):
    """Reference-parity path: densify via gather, mask, one attention."""
    b, nh, d = q.shape
    kvh, _, page_size, _ = k_pages.shape
    grp = nh // kvh
    pp = page_tables.shape[1]
    sc = scale if scale is not None else 1.0 / (d ** 0.5)

    # [kvh, b, pp, ps, d] -> [b, kvh, pp*ps, d]
    def densify(pages):
        g = jnp.take(pages, page_tables, axis=1)
        return jnp.moveaxis(g, 0, 1).reshape(b, kvh, pp * page_size, d)

    k = densify(k_pages)
    v = densify(v_pages)
    qg = q.reshape(b, kvh, grp, d)
    s = jnp.einsum("bhgd,bhkd->bhgk", qg.astype(jnp.float32),
                   k.astype(jnp.float32)) * sc
    valid = (jnp.arange(pp * page_size)[None, :]
             < seq_lens[:, None])                      # [b, L]
    s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    # all-masked rows (empty slots): zero output, not NaN
    p = jnp.where(valid[:, None, None, :].any(-1, keepdims=True), p, 0.0)
    out = jnp.einsum("bhgk,bhkd->bhgd", p, v.astype(jnp.float32))
    return out.reshape(b, nh, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: grid (batch, kv_head, page), scalar-prefetched page table
# ---------------------------------------------------------------------------

def _decode_kernel(pt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, page_size):
    b = pl.program_id(0)
    p = pl.program_id(2)
    num_p = pl.num_programs(2)
    sl = sl_ref[b]

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(p * page_size < sl)
    def _step():
        q = q_ref[0, 0]                                  # [grp, d]
        k = k_ref[0, 0]                                  # [ps, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [grp, ps]
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < sl, s, -jnp.inf)
        m_prev = m_ref[...]                              # [grp, LANES]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        corr = jnp.exp(m_prev - m_new)
        e = jnp.exp(s - m_new[:, :1])
        l_ref[...] = corr * l_prev + jnp.broadcast_to(
            jnp.sum(e, axis=1, keepdims=True), l_prev.shape)
        m_ref[...] = m_new
        pv = jax.lax.dot_general(
            e.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [grp, d]
        acc_ref[...] = acc_ref[...] * corr[:, :1] + pv

    @pl.when(p == num_p - 1)
    def _finish():
        l = l_ref[...][:, :1]
        l = jnp.where(l == 0.0, 1.0, l)   # empty slot -> zeros, not NaN
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_pages, v_pages, page_tables, seq_lens,
                            scale, interpret):
    b, nh, d = q.shape
    kvh, _, page_size, _ = k_pages.shape
    grp = nh // kvh
    pp = page_tables.shape[1]
    qg = q.reshape(b, kvh, grp, d)
    flat_pt = page_tables.reshape(-1).astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # page table + seq_lens
        grid=(b, kvh, pp),
        in_specs=[
            pl.BlockSpec((1, 1, grp, d),
                         lambda bb, h, p, pt, sl: (bb, h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda bb, h, p, pt, sl: (h, pt[bb * pp + p],
                                                   0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda bb, h, p, pt, sl: (h, pt[bb * pp + p],
                                                   0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, grp, d),
                               lambda bb, h, p, pt, sl: (bb, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((grp, d), jnp.float32),
            pltpu.VMEM((grp, _LANES), jnp.float32),
            pltpu.VMEM((grp, _LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale,
                          page_size=page_size),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, grp, d), q.dtype),
        interpret=interpret,
    )(flat_pt, seq_lens.astype(jnp.int32), qg, k_pages, v_pages)
    return out.reshape(b, nh, d)


def paged_attention(q, k_pages, v_pages, page_tables, seq_lens,
                    scale=None, interpret=None, use_kernel=None):
    """Ragged paged decode attention (see module docstring for layouts).

    Routes to the Pallas kernel on TPU when the geometry qualifies
    (`supports`), the XLA gather fallback otherwise. `interpret=True`
    forces the kernel in interpret mode (hermetic CPU testing);
    `use_kernel` overrides the routing outright.
    """
    b, nh, d = q.shape
    kvh, _, page_size, _ = k_pages.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    ok = supports(nh, kvh, d, page_size)
    if use_kernel is None:
        use_kernel = ok and (interpret is True or _on_tpu())
    if use_kernel and not ok:
        raise ValueError(
            f"paged_attention kernel does not support heads={nh}/"
            f"kv_heads={kvh}, head_dim={d}, page_size={page_size}")
    if use_kernel:
        return _paged_attention_pallas(
            q, k_pages, v_pages, page_tables, seq_lens, float(scale),
            bool(interpret) if interpret is not None else not _on_tpu())
    return paged_attention_xla(q, k_pages, v_pages, page_tables,
                               seq_lens, scale=float(scale))
