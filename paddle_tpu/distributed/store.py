"""TCPStore — control-plane KV rendezvous.

Reference parity: TCPStore (paddle/phi/core/distributed/store/tcp_store.h)
+ create_or_get_global_tcp_store (python/paddle/distributed/parallel.py:1134).
Backed by the native server/client (csrc/tcp_store.cpp, ctypes-loaded,
lazily built with g++); a pure-Python socket fallback keeps rendezvous
working without a toolchain. wait/get block CLIENT-side with retries — the
server never blocks on a rank (watchdog-friendly, SURVEY.md §5.3).
"""
from __future__ import annotations

import ctypes
import os
import socket
import subprocess
import threading
import time

_SO_PATH = os.path.join(os.path.dirname(__file__), "_tcp_store.so")
_SRC = os.path.join(os.path.dirname(__file__), os.pardir, "csrc",
                    "tcp_store.cpp")
_lock = threading.Lock()
_lib = None
_lib_tried = False


def _load_lib():
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        stale = (os.path.exists(_SO_PATH) and os.path.exists(_SRC)
                 and os.path.getmtime(_SRC) > os.path.getmtime(_SO_PATH))
        if stale:
            try:
                os.remove(_SO_PATH)
            except OSError:
                pass
        if not os.path.exists(_SO_PATH):
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-o", _SO_PATH, os.path.abspath(_SRC), "-lpthread"],
                    check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.tcp_store_server_start.restype = ctypes.c_void_p
        lib.tcp_store_server_start.argtypes = [ctypes.c_int]
        lib.tcp_store_server_port.restype = ctypes.c_int
        lib.tcp_store_server_port.argtypes = [ctypes.c_void_p]
        lib.tcp_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.tcp_store_set.restype = ctypes.c_int
        lib.tcp_store_set.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int]
        lib.tcp_store_get.restype = ctypes.c_int64
        lib.tcp_store_get.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.tcp_store_add.restype = ctypes.c_int
        lib.tcp_store_add.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_lib() is not None


class _PyStoreServer:
    """Pure-Python fallback server (same wire-level semantics, dict+lock)."""

    def __init__(self, port=0):
        self._kv = {}
        self._mu = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self._srv.listen(128)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self._th = threading.Thread(target=self._serve, daemon=True)
        self._th.start()

    def _serve(self):
        self._srv.settimeout(0.1)
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            threading.Thread(target=self._client, args=(conn,),
                             daemon=True).start()
        self._srv.close()

    def _client(self, conn):
        import struct

        def read_n(n):
            buf = b""
            while len(buf) < n:
                c = conn.recv(n - len(buf))
                if not c:
                    raise ConnectionError
                buf += c
            return buf

        try:
            while True:
                cmd = read_n(1)[0]
                klen = struct.unpack("<I", read_n(4))[0]
                key = read_n(klen).decode()
                if cmd == 1:
                    vlen = struct.unpack("<I", read_n(4))[0]
                    val = read_n(vlen)
                    with self._mu:
                        self._kv[key] = val
                    conn.sendall(b"\x00" + struct.pack("<I", 0))
                elif cmd == 2:
                    with self._mu:
                        val = self._kv.get(key)
                    if val is None:
                        conn.sendall(b"\x01" + struct.pack("<I", 0))
                    else:
                        conn.sendall(b"\x00" + struct.pack("<I", len(val))
                                     + val)
                elif cmd == 3:
                    delta = struct.unpack("<q", read_n(8))[0]
                    with self._mu:
                        cur = struct.unpack(
                            "<q", self._kv.get(key, b"\0" * 8))[0] + delta
                        self._kv[key] = struct.pack("<q", cur)
                    conn.sendall(b"\x00" + struct.pack("<I", 8)
                                 + struct.pack("<q", cur))
                elif cmd == 4:
                    with self._mu:
                        self._kv.pop(key, None)
                    conn.sendall(b"\x00" + struct.pack("<I", 0))
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True


class _PyStoreClient:
    def __init__(self, host, port, timeout):
        self.host, self.port, self.timeout = host, port, timeout

    def _roundtrip(self, payload):
        import struct

        # connect failures (pre-send, safe to retry) surface as
        # ConnectionError; anything after the request may have been
        # APPLIED server-side, so it must NOT look retryable to
        # _client_retry (non-idempotent add) — re-raise as RuntimeError
        try:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
        except OSError as e:
            raise ConnectionError(f"store connect failed: {e}") from e
        try:
            s.sendall(payload)
            hdr = b""
            while len(hdr) < 5:
                hdr += s.recv(5 - len(hdr))
            status = hdr[0]
            vlen = struct.unpack("<I", hdr[1:5])[0]
            val = b""
            while len(val) < vlen:
                val += s.recv(vlen - len(val))
            return status, val
        except OSError as e:
            raise RuntimeError(f"store roundtrip failed mid-stream: {e}") \
                from e
        finally:
            s.close()

    def set(self, key, val):
        import struct

        k = key.encode()
        st, _ = self._roundtrip(b"\x01" + struct.pack("<I", len(k)) + k
                                + struct.pack("<I", len(val)) + val)
        if st != 0:
            raise RuntimeError("store set failed")

    def get_once(self, key):
        import struct

        k = key.encode()
        st, val = self._roundtrip(b"\x02" + struct.pack("<I", len(k)) + k)
        return None if st == 1 else val

    def add(self, key, delta):
        import struct

        k = key.encode()
        st, val = self._roundtrip(b"\x03" + struct.pack("<I", len(k)) + k
                                  + struct.pack("<q", delta))
        if st != 0 or len(val) != 8:
            raise RuntimeError("store add failed")
        return struct.unpack("<q", val)[0]


class TCPStore:
    """Reference TCPStore API: master hosts, everyone set/get/add/waits."""

    def __init__(self, host: str, port: int, world_size: int = 1,
                 is_master: bool = False, timeout: float = 300.0):
        self.host = host
        self.world_size = world_size
        self.is_master = is_master
        self.timeout = timeout
        self._server = None
        lib = _load_lib()
        self._native = lib is not None
        if is_master:
            if self._native:
                self._server = lib.tcp_store_server_start(port)
                if not self._server:
                    raise OSError(f"TCPStore bind :{port} failed")
                self.port = lib.tcp_store_server_port(self._server)
            else:
                self._py_server = _PyStoreServer(port)
                self.port = self._py_server.port
        else:
            self.port = port
        if not self._native:
            self._py_client = _PyStoreClient(host, self.port, timeout)
        self._resolved = socket.gethostbyname(host)

    # -- API ------------------------------------------------------------
    def _client_retry(self, fn, what):
        """Retry fn until the store's master is up (ranks race the
        master's bind at startup — reference TCPStore clients block in
        connect the same way) or self.timeout elapses. ONLY pre-send
        connect failures (ConnectionError) retry: a lost RESPONSE after
        the server applied a non-idempotent add must not re-apply."""
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                return fn()
            except ConnectionError:
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"store {what}: master never came up within "
                        f"{self.timeout}s")
                time.sleep(0.2)

    def set(self, key: str, value: bytes):
        value = value if isinstance(value, bytes) else str(value).encode()

        def go():
            if self._native:
                rc = _lib.tcp_store_set(self._resolved.encode(), self.port,
                                        key.encode(), value, len(value),
                                        int(self.timeout * 1000))
                if rc == -2:
                    raise ConnectionError(f"store set({key!r}) connect")
                if rc != 0:
                    raise RuntimeError(f"store set({key!r}) failed")
            else:
                self._py_client.set(key, value)

        self._client_retry(go, f"set({key!r})")

    def _get_once(self, key: str):
        if self._native:
            # reused per-instance buffer: get() and the watcher poll this
            # in tight loops, so per-call 64MB allocations would churn;
            # grow only when a value overflows (tcp_store_get returns the
            # full length even when truncating). The buffer is shared, so
            # concurrent pollers (rpc server + waiter threads) serialize
            # on a lock — ctypes calls drop the GIL, and an interleaved
            # overwrite would hand one thread another's payload.
            lock = getattr(self, "_get_lock", None)
            if lock is None:
                import threading as _threading

                lock = self._get_lock = _threading.Lock()
            with lock:
                return self._get_once_locked(key)
        return self._py_client.get_once(key)

    def _get_once_locked(self, key: str):
        buf = getattr(self, "_get_buf", None)
        if buf is None:
            buf = self._get_buf = ctypes.create_string_buffer(1 << 16)
        n = _lib.tcp_store_get(self._resolved.encode(), self.port,
                               key.encode(), buf, len(buf),
                               int(self.timeout * 1000))
        if n > len(buf):
            buf = self._get_buf = ctypes.create_string_buffer(int(n))
            n = _lib.tcp_store_get(self._resolved.encode(), self.port,
                                   key.encode(), buf, len(buf),
                                   int(self.timeout * 1000))
        if n == -2:
            raise ConnectionError(f"store get({key!r}) connect failed")
        return None if n < 0 else buf.raw[:n]

    def get(self, key: str) -> bytes:
        """Blocks (client-side retry) until the key exists or timeout."""
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                val = self._get_once(key)
            except ConnectionError:
                val = None
            if val is not None:
                return val
            if time.monotonic() >= deadline:
                raise TimeoutError(f"store get({key!r}) timed out")
            time.sleep(0.05)

    def add(self, key: str, delta: int = 1) -> int:
        def go():
            if self._native:
                out = ctypes.c_int64(0)
                rc = _lib.tcp_store_add(self._resolved.encode(), self.port,
                                        key.encode(), delta,
                                        ctypes.byref(out),
                                        int(self.timeout * 1000))
                if rc == -2:
                    raise ConnectionError(f"store add({key!r}) connect")
                if rc != 0:
                    raise RuntimeError(f"store add({key!r}) failed")
                return out.value
            return self._py_client.add(key, delta)

        return self._client_retry(go, f"add({key!r})")

    def wait(self, keys, timeout: float = None):
        deadline = time.monotonic() + (timeout or self.timeout)
        for key in ([keys] if isinstance(keys, str) else keys):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"store wait({key!r}) timed out")
            saved = self.timeout
            self.timeout = remaining
            try:
                self.get(key)
            finally:
                self.timeout = saved

    def shutdown(self):
        if self._server is not None and _lib is not None:
            _lib.tcp_store_server_stop(self._server)
            self._server = None
        if getattr(self, "_py_server", None) is not None:
            self._py_server.stop()


_global_store = None


def create_or_get_global_tcp_store() -> TCPStore:
    """Reference parallel.py:1134 — one store per job, master on rank 0."""
    global _global_store
    if _global_store is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = int(os.environ.get("MASTER_PORT", "0") or 0)
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        _global_store = TCPStore(addr, port, world_size=world,
                                 is_master=(rank == 0))
    return _global_store
