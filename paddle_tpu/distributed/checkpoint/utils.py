"""Checkpoint helpers: state-dict flattening + array normalization.

Reference parity: python/paddle/distributed/checkpoint/utils.py
(flatten_state_dict/unflatten_state_dict). Fault-tolerance additions:
``CheckpointError`` (every corrupt/truncated-read failure surfaces as
this, naming the file and tensor key), durable atomic file writes
(temp + fsync + ``os.replace``), and host snapshots of device arrays so
an async save can hand pickling+IO to a background thread after the
device→host copy — the only part that blocks the train loop.
"""
from __future__ import annotations

import os
import zlib
from typing import Any, Dict, Tuple

import numpy as np

import jax


class CheckpointError(RuntimeError):
    """A checkpoint could not be written or read back intact (truncated
    pickle, checksum mismatch, missing chunk/tensor). The message names
    the offending file — and the tensor key when one is in play —
    instead of surfacing a bare ``UnpicklingError``/``KeyError`` from
    deep inside the reader."""


def _is_leaf(v) -> bool:
    from ...framework.tensor import Tensor

    return isinstance(v, (Tensor, jax.Array, np.ndarray, int, float))


# ---------------------------------------------------------------------------
# durable writes + checksums
# ---------------------------------------------------------------------------

def fsync_write_bytes(path: str, data: bytes) -> Tuple[int, int]:
    """Write ``data`` durably and atomically: same-directory temp file,
    fsync, ``os.replace``. A reader (or a post-crash scan) can observe
    the old file or the new file, never a truncated one. Returns
    ``(crc32, size)`` of the written bytes."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return zlib.crc32(data), len(data)


def fsync_dir(path: str) -> None:
    """Flush directory entries (the renames above) to disk. Best-effort
    on filesystems that reject directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def file_crc32_size(path: str) -> Tuple[int, int]:
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            crc = zlib.crc32(block, crc)
            size += len(block)
    return crc, size


# ---------------------------------------------------------------------------
# host snapshots (async save: device->host now, pickle+IO later)
# ---------------------------------------------------------------------------

class _HostShard:
    """Host copy of one addressable shard (the fields save_state_dict
    reads off a ``jax.Shard``)."""

    __slots__ = ("index", "replica_id", "data")

    def __init__(self, index, replica_id, data):
        self.index = index
        self.replica_id = replica_id
        self.data = data


class HostArraySnapshot:
    """Host-side stand-in for a ``jax.Array`` inside ``save_state_dict``:
    same shape/dtype/addressable_shards surface, numpy payloads. Built
    synchronously by ``snapshot_to_host``; consumed by a background
    writer thread without touching the device again."""

    __slots__ = ("shape", "dtype", "addressable_shards")

    def __init__(self, arr: jax.Array):
        self.shape = tuple(arr.shape)
        self.dtype = arr.dtype
        self.addressable_shards = [
            _HostShard(s.index, s.replica_id, np.asarray(s.data))
            for s in arr.addressable_shards
            if s.replica_id == 0]


def snapshot_to_host(state_dict: Dict) -> Dict:
    """Deep-copy a nested state_dict's device arrays to host snapshots
    (sharding structure preserved — 1/N shards stay 1/N chunks on disk).
    This device→host copy is the only part of an async save that blocks
    the caller."""
    from ...framework.tensor import Tensor

    def walk(v):
        if isinstance(v, dict):
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, Tensor):
            v = v._data
        if isinstance(v, jax.Array):
            return HostArraySnapshot(v)
        if isinstance(v, np.ndarray):
            return np.array(v)
        return v

    return walk(state_dict)


def flatten_state_dict(state_dict: Dict) -> Tuple[Dict[str, Any],
                                                  Dict[str, Tuple[str, ...]]]:
    """Flatten nested dicts to ``"a.b.c" -> value``; returns the flat dict
    plus the mapping back to the original key paths."""
    flat: Dict[str, Any] = {}
    mapping: Dict[str, Tuple[str, ...]] = {}

    def walk(prefix: Tuple[str, ...], obj):
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(prefix + (str(k),), v)
        else:
            key = ".".join(prefix)
            if key in flat:
                raise ValueError(f"duplicate flattened key {key!r}")
            flat[key] = obj
            mapping[key] = prefix
    walk((), state_dict)
    return flat, mapping


def unflatten_state_dict(flat: Dict[str, Any],
                         mapping: Dict[str, Tuple[str, ...]]) -> Dict:
    out: Dict = {}
    for key, value in flat.items():
        path = mapping[key]
        cur = out
        for p in path[:-1]:
            cur = cur.setdefault(p, {})
        cur[path[-1]] = value
    return out


def to_jax_array(v) -> jax.Array:
    from ...framework.tensor import Tensor

    if isinstance(v, Tensor):
        return v._data
    if isinstance(v, (jax.Array, HostArraySnapshot)):
        return v
    import jax.numpy as jnp

    return jnp.asarray(v)


def offsets_of(shard_index, shape) -> Tuple[int, ...]:
    """Global offset of a shard from its index (tuple of slices)."""
    return tuple(
        (sl.start or 0) for sl in shard_index
    ) if shard_index else tuple(0 for _ in shape)


def pack_numpy(arr: np.ndarray):
    """bfloat16-safe numpy payload (raw uint16 view)."""
    name = arr.dtype.name if hasattr(arr.dtype, "name") else str(arr.dtype)
    if name == "bfloat16":
        return {"dtype": "bfloat16", "raw": np.asarray(arr).view(np.uint16)}
    return {"dtype": name, "raw": np.asarray(arr)}


def unpack_numpy(payload) -> np.ndarray:
    if payload["dtype"] == "bfloat16":
        import ml_dtypes

        return payload["raw"].view(ml_dtypes.bfloat16)
    return payload["raw"]
