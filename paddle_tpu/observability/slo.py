"""SLO tracking: declared objectives with rolling-window burn rates.

An SLO here is "at least ``target`` of events keep ``metric`` ≤
``threshold``" (e.g. "99% of requests get TTFT ≤ 250 ms") evaluated
over a rolling time window. Each ``observe`` is O(1): the sample
becomes a (timestamp, ok) pair in a bounded window deque; everything
derived — good fraction, burn rate, breach flag — is computed lazily at
scrape time from the samples still inside the window.

**Burn-rate semantics** (the standard SRE definition): the error budget
is ``1 - target`` (the fraction of events ALLOWED to violate). The burn
rate is ``bad_fraction / (1 - target)`` over the window — 1.0 means the
budget is being consumed exactly at the sustainable rate, >1 means the
objective will be violated if the window's behavior continues, and the
``breaching`` flag is simply ``good_fraction < target`` (budget already
overdrawn inside this window). A window with no samples reports burn
rate 0 and not-breaching (no traffic is not an outage).

Gauges (lazy, scrape-time only) land on the bound registry as
``slo.<name>.good_fraction`` / ``slo.<name>.burn_rate`` /
``slo.<name>.breaching`` — so a Prometheus scrape of the serving
engine's registry carries burn rates next to the latency summaries.
"""
from __future__ import annotations

import collections
import threading
import time

from .registry import registry as _registry

__all__ = ["SLO", "SLOTracker"]


class SLO:
    """One declared objective: ``metric`` ≤ ``threshold`` for at least
    ``target`` of events over a rolling ``window_s`` window."""

    __slots__ = ("name", "metric", "threshold", "target", "window_s",
                 "description", "_window", "_lock", "_memo",
                 "total_observed", "total_bad")

    def __init__(self, name, metric, threshold, target=0.99,
                 window_s=60.0, description="", max_samples=65536):
        if not (0.0 < target < 1.0):
            raise ValueError(f"target must be in (0, 1), got {target}")
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.name = name
        self.metric = metric
        self.threshold = float(threshold)
        self.target = float(target)
        self.window_s = float(window_s)
        self.description = description
        self._window = collections.deque(maxlen=int(max_samples))
        self._lock = threading.Lock()
        self._memo = None
        self.total_observed = 0
        self.total_bad = 0

    def observe(self, value, now):
        ok = float(value) <= self.threshold
        with self._lock:
            self._window.append((now, ok))
            self.total_observed += 1
            if not ok:
                self.total_bad += 1

    def _prune(self, now):
        # caller holds the lock
        lo = now - self.window_s
        w = self._window
        while w and w[0][0] < lo:
            w.popleft()

    def status(self, now) -> dict:
        # one /metrics scrape evaluates three lazy gauges per SLO,
        # each needing this dict — memoize keyed on (sample count,
        # ~now) so a scrape prices the O(window) prune/count ONCE, and
        # any new observation or time movement invalidates it
        key = (self.total_observed, round(now, 1))
        memo = self._memo
        if memo is not None and memo[0] == key:
            return memo[1]
        with self._lock:
            self._prune(now)
            n = len(self._window)
            bad = sum(1 for _, ok in self._window if not ok)
        good_frac = (n - bad) / n if n else 1.0
        budget = 1.0 - self.target
        burn = (bad / n) / budget if n else 0.0
        st = {
            "name": self.name, "metric": self.metric,
            "threshold": self.threshold, "target": self.target,
            "window_s": self.window_s, "samples": n, "bad": bad,
            "good_fraction": round(good_frac, 6),
            "burn_rate": round(burn, 4),
            "breaching": bool(n and good_frac < self.target),
            "total_observed": self.total_observed,
            "total_bad": self.total_bad,
        }
        self._memo = (key, st)
        return st

    def reset(self):
        with self._lock:
            self._window.clear()
            self._memo = None
            self.total_observed = 0
            self.total_bad = 0


class SLOTracker:
    """A set of SLOs fed by metric name. The serving engine owns one:
    ``declare`` at construction, `ServingMetrics.on_finish` feeds
    ``observe_metric("ttft_s", ...)`` / ``("itl_s", ...)`` per retired
    request, and the lazy gauges publish burn rates on every scrape."""

    def __init__(self, registry=None, clock=time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self._slos: dict = {}            # name -> SLO
        self._by_metric: dict = {}       # metric -> [SLO]
        self._registry = registry if registry is not None else _registry()

    def declare(self, name, metric, threshold, target=0.99,
                window_s=60.0, description="") -> SLO:
        """Register an objective; re-declaring a name replaces it."""
        slo = SLO(name, metric, threshold, target=target,
                  window_s=window_s, description=description)
        with self._lock:
            old = self._slos.get(name)
            if old is not None:
                self._by_metric[old.metric] = [
                    s for s in self._by_metric.get(old.metric, [])
                    if s is not old]
            self._slos[name] = slo
            self._by_metric.setdefault(metric, []).append(slo)
        self._bind_gauges(slo, self._registry)
        return slo

    def _bind_gauges(self, slo, reg):
        if reg is None:
            return
        base = f"slo.{slo.name}"
        reg.gauge(f"{base}.good_fraction").set_fn(
            lambda s=slo: s.status(self.clock())["good_fraction"])
        reg.gauge(f"{base}.burn_rate").set_fn(
            lambda s=slo: s.status(self.clock())["burn_rate"])
        reg.gauge(f"{base}.breaching").set_fn(
            lambda s=slo: s.status(self.clock())["breaching"])

    def bind_registry(self, reg):
        """Re-register every SLO's gauges (the engine rebinds after
        `reset_metrics` swaps its registry)."""
        self._registry = reg
        with self._lock:
            slos = list(self._slos.values())
        for slo in slos:
            self._bind_gauges(slo, reg)

    # -- feeds -----------------------------------------------------------
    def observe(self, name, value):
        """Feed one sample to the named SLO. O(1)."""
        slo = self._slos.get(name)
        if slo is not None:
            slo.observe(value, self.clock())

    def observe_metric(self, metric, value):
        """Feed one sample to every SLO declared on ``metric``. O(#slos
        on that metric) — the producer does not need to know which
        objectives exist."""
        for slo in self._by_metric.get(metric, ()):
            slo.observe(value, self.clock())

    # -- surface ---------------------------------------------------------
    def names(self):
        with self._lock:
            return sorted(self._slos)

    def status(self, name) -> dict:
        return self._slos[name].status(self.clock())

    def snapshot(self) -> dict:
        now = self.clock()
        with self._lock:
            slos = list(self._slos.values())
        return {s.name: s.status(now) for s in slos}

    def breaching(self) -> list:
        """Names of SLOs currently over budget in their window."""
        return [n for n, st in self.snapshot().items()
                if st["breaching"]]

    def reset(self):
        """Clear every window (engine warmup) — declarations stay."""
        with self._lock:
            slos = list(self._slos.values())
        for s in slos:
            s.reset()
