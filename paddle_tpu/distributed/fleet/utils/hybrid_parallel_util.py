"""Hybrid-parallel gradient/param sync helpers.

Reference parity: fleet/utils/hybrid_parallel_util.py —
fused_allreduce_gradients (grads over dp or dp×sep group :254-269),
broadcast_*_parameters (:287).

TPU-first: under the single controller grads come out of the compiled step
already reduced (GSPMD) and there is exactly one copy of each param, so
these are correctness no-ops kept for 1:1 porting of reference training
scripts; fused_allreduce_gradients still performs a real allreduce when
handed explicitly sharded per-rank grads.
"""
from __future__ import annotations

from ...collective import all_reduce, ReduceOp


def fused_allreduce_gradients(parameter_list, hcg=None, group=None):
    group = group or (hcg.get_data_parallel_group() if hcg is not None
                      else None)
    for p in parameter_list:
        g = getattr(p, "grad", None)
        if g is None:
            continue
        sh = getattr(g._data, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec and any(s is not None for s in spec):
            all_reduce(g, op=ReduceOp.SUM, group=group)


def broadcast_dp_parameters(model, hcg):
    return None


def broadcast_mp_parameters(model, hcg):
    return None


def broadcast_sharding_parameters(model, hcg):
    return None


def broadcast_sep_parameters(model, hcg):
    return None


def broadcast_input_data(hcg, *inputs, **kwargs):
    return inputs if not kwargs else (inputs, kwargs)
