"""Globally-reduced metric helpers (reference fleet/metrics/metric.py).

Each function takes a local numpy value / Tensor, reduces it over the
trainer world, and returns the global result as numpy. Reduction uses
paddle.distributed.all_reduce when a multi-process world is
initialized; single-controller (world 1) values are already global.
"""
from __future__ import annotations

import builtins

import numpy as np


def _to_np(v):
    if hasattr(v, "numpy"):
        return np.asarray(v.numpy(), dtype=np.float64)
    return np.asarray(v, dtype=np.float64)


_GEN = [0]


def _allreduce(arr, op="sum"):
    """Reduce across TRAINER PROCESSES. Under the single controller the
    local value is already global (device axes don't partial metrics),
    so this is the identity unless a multi-process gloo world was
    initialized (gloo_init_parallel_env) — then ranks exchange values
    through the TCPStore and reduce locally (exact, order-free)."""
    from ... import compat

    store = getattr(compat, "_GLOO_STORE", None)
    world = getattr(compat, "_GLOO_WORLD", 0)
    if store is None or world <= 1:
        return arr
    import pickle

    _GEN[0] += 1
    gen = _GEN[0]
    rank = getattr(compat, "_GLOO_RANK", 0)   # the gloo world's rank
    # ONE key per rank (generation-tagged payload) + the single-key
    # barrier: store memory stays bounded over any number of calls
    store.set(f"fleet/metric/{rank}", pickle.dumps((gen, arr)))
    compat.gloo_barrier()                     # everyone has written gen
    vals = []
    for r in range(world):
        g, v = pickle.loads(store.get(f"fleet/metric/{r}"))
        if g != gen:
            raise RuntimeError(
                f"fleet.metrics generation skew: rank {r} at {g}, "
                f"expected {gen} (mismatched metric call sequences "
                "across ranks)")
        vals.append(v)
    compat.gloo_barrier()                     # everyone has read gen
    red = {"sum": np.sum, "max": np.max, "min": np.min}[op]
    return red(np.stack([np.asarray(v, np.float64) for v in vals]),
               axis=0)


def sum(input, scope=None, util=None):
    """Global sum (reference metric.py:26)."""
    return _allreduce(_to_np(input).sum(keepdims=False), "sum")


def max(input, scope=None, util=None):
    return _allreduce(_to_np(input).max(), "max")


def min(input, scope=None, util=None):
    return _allreduce(_to_np(input).min(), "min")


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from the Auc metric's positive/negative histogram
    buckets (reference metric.py:149): sum buckets over the world, then
    the same threshold-sweep trapezoid as metric.Auc."""
    pos = _allreduce(_to_np(stat_pos), "sum").reshape(-1)
    neg = _allreduce(_to_np(stat_neg), "sum").reshape(-1)
    tot_pos, tot_neg = pos.sum(), neg.sum()
    if tot_pos == 0 or tot_neg == 0:
        return 0.0
    # prepend the (0,0) ROC anchor — without it the leading triangle is
    # lost and a populated top bucket degenerates the integral to 0
    tp = np.concatenate([[0.0], np.cumsum(pos[::-1])])
    fp = np.concatenate([[0.0], np.cumsum(neg[::-1])])
    tpr = tp / tot_pos
    fpr = fp / tot_neg
    trap = np.trapezoid if hasattr(np, "trapezoid") else np.trapz
    return float(trap(tpr, fpr))


def mae(abserr, total_ins_num, scope=None, util=None):
    """Global mean absolute error from summed |err| and counts."""
    e = _allreduce(_to_np(abserr).sum(), "sum")
    n = _allreduce(_to_np(total_ins_num).sum(), "sum")
    return float(e / builtins.max(n, 1.0))


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    e = _allreduce(_to_np(sqrerr).sum(), "sum")
    n = _allreduce(_to_np(total_ins_num).sum(), "sum")
    return float(np.sqrt(e / builtins.max(n, 1.0)))


def mse(sqrerr, total_ins_num, scope=None, util=None):
    e = _allreduce(_to_np(sqrerr).sum(), "sum")
    n = _allreduce(_to_np(total_ins_num).sum(), "sum")
    return float(e / builtins.max(n, 1.0))


def acc(correct, total, scope=None, util=None):
    c = _allreduce(_to_np(correct).sum(), "sum")
    n = _allreduce(_to_np(total).sum(), "sum")
    return float(c / builtins.max(n, 1.0))
