"""Sparse functional ops (reference sparse/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _values_op(x, fn):
    from .. import SparseCooTensor, SparseCsrTensor, _coo, _rewrap

    c = _coo(x)
    return _rewrap(x, SparseCooTensor(c._indices, fn(c._values), c._shape,
                                      coalesced=c._coalesced))


def relu(x, name=None):
    return _values_op(x, jax.nn.relu)


def relu6(x, name=None):
    return _values_op(x, lambda v: jnp.clip(v, 0, 6))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _values_op(x, lambda v: jnp.where(v >= 0, v, negative_slope * v))


def softmax(x, axis=-1, name=None):
    """Softmax over the stored nonzeros of each row (implicit zeros act as
    -inf, i.e. they do not participate) — reference sparse softmax
    semantics for 2-D COO/CSR."""
    from .. import SparseCooTensor, _coo, _rewrap, coalesce

    if axis not in (-1, 1):
        raise NotImplementedError("sparse softmax supports the last axis")
    c = coalesce(_coo(x))
    if c.sparse_dim() != 2 or c.dense_dim() != 0:
        raise NotImplementedError("sparse softmax supports 2-D matrices")
    rows = c._indices[0]
    n_rows = c._shape[0]
    vals = c._values.astype(jnp.float32)
    # zero-valued duplicate slots from static coalesce must not join the
    # softmax: mark occupied slots by value... a zero value is a valid
    # logit, so mark via first-occurrence structure instead
    ids = c._linear_ids()
    first = jnp.concatenate([jnp.ones((1,), bool), ids[1:] != ids[:-1]])
    neg_inf = jnp.asarray(-jnp.inf, vals.dtype)
    masked = jnp.where(first, vals, neg_inf)
    row_max = jax.ops.segment_max(masked, rows, num_segments=n_rows)
    e = jnp.where(first, jnp.exp(masked - row_max[rows]), 0.0)
    denom = jax.ops.segment_sum(e, rows, num_segments=n_rows)
    out = e / jnp.maximum(denom[rows], 1e-38)
    return _rewrap(x, SparseCooTensor(c._indices, out.astype(c._values.dtype),
                                      c._shape, coalesced=True))


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    raise NotImplementedError(
        "sparse attention rides the dense flash/ring paths on TPU "
        "(nn/functional/flash_attention.py)")
