"""Convolution functionals.

Reference parity: python/paddle/nn/functional/conv.py (conv2d etc., backed by
phi conv kernels / cuDNN). TPU-first: `jax.lax.conv_general_dilated` lowers to
XLA convolution, which the TPU compiler maps onto the MXU; NCHW in/out layouts
match the reference while XLA is free to pick internal layouts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._dispatch import nary, ensure_tensor


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 2 * n:  # paddle [lo, hi] pairs
            return tuple(v)
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _padding_arg(padding, n, stride, dilation, kernel):
    if isinstance(padding, str):
        p = padding.upper()
        if p == "SAME":
            return "SAME"
        if p == "VALID":
            return "VALID"
        raise ValueError(padding)
    if isinstance(padding, (list, tuple)) and len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    if isinstance(padding, (list, tuple)) and len(padding) == n and isinstance(padding[0], (list, tuple)):
        return [tuple(p) for p in padding]
    pads = _tuplize(padding, n)
    return [(p, p) for p in pads]


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NCH", "OIH", "NCH") if not channel_last else ("NHC", "OIH", "NHC")
    if n == 2:
        return ("NCHW", "OIHW", "NCHW") if not channel_last else ("NHWC", "OIHW", "NHWC")
    return ("NCDHW", "OIDHW", "NCDHW") if not channel_last else ("NDHWC", "OIDHW", "NDHWC")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format[-1] == "C"
    strides = _tuplize(stride, n)
    dilations = _tuplize(dilation, n)
    kernel = None
    pad_arg = _padding_arg(padding, n, strides, dilations, kernel)
    dn = _dim_numbers(n, channel_last)

    def f(v, w, *maybe_bias):
        out = jax.lax.conv_general_dilated(
            v, w,
            window_strides=strides,
            padding=pad_arg,
            rhs_dilation=dilations,
            feature_group_count=groups,
            dimension_numbers=dn,
            preferred_element_type=jnp.float32 if v.dtype == jnp.bfloat16 else None,
        )
        if out.dtype != v.dtype:
            out = out.astype(v.dtype)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    inputs = [ensure_tensor(x), ensure_tensor(weight)]
    if bias is not None:
        inputs.append(ensure_tensor(bias))
    return nary(f, inputs, f"conv{n}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format, output_size=None):
    channel_last = data_format[-1] == "C"
    strides = _tuplize(stride, n)
    dilations = _tuplize(dilation, n)
    pads = _padding_arg(padding, n, strides, dilations, None)
    opads = _tuplize(output_padding, n)
    dn = _dim_numbers(n, channel_last)

    def f(v, w, *maybe_bias):
        # paddle/torch weight layout for transpose conv: [in, out/groups, *k]
        # jax transpose conv via conv_general_dilated with lhs_dilation
        kshape = w.shape[2:]
        if isinstance(pads, str):
            pad_list = None
        else:
            pad_list = pads
        # effective padding for fractionally-strided conv
        tpads = []
        for i in range(n):
            k = (kshape[i] - 1) * dilations[i]
            if pad_list is None:
                lo = hi = 0
            else:
                lo, hi = pad_list[i]
            tpads.append((k - lo, k - hi + opads[i]))
        # flip spatial dims and swap in/out channels
        wt = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        wt = jnp.swapaxes(wt, 0, 1)  # [out/groups, in, *k]
        if groups > 1:
            ci = w.shape[0]
            co_g = w.shape[1]
            wt = w.reshape(groups, ci // groups, co_g, *kshape)
            wt = jnp.flip(wt, axis=tuple(range(3, 3 + n)))
            wt = jnp.swapaxes(wt, 1, 2).reshape(groups * co_g, ci // groups, *kshape)
        out = jax.lax.conv_general_dilated(
            v, wt,
            window_strides=(1,) * n,
            padding=tpads,
            lhs_dilation=strides,
            rhs_dilation=dilations,
            feature_group_count=groups,
            dimension_numbers=dn,
        )
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    inputs = [ensure_tensor(x), ensure_tensor(weight)]
    if bias is not None:
        inputs.append(ensure_tensor(bias))
    return nary(f, inputs, f"conv{n}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size)
