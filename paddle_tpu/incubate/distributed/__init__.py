from . import models  # noqa: F401

from . import fleet  # noqa: E402,F401
