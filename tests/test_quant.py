"""paddle.nn.quant tests: weight quantize round-trip, weight-only /
llm.int8 linears vs the dequantized oracle, QAT fake-quant STE
gradients, LSQ learned scales, QAT-wrapped linear training."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn import quant as Q


def _w(shape=(64, 32), seed=0, dtype=np.float32, scale=0.1):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape) * scale).astype(dtype)


class TestWeightQuantize:
    def test_round_trip_int8(self):
        w = _w()
        q, s = Q.weight_quantize(paddle.to_tensor(w))
        assert tuple(q.shape) == (32, 64)      # transposed, reference shape
        assert tuple(s.shape) == (32,)
        assert q._data.dtype == jnp.int8
        back = Q.weight_dequantize(q, s, out_dtype="float32")
        # absmax int8: max error is scale/2 = |w|_max / 254 per channel
        err = np.abs(np.asarray(back._data) - w)
        bound = np.abs(w).max(axis=0) / 254 + 1e-7
        assert (err <= bound[None, :] + 1e-6).all()

    def test_round_trip_int4(self):
        w = _w()
        q, s = Q.weight_quantize(paddle.to_tensor(w),
                                 algo="weight_only_int4")
        assert int(np.abs(np.asarray(q._data)).max()) <= 8
        back = np.asarray(Q.weight_dequantize(
            q, s, algo="weight_only_int4", out_dtype="float32")._data)
        assert np.abs(back - w).max() < np.abs(w).max() / 7

    def test_grouped(self):
        w = _w((128, 16))
        q, s = Q.weight_quantize(paddle.to_tensor(w), group_size=64)
        assert tuple(s.shape) == (2, 16)
        back = np.asarray(Q.weight_dequantize(q, s,
                                              out_dtype="float32")._data)
        assert np.abs(back - w).max() < np.abs(w).max() / 100

    def test_bad_algo_raises(self):
        with pytest.raises(ValueError):
            Q.weight_quantize(paddle.to_tensor(_w()), algo="int3")


class TestQuantizedLinears:
    def test_weight_only_linear_matches_dequant_matmul(self):
        x = paddle.to_tensor(_w((4, 32), seed=1))
        w = _w((32, 16), seed=2)
        q, s = Q.weight_quantize(paddle.to_tensor(w))
        bias = paddle.to_tensor(_w((16,), seed=3))
        got = Q.weight_only_linear(x, q, bias=bias, weight_scale=s)
        wd = np.asarray(Q.weight_dequantize(q, s, out_dtype="float32")._data)
        want = np.asarray(x._data) @ wd + np.asarray(bias._data)
        np.testing.assert_allclose(np.asarray(got._data), want,
                                   rtol=1e-4, atol=1e-5)

    def test_llm_int8_linear_close_to_fp(self):
        rng = np.random.default_rng(5)
        x = (rng.standard_normal((8, 64)) * 0.5).astype(np.float32)
        x[:, 3] *= 30.0   # outlier feature column
        w = _w((64, 32), seed=6)
        q, s = Q.weight_quantize(paddle.to_tensor(w), algo="llm.int8")
        got = np.asarray(Q.llm_int8_linear(
            paddle.to_tensor(x), q, weight_scale=s, threshold=6.0)._data)
        want = x @ w
        # int8 dynamic quant: ~1% relative error on the inlier part
        assert np.abs(got - want).max() < 0.05 * np.abs(want).max() + 1e-3

    def test_apply_per_channel_scale(self):
        x = _w((4, 8), seed=7) + 1.0
        s = np.abs(_w((8,), seed=8)) + 0.5
        got = np.asarray(Q.apply_per_channel_scale(
            paddle.to_tensor(x), paddle.to_tensor(s))._data)
        np.testing.assert_allclose(got, x / s, rtol=1e-6)


class TestFakeQuant:
    def test_abs_max_forward_and_ste_grad(self):
        fq = Q.FakeQuantAbsMax(quant_bits=8)
        x = paddle.to_tensor(_w((16, 16), seed=9), stop_gradient=False)
        y = fq(x)
        # quantized to the 255-level grid
        scale = np.abs(np.asarray(x._data)).max() / 127
        ratio = np.asarray(y._data) / scale
        np.testing.assert_allclose(ratio, np.round(ratio), atol=1e-4)
        # STE: gradient passes through as identity
        y.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad._data), 1.0)

    def test_channel_wise_scales_differ(self):
        fq = Q.FakeQuantChannelWiseAbsMax(quant_bits=8, quant_axis=1)
        x = np.stack([_w((8,), seed=1, scale=1.0),
                      _w((8,), seed=2, scale=10.0)], axis=1)
        y = np.asarray(fq(paddle.to_tensor(x))._data)
        for c, col in enumerate(x.T):
            sc = np.abs(col).max() / 127
            np.testing.assert_allclose(y[:, c] / sc,
                                       np.round(y[:, c] / sc), atol=1e-4)

    def test_moving_average_updates_in_train_only(self):
        fq = Q.FakeQuantMovingAverageAbsMax(moving_rate=0.5)
        x = paddle.to_tensor(np.full((4,), 2.0, np.float32))
        fq.train()
        fq(x)
        s1 = float(fq.scale._data)
        fq(paddle.to_tensor(np.full((4,), 10.0, np.float32)))
        s2 = float(fq.scale._data)
        assert s2 > s1
        fq.eval()
        fq(paddle.to_tensor(np.full((4,), 100.0, np.float32)))
        assert float(fq.scale._data) == s2   # frozen in eval

    def test_lsq_weight_scale_learns(self):
        fq = Q.FakeQuantWeightLSQPlus(quant_bits=8)
        x = paddle.to_tensor(_w((8, 8), seed=11), stop_gradient=False)
        y = fq(x)
        (y * y).sum().backward()
        assert fq.s.grad is not None
        assert np.isfinite(np.asarray(fq.s.grad._data)).all()


class TestQATLinear:
    def test_wrapped_linear_trains(self):
        paddle.seed(0)
        lin = nn.Linear(16, 4)
        qlin = Q.QuantizedLinear(lin)
        import paddle_tpu.optimizer as popt

        opt = popt.SGD(learning_rate=0.05,
                       parameters=[lin.weight, lin.bias])
        x = paddle.to_tensor(_w((8, 16), seed=12))
        target = paddle.to_tensor(_w((8, 4), seed=13))
        losses = []
        for _ in range(5):
            out = qlin(x)
            loss = ((out - target) * (out - target)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_stub_identity_and_observer(self):
        st = Q.Stub()
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        assert np.allclose(np.asarray(st(x)._data), 1.0)
        st2 = Q.Stub(Q.FakeQuantAbsMax())
        assert st2(x).shape == x.shape
