"""Hybrid-parallel optimizer wrapper.

Reference parity: HybridParallelOptimizer
(fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:255)
with HybridParallelClipGrad (:41) — global-norm clip across all parallel
groups — and the sharding-stage-1 hookup.

TPU-first: grads under the single controller are already global values
(GSPMD reduced them), so the cross-group clip-norm allreduces of the
reference collapse into a plain global-norm computation; sharding stage 1
activates by sharding the inner optimizer's accumulators over the
"sharding" axis (DygraphShardingOptimizer).
"""
from __future__ import annotations

from ....optimizer.optimizer import Optimizer
from .dygraph_sharding_optimizer import DygraphShardingOptimizer


class HybridParallelOptimizer:
    def __init__(self, optimizer: Optimizer, hcg, strategy=None):
        self._hcg = hcg
        self._strategy = strategy
        sharding_degree = (hcg.get_sharding_parallel_world_size()
                           if hcg is not None else 1)
        if sharding_degree > 1 and not isinstance(
            optimizer, DygraphShardingOptimizer
        ):
            optimizer = DygraphShardingOptimizer(optimizer, hcg)
        self._inner_opt = optimizer

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self._inner_opt.step()
        return None, None

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
