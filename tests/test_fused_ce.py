"""fused_linear_cross_entropy: numeric parity (loss + grads) against the
unfused matmul→cross_entropy path, which is itself OpTest-verified.
Reference role: c_softmax_with_cross_entropy / fused CE kernels
(paddle/phi/kernels/gpu/c_softmax_with_cross_entropy_kernel.cu)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import ops


def _setup(n=37, h=16, v=53, ignore=None, seed=0):
    rng = np.random.default_rng(seed)
    hidden = paddle.to_tensor(rng.standard_normal((n, h)), dtype="float32")
    weight = paddle.to_tensor(rng.standard_normal((v, h)) * 0.1,
                              dtype="float32")
    lbl = rng.integers(0, v, (n,))
    if ignore is not None:
        lbl[:: 5] = ignore
    labels = paddle.to_tensor(lbl, dtype="int64")
    return hidden, weight, labels


def _unfused(hidden, weight, labels, reduction, ignore_index):
    logits = ops.matmul(hidden, weight, transpose_y=True)
    return F.cross_entropy(logits, labels, reduction=reduction,
                           ignore_index=ignore_index)


@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_fused_ce_loss_parity(reduction):
    hidden, weight, labels = _setup()
    got = F.fused_linear_cross_entropy(hidden, weight, labels,
                                       reduction=reduction, n_chunks=4)
    want = _unfused(hidden, weight, labels, reduction, -100)
    np.testing.assert_allclose(np.asarray(got._data), np.asarray(want._data),
                               rtol=2e-5, atol=2e-5)


def test_fused_ce_ignore_index_and_grads():
    hidden, weight, labels = _setup(ignore=-1)
    hidden.stop_gradient = False
    weight.stop_gradient = False
    loss = F.fused_linear_cross_entropy(hidden, weight, labels,
                                        ignore_index=-1, n_chunks=3)
    loss.backward()
    gh, gw = np.asarray(hidden.grad._data), np.asarray(weight.grad._data)

    hidden2, weight2, labels2 = _setup(ignore=-1)
    hidden2.stop_gradient = False
    weight2.stop_gradient = False
    loss2 = _unfused(hidden2, weight2, labels2, "mean", -1)
    loss2.backward()
    np.testing.assert_allclose(float(loss._data), float(loss2._data),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(gh, np.asarray(hidden2.grad._data),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(gw, np.asarray(weight2.grad._data),
                               rtol=2e-4, atol=2e-5)


def test_fused_ce_untransposed_weight():
    hidden, weight, labels = _setup()
    w_hv = paddle.to_tensor(np.asarray(weight._data).T.copy())
    w_hv.stop_gradient = False
    loss = F.fused_linear_cross_entropy(hidden, w_hv, labels,
                                        transpose_y=False, n_chunks=2)
    loss.backward()
    weight.stop_gradient = False
    want = _unfused(hidden, weight, labels, "mean", -100)
    want.backward()
    np.testing.assert_allclose(float(loss._data), float(want._data),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(w_hv.grad._data),
                               np.asarray(weight.grad._data).T,
                               rtol=2e-4, atol=2e-5)


def test_gpt_model_fused_loss_parity():
    from paddle_tpu.models import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=16,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(1)
    ids = paddle.to_tensor(rng.integers(0, 97, (2, 16)), dtype="int64")
    labels = paddle.to_tensor(rng.integers(0, 97, (2, 16)), dtype="int64")
    mask = paddle.to_tensor((rng.random((2, 16)) > 0.3).astype("float32"))

    crit = GPTPretrainingCriterion()
    want = crit(model(ids), labels, mask)
    got = model.loss(ids, labels, mask)
    np.testing.assert_allclose(float(got._data), float(want._data),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# vocab-tiled streaming CE (ops/pallas/fused_cross_entropy.py, ISSUE 7):
# interpret-mode kernel == XLA tile scan == the unfused dense path, for
# loss AND both gradients; plus the FLAGS_fused_ce routing surface.
# ---------------------------------------------------------------------------

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import fused_cross_entropy as fce
from paddle_tpu.utils import flags as _flags


def _dense_ref(h, w, lbl, ii):
    logits = h.astype(jnp.float32) @ w.astype(jnp.float32).T
    lse = jax.nn.logsumexp(logits, axis=-1)
    safe = jnp.where(lbl == ii, 0, lbl)
    picked = jnp.take_along_axis(logits, safe[:, None], -1)[:, 0]
    return jnp.where(lbl != ii, lse - picked, 0.0)


@pytest.mark.parametrize("n,vocab,ii", [(64, 256, -100), (100, 384, -1)])
def test_vocab_tiled_kernel_parity(n, vocab, ii):
    """Interpret kernel vs XLA tiles vs dense: loss, dhidden, dweight.
    n=100 exercises the token-tile padding path."""
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((n, 32)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((vocab, 32)) * 0.1, jnp.float32)
    lbl = rng.integers(0, vocab, (n,))
    lbl[::5] = ii
    lbl = jnp.asarray(lbl, jnp.int32)

    def kern(h, w):
        return jnp.sum(jnp.sin(fce.fused_cross_entropy(
            h, w, lbl, ignore_index=ii, interpret=True)))

    def xla(h, w):
        return jnp.sum(jnp.sin(fce.fused_cross_entropy(
            h, w, lbl, ignore_index=ii, use_kernel=False)))

    def dense(h, w):
        return jnp.sum(jnp.sin(_dense_ref(h, w, lbl, ii)))

    lk, lx, ld = kern(h, w), xla(h, w), dense(h, w)
    assert abs(float(lk) - float(lx)) < 1e-4
    assert abs(float(lk) - float(ld)) < 1e-4
    gk = jax.grad(kern, (0, 1))(h, w)
    gx = jax.grad(xla, (0, 1))(h, w)
    gd = jax.grad(dense, (0, 1))(h, w)
    for a, b, c in zip(gk, gx, gd):
        assert float(jnp.max(jnp.abs(a - b))) < 2e-4
        assert float(jnp.max(jnp.abs(a - c))) < 2e-4


def test_vocab_tiled_ignored_rows_zero_grads():
    """An all-ignored batch must yield exactly zero dh/dw (the masked
    cotangent can't leak the recomputed softmax term)."""
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((256, 8)), jnp.float32)
    lbl = jnp.full((16,), -100, jnp.int32)
    gh, gw = jax.grad(
        lambda h, w: jnp.sum(fce.fused_cross_entropy(
            h, w, lbl, interpret=True)), (0, 1))(h, w)
    assert float(jnp.max(jnp.abs(gh))) == 0.0
    assert float(jnp.max(jnp.abs(gw))) == 0.0


def test_vocab_tiled_bf16():
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.standard_normal((32, 16)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((256, 16)) * 0.1, jnp.bfloat16)
    lbl = jnp.asarray(rng.integers(0, 256, (32,)), jnp.int32)
    got = fce.fused_cross_entropy(h, w, lbl, interpret=True)
    want = _dense_ref(h, w, lbl, -100)
    assert float(jnp.max(jnp.abs(got - want))) < 3e-2


def test_fused_linear_ce_routing_flag():
    """F.fused_linear_cross_entropy: FLAGS_fused_ce on (vocab-tiled) and
    off (token-chunked) agree with each other and the unfused path —
    both reductions, both weight layouts."""
    hidden, weight, labels = _setup(n=37, h=16, v=53)
    want = _unfused(hidden, weight, labels, "mean", -100)
    for tiled in (True, False):
        _flags.set_flags({"FLAGS_fused_ce": tiled})
        try:
            got = F.fused_linear_cross_entropy(hidden, weight, labels)
            np.testing.assert_allclose(float(got._data),
                                       float(want._data), rtol=2e-5,
                                       atol=2e-5)
            w_hv = paddle.to_tensor(np.asarray(weight._data).T.copy())
            got_t = F.fused_linear_cross_entropy(hidden, w_hv, labels,
                                                 transpose_y=False)
            np.testing.assert_allclose(float(got_t._data),
                                       float(want._data), rtol=2e-5,
                                       atol=2e-5)
        finally:
            _flags.set_flags({"FLAGS_fused_ce": True})


def test_supports_gate():
    assert fce.supports(50304, 2048, jnp.bfloat16)   # the bench vocab
    assert fce.supports(384, 32, jnp.float32)
    assert not fce.supports(53, 32, jnp.float32)     # vocab % 128 != 0
    assert not fce.supports(256, 32, jnp.int32)


def test_cross_entropy_soft_label_ignore_index_raises():
    """Reference parity regression (ISSUE 7 satellite): ignore_index has
    no meaning for soft labels — the reference raises, we silently
    ignored it."""
    logits = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((4, 8)), dtype="float32")
    soft = paddle.to_tensor(np.full((4, 8), 1 / 8), dtype="float32")
    with pytest.raises(ValueError, match="ignore_index"):
        F.cross_entropy(logits, soft, soft_label=True, ignore_index=3)
    # the default -100 sentinel stays legal with soft labels
    loss = F.cross_entropy(logits, soft, soft_label=True)
    assert np.isfinite(float(loss._data))
