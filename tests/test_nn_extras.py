"""New op/layer coverage: cdist/renorm/as_strided, Unfold/Fold,
spectral/weight norm, grid_sample/affine_grid."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestNewOps:
    def test_cdist_matches_scipy(self):
        import scipy.spatial.distance as sd

        rng = np.random.default_rng(0)
        a = rng.standard_normal((4, 3)).astype("float32")
        b = rng.standard_normal((6, 3)).astype("float32")
        for p in (2.0, 1.0, float("inf")):
            got = paddle.cdist(paddle.to_tensor(a), paddle.to_tensor(b),
                               p=p).numpy()
            ref = sd.cdist(a, b, "minkowski", p=p) if p != float("inf") \
                else sd.cdist(a, b, "chebyshev")
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_renorm(self):
        rng = np.random.default_rng(1)
        x = paddle.to_tensor(rng.standard_normal((3, 5)).astype("float32")
                             * 4)
        out = paddle.renorm(x, 2.0, 0, 1.0).numpy()
        assert np.all(np.linalg.norm(out, axis=1) <= 1.0 + 1e-5)

    def test_as_strided_windows(self):
        x = paddle.to_tensor(np.arange(8, dtype="float32"))
        # sliding windows of 3, stride 1
        out = paddle.as_strided(x, [6, 3], [1, 1]).numpy()
        for i in range(6):
            np.testing.assert_array_equal(out[i], np.arange(i, i + 3))


class TestUnfoldFold:
    def test_unfold_fold_round_trip(self):
        rng = np.random.default_rng(2)
        x = paddle.to_tensor(rng.standard_normal((1, 2, 6, 6))
                             .astype("float32"))
        u = nn.Unfold(kernel_sizes=2, strides=2)
        cols = u(x)
        assert cols.shape == [1, 2 * 2 * 2, 9]
        f = nn.Fold(output_sizes=(6, 6), kernel_sizes=2, strides=2)
        back = f(cols)
        np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)


class TestNormWrappers:
    def test_spectral_norm_unit_sigma(self):
        paddle.seed(3)
        lin = nn.Linear(6, 4)
        lin.weight._data = lin.weight._data * 10.0
        nn.utils.spectral_norm(lin, n_power_iterations=20)
        x = paddle.to_tensor(np.random.default_rng(4)
                             .standard_normal((2, 6)).astype("float32"))
        lin(x)  # runs the hook, sets lin.weight to the normalized value
        s = np.linalg.svd(lin.weight.numpy(), compute_uv=False)
        assert abs(s[0] - 1.0) < 1e-3

    def test_weight_norm_preserves_function(self):
        paddle.seed(5)
        lin = nn.Linear(4, 3)
        ref_w = lin.weight.numpy().copy()
        x = paddle.to_tensor(np.random.default_rng(6)
                             .standard_normal((2, 4)).astype("float32"))
        ref = (x.numpy() @ ref_w) + lin.bias.numpy()
        nn.utils.weight_norm(lin)
        np.testing.assert_allclose(lin(x).numpy(), ref, rtol=1e-5)
        # g and v are the trainable params now
        names = dict(lin.named_parameters())
        assert "weight_g" in names and "weight_v" in names


class TestGridSample:
    def test_identity_affine_grid_sample(self):
        rng = np.random.default_rng(7)
        x = paddle.to_tensor(rng.standard_normal((1, 2, 5, 5))
                             .astype("float32"))
        theta = paddle.to_tensor(
            np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32))
        grid = F.affine_grid(theta, [1, 2, 5, 5], align_corners=True)
        out = F.grid_sample(x, grid, align_corners=True)
        np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-5)

    def test_shift_out_of_bounds_zero_padded(self):
        x = paddle.to_tensor(np.ones((1, 1, 4, 4), np.float32))
        theta = paddle.to_tensor(
            np.array([[[1.0, 0, 2.0], [0, 1.0, 0]]], np.float32))  # shift x
        grid = F.affine_grid(theta, [1, 1, 4, 4], align_corners=True)
        out = F.grid_sample(x, grid, align_corners=True).numpy()
        # shifted fully out on the right: half the columns are zeros
        assert np.all(out[..., -1] == 0)

    def test_nearest_mode(self):
        x = paddle.to_tensor(
            np.arange(16, dtype="float32").reshape(1, 1, 4, 4))
        theta = paddle.to_tensor(
            np.array([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32))
        grid = F.affine_grid(theta, [1, 1, 4, 4], align_corners=True)
        out = F.grid_sample(x, grid, mode="nearest",
                            align_corners=True)
        np.testing.assert_array_equal(out.numpy(), x.numpy())


class TestReviewFixes:
    def test_cdist_zero_distance_grad_finite(self):
        a = paddle.to_tensor(np.zeros((1, 2), np.float32),
                             stop_gradient=False)
        d = paddle.cdist(a, a)
        d.sum().backward()
        assert np.all(np.isfinite(np.asarray(a.grad._data)))

    def test_vector_round_trip_keeps_dtype_and_grads(self):
        import jax.numpy as jnp

        paddle.seed(9)
        lin = nn.Linear(3, 2)
        lin.weight._data = lin.weight._data.astype(jnp.bfloat16)
        vec = nn.utils.parameters_to_vector(lin.parameters())
        assert not vec.stop_gradient          # differentiable
        (vec * vec).sum().backward()
        assert lin.weight.grad is not None
        nn.utils.vector_to_parameters(
            paddle.to_tensor(np.zeros(vec.shape, np.float32)),
            lin.parameters())
        assert str(lin.weight._data.dtype) == "bfloat16"  # dtype kept

    def test_grid_sample_reflection_and_bad_mode(self):
        x = paddle.to_tensor(np.arange(4, dtype="float32")
                             .reshape(1, 1, 1, 4))
        theta = paddle.to_tensor(
            np.array([[[1.0, 0, 1.0], [0, 1.0, 0]]], np.float32))
        grid = F.affine_grid(theta, [1, 1, 1, 4], align_corners=True)
        out = F.grid_sample(x, grid, padding_mode="reflection",
                            align_corners=True).numpy()[0, 0, 0]
        # x coords sample at [1.5, 2.5, 3.5->reflect 2.5, 4.5->reflect 1.5]
        np.testing.assert_allclose(out, [1.5, 2.5, 2.5, 1.5], atol=1e-5)
        with pytest.raises(ValueError):
            F.grid_sample(x, grid, padding_mode="nope")


class TestTopLevelAPI:
    def test_summary_and_flops(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        info = paddle.summary(m, (2, 4))
        assert info["total_params"] == 4 * 8 + 8 + 8 * 2 + 2
        assert paddle.flops(m, (2, 4)) == 2 * 32 + 8 * 2 + 2 * 16

    def test_dtype_info_and_modes(self):
        assert paddle.iinfo("int32").max == 2 ** 31 - 1
        assert paddle.finfo("bfloat16").bits == 16
        assert paddle.in_dynamic_mode()
        paddle.disable_static()
        with pytest.raises(NotImplementedError):
            paddle.enable_static()
        with paddle.LazyGuard():
            lin = nn.Linear(2, 2)
        assert lin.weight is not None

    def test_new_math_ops(self):
        y = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        assert abs(float(paddle.trapezoid(y)) - 4.0) < 1e-6
        m, e = paddle.frexp(paddle.to_tensor(np.array([8.0]),
                                              stop_gradient=False))
        assert float(m.numpy()[0]) == 0.5 and float(e.numpy()[0]) == 4.0
        assert str(e._data.dtype) == str(m._data.dtype)  # float exponent
        m.sum().backward()          # grads flow (dispatch-registered)
        with pytest.raises(ValueError):
            paddle.trapezoid(paddle.to_tensor(np.array([1.0, 2.0])),
                             x=paddle.to_tensor(np.array([0.0, 1.0])),
                             dx=5.0)
        z = paddle.trapezoid(paddle.to_tensor(np.array([1.0, 2.0])), dx=0.0)
        assert float(z) == 0.0
        v = paddle.vander(paddle.to_tensor(np.array([1.0, 2.0])), n=3)
        assert v.shape == [2, 3]
        nq = paddle.nanquantile(
            paddle.to_tensor(np.array([1.0, np.nan, 3.0])), 0.5)
        assert float(nq) == 2.0
        draws = paddle.poisson(
            paddle.to_tensor(np.full((1000,), 5.0, np.float32)))
        assert 4.0 < float(draws.mean()) < 6.0


class TestRnntLoss:
    """RNN-Transducer loss (reference warprnnt_kernel.h) vs a direct
    numpy log-semiring DP."""

    def _np_rnnt(self, logits, labels, T, U, blank=0):
        from scipy.special import log_softmax

        lp = log_softmax(logits, axis=-1)
        B = logits.shape[0]
        out = np.zeros(B)
        for b in range(B):
            t_len, u_len = T[b], U[b]
            alpha = np.full((t_len, u_len + 1), -np.inf)
            alpha[0, 0] = 0.0
            for t in range(t_len):
                for u in range(u_len + 1):
                    if t == 0 and u == 0:
                        continue
                    cands = []
                    if t > 0:
                        cands.append(alpha[t - 1, u]
                                     + lp[b, t - 1, u, blank])
                    if u > 0:
                        cands.append(alpha[t, u - 1]
                                     + lp[b, t, u - 1, labels[b, u - 1]])
                    m = max(cands)
                    alpha[t, u] = m + np.log(
                        sum(np.exp(c - m) for c in cands))
            out[b] = -(alpha[t_len - 1, u_len]
                       + lp[b, t_len - 1, u_len, blank])
        return out

    def test_matches_numpy_dp_and_grads(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(0)
        B, T, U, V = 2, 5, 3, 6
        logits = rng.standard_normal((B, T, U + 1, V)).astype(np.float32)
        labels = rng.integers(1, V, (B, U)).astype(np.int64)
        tl = np.array([5, 4])
        ul = np.array([3, 2])
        want = self._np_rnnt(logits, labels, tl, ul)
        got = F.rnnt_loss(paddle.to_tensor(logits),
                          paddle.to_tensor(labels),
                          paddle.to_tensor(tl), paddle.to_tensor(ul),
                          fastemit_lambda=0.0, reduction="none")
        np.testing.assert_allclose(np.asarray(got._data), want, rtol=1e-4)
        # FastEmit regularization must actually change the objective
        fe = F.rnnt_loss(paddle.to_tensor(logits),
                         paddle.to_tensor(labels),
                         paddle.to_tensor(tl), paddle.to_tensor(ul),
                         fastemit_lambda=0.5, reduction="none")
        assert not np.allclose(np.asarray(fe._data), want)

        lg = paddle.to_tensor(logits)
        lg.stop_gradient = False
        loss = F.rnnt_loss(lg, paddle.to_tensor(labels),
                           paddle.to_tensor(tl), paddle.to_tensor(ul))
        loss.backward()
        g = np.asarray(lg.grad._data)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


class TestIncubateFusedLayers:
    def test_fused_dropout_add_layer(self):
        from paddle_tpu.incubate.nn import FusedDropoutAdd

        layer = FusedDropoutAdd(p=0.0)
        layer.eval()
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        y = paddle.to_tensor(np.full((2, 3), 2.0, np.float32))
        np.testing.assert_allclose(np.asarray(layer(x, y)._data), 3.0)
        assert "p=0.0" in layer.extra_repr()

    def test_fused_dropout_layer(self):
        from paddle_tpu.incubate.nn import FusedDropout

        layer = FusedDropout(p=0.5)
        layer.eval()
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        np.testing.assert_allclose(np.asarray(layer(x)._data), 1.0)


class TestLayerWrappersR5:
    """r5: the layer-class wrappers completing nn.__all__ (each over an
    already-tested functional) — constructor/forward smoke + a numeric
    spot check per family."""

    def test_loss_wrappers(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((4, 5)).astype(np.float32))
        y = paddle.to_tensor(
            np.where(rng.uniform(size=(4, 5)) > 0.5, 1.0, -1.0)
            .astype(np.float32))
        l = paddle.nn.SoftMarginLoss()(x, y)
        want = np.log1p(np.exp(-np.asarray(y._data)
                               * np.asarray(x._data))).mean()
        np.testing.assert_allclose(float(l), want, rtol=1e-5)

        lbl = paddle.to_tensor(rng.integers(0, 5, (4,)), dtype="int64")
        assert np.isfinite(float(paddle.nn.MultiMarginLoss()(x, lbl)))
        onehot = paddle.to_tensor(
            (rng.uniform(size=(4, 5)) > 0.5).astype(np.float32))
        assert np.isfinite(
            float(paddle.nn.MultiLabelSoftMarginLoss()(x, onehot)))
        var = paddle.to_tensor(
            rng.uniform(0.5, 2, (4, 5)).astype(np.float32))
        assert np.isfinite(float(paddle.nn.GaussianNLLLoss()(x, x, var)))
        assert np.isfinite(float(paddle.nn.PoissonNLLLoss()(
            x, paddle.to_tensor(
                rng.poisson(2.0, (4, 5)).astype(np.float32)))))
        a, p, n = (paddle.to_tensor(
            rng.standard_normal((3, 6)).astype(np.float32))
            for _ in range(3))
        assert np.isfinite(
            float(paddle.nn.TripletMarginWithDistanceLoss()(a, p, n)))

    def test_ctc_and_rnnt_wrappers(self):
        rng = np.random.default_rng(1)
        T, B, C, L = 6, 2, 5, 3
        logp = paddle.to_tensor(
            np.log(np.random.default_rng(1).dirichlet(
                np.ones(C), (T, B)).astype(np.float32)))
        labels = paddle.to_tensor(
            rng.integers(1, C, (B, L)), dtype="int64")
        il = paddle.to_tensor(np.full((B,), T, np.int64))
        ll = paddle.to_tensor(np.full((B,), L, np.int64))
        out = paddle.nn.CTCLoss()(logp, labels, il, ll)
        assert np.isfinite(float(out)) and float(out) > 0

    def test_hsigmoid_layer_owns_params(self):
        rng = np.random.default_rng(2)
        layer = paddle.nn.HSigmoidLoss(8, 10)
        assert layer.weight.shape[0] == 9
        x = paddle.to_tensor(rng.standard_normal((4, 8)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 10, (4,)), dtype="int64")
        out = layer(x, y)
        assert out.shape[0] == 4 and np.isfinite(
            np.asarray(out._data)).all()

    def test_adaptive_log_softmax(self):
        rng = np.random.default_rng(3)
        layer = paddle.nn.AdaptiveLogSoftmaxWithLoss(16, 20, [5, 10])
        x = paddle.to_tensor(
            rng.standard_normal((6, 16)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 20, (6,)), dtype="int64")
        out, loss = layer(x, y)
        assert np.isfinite(float(loss))

    def test_pool_pad_wrappers(self):
        rng = np.random.default_rng(4)
        x2 = paddle.to_tensor(
            rng.standard_normal((1, 2, 8, 8)).astype(np.float32))
        assert paddle.nn.LPPool2D(2.0, 2)(x2).shape == [1, 2, 4, 4]
        x1 = paddle.to_tensor(
            rng.standard_normal((1, 2, 8)).astype(np.float32))
        assert paddle.nn.LPPool1D(2.0, 2)(x1).shape == [1, 2, 4]
        assert paddle.nn.FractionalMaxPool2D((4, 4))(x2).shape \
            == [1, 2, 4, 4]
        pooled, idx = paddle.nn.functional.max_pool2d(
            x2, 2, return_mask=True)
        un = paddle.nn.MaxUnPool2D(2)(pooled, idx)
        assert un.shape == [1, 2, 8, 8]
        z = paddle.nn.ZeroPad1D([1, 2])(x1)
        assert z.shape == [1, 2, 11]
        z3 = paddle.nn.ZeroPad3D([1, 1, 0, 0, 2, 0])(paddle.to_tensor(
            rng.standard_normal((1, 1, 2, 3, 4)).astype(np.float32)))
        assert z3.shape[-1] == 6
        sm = paddle.nn.Softmax2D()(x2)
        np.testing.assert_allclose(
            np.asarray(sm._data).sum(1), 1.0, rtol=1e-5)
        drop = paddle.nn.FeatureAlphaDropout(0.5)
        drop.eval()
        np.testing.assert_allclose(np.asarray(drop(x2)._data),
                                   np.asarray(x2._data))

    def test_spectral_norm_layer(self):
        rng = np.random.default_rng(5)
        w = paddle.to_tensor(
            rng.standard_normal((6, 4)).astype(np.float32))
        sn = paddle.nn.SpectralNorm(w.shape, power_iters=20)
        wn = np.asarray(sn(w)._data)
        s = np.linalg.svd(wn, compute_uv=False)
        np.testing.assert_allclose(s.max(), 1.0, rtol=1e-3)

    def test_rnn_drivers(self):
        rng = np.random.default_rng(6)
        cell = paddle.nn.GRUCell(4, 8)
        rnn = paddle.nn.RNN(cell)
        x = paddle.to_tensor(
            rng.standard_normal((2, 5, 4)).astype(np.float32))
        out, state = rnn(x)
        assert out.shape == [2, 5, 8]
        # manual unroll must match
        h = None
        for t in range(5):
            y, h = cell(paddle.Tensor._wrap(x._data[:, t]), h)
        np.testing.assert_allclose(np.asarray(out._data)[:, -1],
                                   np.asarray(y._data), atol=1e-5)

        bi = paddle.nn.BiRNN(paddle.nn.GRUCell(4, 8),
                             paddle.nn.GRUCell(4, 8))
        out2, (sf, sb) = bi(x)
        assert out2.shape == [2, 5, 16]


class TestInplaceAndSparseAttention:
    def test_inplace_activation_variants(self):
        import paddle_tpu.nn.functional as F

        for name, ref in [("tanh_", np.tanh),
                          ("elu_", lambda v: np.where(
                              v > 0, v, np.expm1(v))),
                          ("leaky_relu_", lambda v: np.where(
                              v > 0, v, 0.01 * v)),
                          ("hardtanh_", lambda v: np.clip(v, -1, 1)),
                          ("thresholded_relu_", lambda v: np.where(
                              v > 1.0, v, 0.0))]:
            x = paddle.to_tensor(
                np.asarray([-2.0, -0.5, 0.5, 2.0], np.float32))
            out = getattr(F, name)(x)
            assert out is x                     # in-place contract
            np.testing.assert_allclose(
                np.asarray(x._data),
                ref(np.asarray([-2.0, -0.5, 0.5, 2.0], np.float32)),
                rtol=1e-6, err_msg=name)

    def test_sparse_attention_matches_dense_on_full_pattern(self):
        import scipy.special as sps

        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(0)
        b, h, s, d = 1, 2, 4, 8
        q, k, v = (rng.standard_normal((b, h, s, d)).astype(np.float32)
                   for _ in range(3))
        # full CSR pattern == dense attention
        offs = np.tile(np.arange(0, s * s + 1, s, dtype=np.int32),
                       (b, h, 1))
        cols = np.tile(np.tile(np.arange(s, dtype=np.int32), s),
                       (b, h, 1))
        out = F.sparse_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), paddle.to_tensor(offs),
            paddle.to_tensor(cols))
        scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        want = np.einsum("bhqk,bhkd->bhqd",
                         sps.softmax(scores, -1), v)
        np.testing.assert_allclose(np.asarray(out._data), want,
                                   rtol=1e-4, atol=1e-5)

    def test_sparse_attention_banded_pattern(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(1)
        b, h, s, d = 1, 1, 4, 4
        q, k, v = (rng.standard_normal((b, h, s, d)).astype(np.float32)
                   for _ in range(3))
        # diagonal-only pattern: each row attends to itself => out == v
        offs = np.tile(np.arange(s + 1, dtype=np.int32), (b, h, 1))
        cols = np.tile(np.arange(s, dtype=np.int32), (b, h, 1))
        out = F.sparse_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), paddle.to_tensor(offs),
            paddle.to_tensor(cols))
        np.testing.assert_allclose(np.asarray(out._data), v, rtol=1e-5)


class TestIncubateFusedFunctionals:
    """r5: the fused functional batch vs numpy references."""

    def test_rope_neox_and_gptj(self):
        import scipy  # noqa: F401
        from paddle_tpu.incubate.nn.functional import (
            fused_rotary_position_embedding,
        )

        rng = np.random.default_rng(0)
        b, s, h, d = 2, 6, 2, 8
        q = rng.standard_normal((b, s, h, d)).astype(np.float32)
        k = rng.standard_normal((b, s, h, d)).astype(np.float32)

        def np_rope(x, neox):
            inv = 1.0 / (10000.0 ** (np.arange(0, d, 2) / d))
            freqs = np.outer(np.arange(s), inv)
            if neox:
                emb = np.repeat(freqs, 2, axis=-1)
                sin = np.sin(emb)[None, :, None, :]
                cos = np.cos(emb)[None, :, None, :]
                x1, x2 = x[..., 0::2], x[..., 1::2]
                s1, c1 = sin[..., 0::2], cos[..., 0::2]
                out = np.empty_like(x)
                out[..., 0::2] = x1 * c1 - x2 * s1
                out[..., 1::2] = x2 * c1 + x1 * s1
                return out
            # half (GPT-J) style: pair (j, j+half) rotates by freq j — the
            # table is [freqs, freqs], NOT the neox interleave (which would
            # pair positions with wrong frequencies; the r5 ADVICE bug was
            # exactly that and this reference used to encode it too)
            half = d // 2
            s1 = np.sin(freqs)[None, :, None, :]
            c1 = np.cos(freqs)[None, :, None, :]
            x1, x2 = x[..., :half], x[..., half:]
            return np.concatenate([x1 * c1 - x2 * s1,
                                   x2 * c1 + x1 * s1], -1)

        for neox in (True, False):
            oq, ok, _ = fused_rotary_position_embedding(
                paddle.to_tensor(q), paddle.to_tensor(k),
                use_neox_rotary_style=neox)
            np.testing.assert_allclose(np.asarray(oq._data),
                                       np_rope(q, neox), rtol=1e-5,
                                       atol=1e-5)
            np.testing.assert_allclose(np.asarray(ok._data),
                                       np_rope(k, neox), rtol=1e-5,
                                       atol=1e-5)

    def test_fused_norms(self):
        from paddle_tpu.incubate.nn.functional import (
            fused_layer_norm, fused_rms_norm,
        )

        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 4, 8)).astype(np.float32)
        w = rng.standard_normal(8).astype(np.float32)
        b = rng.standard_normal(8).astype(np.float32)
        r = rng.standard_normal((2, 4, 8)).astype(np.float32)
        out, res = fused_layer_norm(
            paddle.to_tensor(x), paddle.to_tensor(w),
            paddle.to_tensor(b), 1e-5, begin_norm_axis=2,
            residual=paddle.to_tensor(r))
        pre = x + r
        mu = pre.mean(-1, keepdims=True)
        want = (pre - mu) / np.sqrt(pre.var(-1, keepdims=True) + 1e-5) \
            * w + b
        np.testing.assert_allclose(np.asarray(out._data), want,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(res._data), pre,
                                   rtol=1e-6)
        # reference contract: bare tensor when residual is None
        out2 = fused_rms_norm(paddle.to_tensor(x),
                              paddle.to_tensor(w), None, 1e-6,
                              begin_norm_axis=2)
        want2 = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(np.asarray(out2._data), want2,
                                   rtol=1e-4, atol=1e-5)

    def test_fused_ffn_and_mha(self):
        import scipy.special as sps

        from paddle_tpu.incubate.nn.functional import (
            fused_feedforward, fused_multi_head_attention,
        )

        rng = np.random.default_rng(2)
        b, s, e = 2, 4, 8
        x = rng.standard_normal((b, s, e)).astype(np.float32) * 0.3
        w1 = rng.standard_normal((e, 16)).astype(np.float32) * 0.3
        w2 = rng.standard_normal((16, e)).astype(np.float32) * 0.3
        out = fused_feedforward(
            paddle.to_tensor(x), paddle.to_tensor(w1),
            paddle.to_tensor(w2), dropout1_rate=0.0, dropout2_rate=0.0,
            pre_layer_norm=True, training=False)
        h = (x - x.mean(-1, keepdims=True)) / np.sqrt(
            x.var(-1, keepdims=True) + 1e-5)
        want = x + np.maximum(h @ w1, 0) @ w2
        np.testing.assert_allclose(np.asarray(out._data), want,
                                   rtol=1e-4, atol=1e-5)

        nh = 2
        qkvw = rng.standard_normal((3, nh, e // nh, e)) \
            .astype(np.float32) * 0.3
        lw = rng.standard_normal((e, e)).astype(np.float32) * 0.3
        out2 = fused_multi_head_attention(
            paddle.to_tensor(x), paddle.to_tensor(qkvw),
            paddle.to_tensor(lw), pre_layer_norm=True,
            dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
        assert tuple(out2.shape) == (b, s, e)
        assert np.isfinite(np.asarray(out2._data)).all()

    def test_varlen_attention(self):
        import scipy.special as sps

        from paddle_tpu.incubate.nn.functional import (
            variable_length_memory_efficient_attention,
        )

        rng = np.random.default_rng(3)
        b, h, s, d = 2, 2, 6, 4
        q = rng.standard_normal((b, h, s, d)).astype(np.float32)
        k = rng.standard_normal((b, h, s, d)).astype(np.float32)
        v = rng.standard_normal((b, h, s, d)).astype(np.float32)
        lens = np.asarray([4, 6], np.int32)
        out = variable_length_memory_efficient_attention(
            paddle.to_tensor(q), paddle.to_tensor(k),
            paddle.to_tensor(v), paddle.to_tensor(lens),
            paddle.to_tensor(lens))
        got = np.asarray(out._data)
        # batch 0: rows/cols beyond len 4 are dead; compare the live
        # block against dense attention over the first 4 positions
        sc = np.einsum("hqd,hkd->hqk", q[0, :, :4], k[0, :, :4]) \
            / np.sqrt(d)
        want = np.einsum("hqk,hkd->hqd", sps.softmax(sc, -1),
                         v[0, :, :4])
        np.testing.assert_allclose(got[0, :, :4], want, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(got[0, :, 4:], 0.0, atol=1e-6)

    def test_global_initializer_honored(self):
        from paddle_tpu.nn.initializer import (
            Constant, set_global_initializer,
        )

        set_global_initializer(Constant(0.5), Constant(-0.25))
        try:
            lin = paddle.nn.Linear(3, 3)
            np.testing.assert_allclose(np.asarray(lin.weight._data), 0.5)
            np.testing.assert_allclose(np.asarray(lin.bias._data), -0.25)
        finally:
            set_global_initializer(None)
            # set_global_initializer(None, None) clears per reference
            from paddle_tpu.nn import initializer as I
            I._GLOBAL_INIT = None
        lin2 = paddle.nn.Linear(3, 3)
        assert np.asarray(lin2.weight._data).std() > 0


def test_rope_position_ids_index_full_table():
    # decode-with-cache: position_ids >= current seq_len must index the
    # FULL sin/cos table (a [:seq_len] truncation would silently clamp)
    import numpy as np

    from paddle_tpu.incubate.nn import functional as IF

    d = 8
    rs = np.random.RandomState(0)
    table = rs.randn(64, d).astype(np.float32)
    sin, cos = np.sin(table), np.cos(table)
    q = rs.randn(1, 4, 2, d).astype(np.float32)

    def run(s, c, p):
        out = IF.fused_rotary_position_embedding(
            paddle.to_tensor(q), sin=paddle.to_tensor(s),
            cos=paddle.to_tensor(c),
            position_ids=paddle.to_tensor(p),
            use_neox_rotary_style=True)
        t = out[0] if isinstance(out, (tuple, list)) else out
        return t.numpy()

    a = run(sin, cos, np.array([[10, 11, 12, 13]], dtype=np.int64))
    b = run(sin[10:14], cos[10:14],
            np.array([[0, 1, 2, 3]], dtype=np.int64))
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_fused_mha_transpose_wb_requires_num_heads():
    import pytest as _pytest

    from paddle_tpu.incubate.nn import functional as IF

    with _pytest.raises(ValueError, match="num_heads"):
        IF.fused_multi_head_attention(
            paddle.randn([2, 3, 8]), qkv_weight=paddle.randn([8, 24]),
            qkv_bias=None, linear_weight=paddle.randn([8, 8]),
            linear_bias=None, transpose_qkv_wb=True)
