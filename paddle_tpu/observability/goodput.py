"""Training goodput attribution: fold the registry's per-step gauges
into one step-time breakdown.

Every stall source the runtime already measures publishes its own
instrument (PR 5/8/12 producers): ``input.stall_ms`` (time the step
loop waited for data), ``checkpoint.blocked_ms`` (synchronous slice of
an async save), ``pipeline.bubble_fraction`` (schedule-structural idle
on pp meshes). ``goodput_breakdown`` reads them, converts each to a
fraction of the measured step time, and reports

    goodput_frac = 1 - sum(attributed stall fractions)

— the fraction of wall time actually spent stepping the model. What is
NOT attributable from host-side gauges (overlapped H2D, per-axis
collective bytes) is reported informationally, never subtracted: the
breakdown only claims what was measured. Everything is also published
as ``goodput.*`` gauges so scrapes and the BENCH record carry the same
numbers.
"""
from __future__ import annotations

from .registry import registry as _registry

__all__ = ["goodput_breakdown", "goodput_baseline"]


def _hist_mean(reg, name, last=None):
    h = reg.get(name)
    if h is None or not getattr(h, "count", 0):
        return None
    if last is not None:
        xs = h.samples()[-int(last):]
        return sum(xs) / len(xs) if xs else None
    return h.mean()


def _hist_sum_count(reg, name):
    h = reg.get(name)
    if h is None or not getattr(h, "count", 0):
        return 0.0, 0
    return float(h.total), int(h.count)


def _gauge(reg, name):
    g = reg.get(name)
    v = g.value if g is not None else None
    return v if isinstance(v, (int, float)) else None


def goodput_baseline(registry=None) -> dict:
    """Snapshot of the cumulative instruments BEFORE a measured loop.
    Pass the result to ``goodput_breakdown(baseline=...)`` so a
    process that ran earlier lanes (a primary bench before the
    secondary, selftests) does not charge THEIR checkpoint blocking or
    a stale pipeline gauge to this run's steps.

    The pipeline-bubble gauge is CLEARED here rather than
    value-compared later: the bubble fraction is schedule-structural
    (two runs of the same pp config publish the identical float), so
    only a write that happens inside the measured window — which
    re-sets the gauge — can be attributed."""
    reg = registry if registry is not None else _registry()
    s, n = _hist_sum_count(reg, "checkpoint.blocked_ms")
    g = reg.get("pipeline.bubble_fraction")
    if g is not None:
        g.reset()
    return {"checkpoint_blocked": (s, n)}


def goodput_breakdown(step_ms, steps=None, registry=None,
                      publish=True, baseline=None) -> dict:
    """Per-step goodput breakdown against a measured ``step_ms``.

    ``steps`` (the measured-loop length) scopes histogram reads to the
    most recent window and amortizes whole-run costs (checkpoint
    blocking) per step. ``baseline`` (from `goodput_baseline`, taken
    before the loop) subtracts cumulative costs accrued BEFORE the
    measured window. Returns a JSON-able dict for BENCH records;
    publishes ``goodput.*`` gauges unless ``publish=False``.
    """
    reg = registry if registry is not None else _registry()
    baseline = baseline or {}
    step_ms = float(step_ms)
    out = {"step_ms": round(step_ms, 4)}
    attributed = {}

    stall = _hist_mean(reg, "input.stall_ms", last=steps)
    if stall is not None:
        attributed["input_stall"] = stall

    blocked_sum, blocked_n = _hist_sum_count(reg, "checkpoint.blocked_ms")
    base_sum, base_n = baseline.get("checkpoint_blocked", (0.0, 0))
    blocked_sum = max(0.0, blocked_sum - base_sum)
    blocked_n = max(0, blocked_n - base_n)
    if blocked_n:
        # blocking save cost amortized over the measured steps (saves
        # are sparse; per-save numbers stay in checkpoint.blocked_ms)
        attributed["checkpoint_block"] = (
            blocked_sum / steps if steps else blocked_sum / blocked_n)

    # goodput_baseline cleared this gauge, so a value here means a
    # pipeline schedule published it INSIDE the measured window
    bubble = _gauge(reg, "pipeline.bubble_fraction")
    if bubble is not None:
        attributed["pipeline_bubble"] = bubble * step_ms

    host = _hist_mean(reg, "timeline.train.host_ms", last=steps)
    if host is not None and host > step_ms:
        # host loop ran slower than the measured step rate: dispatch /
        # telemetry / python overhead the device had to wait for
        attributed["host_overhead"] = host - step_ms

    fracs = {}
    for k, ms in attributed.items():
        out[f"{k}_ms"] = round(ms, 4)
        fracs[k] = min(max(ms / step_ms, 0.0), 1.0) if step_ms else 0.0
    out["fracs"] = {k: round(v, 5) for k, v in fracs.items()}
    out["goodput_frac"] = round(
        max(0.0, 1.0 - sum(fracs.values())), 5)

    # informational (overlapped or byte-denominated: measured, but not
    # subtractable from step time without a bandwidth model)
    info = {}
    h2d = _hist_mean(reg, "input.h2d_ms", last=steps)
    if h2d is not None:
        info["h2d_ms_overlapped"] = round(h2d, 4)
    comm = {}
    for name in ("comm.grad_scatter_bytes_per_step",
                 "comm.param_gather_bytes_per_step",
                 "comm.bucket_bytes_per_step",
                 "hlo.comm_bytes_per_step"):
        v = _gauge(reg, name)
        if v:
            comm[name.split(".", 1)[1]] = v
    for name in reg.names(prefix="hlo.comm_bytes_per_step."):
        v = _gauge(reg, name)
        if v:
            comm.setdefault("per_axis", {})[
                name.rsplit(".", 1)[1]] = v
    if comm:
        info["comm_bytes"] = comm
    if info:
        out["informational"] = info

    if publish:
        try:
            reg.gauge("goodput.goodput_frac").set(out["goodput_frac"])
            reg.gauge("goodput.step_ms").set(out["step_ms"])
            for k, v in fracs.items():
                reg.gauge(f"goodput.{k}_frac").set(round(v, 5))
        except Exception:
            pass
    return out
