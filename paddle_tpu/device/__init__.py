"""paddle.device parity namespace + memory stats.

Reference: python/paddle/device/__init__.py and the memory stat counters
(paddle/phi/core/memory/stats.h -> paddle.device.cuda.max_memory_allocated).
On TPU, PJRT owns HBM; stats come from jax device memory profiling.
"""
from __future__ import annotations

import jax

from ..framework.device import (  # noqa: F401
    set_device,
    get_device,
    current_place,
    device_count,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    Place,
    CPUPlace,
    TPUPlace,
)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def synchronize(device=None):
    """Block until all queued work on the device completes (reference:
    paddle.device.synchronize / cudaDeviceSynchronize). PJRT equivalent:
    block_until_ready on a trivial transfer."""
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()


def memory_stats(device=None):
    dev = jax.devices()[0] if device is None else device
    try:
        return dev.memory_stats() or {}
    except Exception:
        return {}


def max_memory_allocated(device=None):
    return memory_stats(device).get("peak_bytes_in_use", 0)


def memory_allocated(device=None):
    return memory_stats(device).get("bytes_in_use", 0)


def max_memory_reserved(device=None):
    return memory_stats(device).get("peak_bytes_in_use", 0)


def memory_reserved(device=None):
    return memory_stats(device).get("bytes_in_use", 0)


class cuda:
    """Alias namespace so reference scripts using paddle.device.cuda.* run."""

    synchronize = staticmethod(synchronize)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    memory_reserved = staticmethod(memory_reserved)

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def empty_cache():
        pass


class tpu:
    synchronize = staticmethod(synchronize)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_allocated = staticmethod(memory_allocated)

    @staticmethod
    def device_count():
        return device_count()
