"""Pooling functionals (python/paddle/nn/functional/pooling.py parity;
reference kernels paddle/phi/kernels/pool_kernel.h). XLA reduce_window maps
these to efficient TPU windowed reductions.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._dispatch import unary, ensure_tensor
from .conv import _tuplize


def _pool_nd(x, kernel, stride, padding, n, reducer, init, ceil_mode=False,
             data_format="NCHW", count_include_pad=True, average=False):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"
    ks = _tuplize(kernel, n)
    st = _tuplize(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pads = padding.upper()
    else:
        p = _tuplize(padding, n)
        pads = [(int(pi), int(pi)) for pi in p]

    if channel_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pad_full = [(0, 0)] + pads + [(0, 0)] if isinstance(pads, list) else pads
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pad_full = [(0, 0), (0, 0)] + pads if isinstance(pads, list) else pads

    def f(v):
        # init values must be CONCRETE numpy scalars: a jnp constant becomes
        # a tracer under jit, defeating jax's monoid-reducer matching, and
        # reduce_window then loses its autodiff rule (fails only inside
        # jit-of-vjp, e.g. TrainStep over a conv net).
        if average:
            zero = np.zeros((), v.dtype)
            summed = jax.lax.reduce_window(
                v, zero, jax.lax.add, window, strides, padding=pad_full
            )
            if count_include_pad or not isinstance(pad_full, list) or all(p == (0, 0) for p in pad_full):
                denom = np.prod(ks)
                return (summed / denom).astype(v.dtype)
            ones = jnp.ones_like(v)
            counts = jax.lax.reduce_window(
                ones, zero, jax.lax.add, window, strides, padding=pad_full
            )
            return (summed / counts).astype(v.dtype)
        if jnp.issubdtype(v.dtype, jnp.floating):
            init_v = np.asarray(-np.inf, v.dtype)
        else:
            init_v = np.asarray(jnp.iinfo(v.dtype).min, v.dtype)
        return jax.lax.reduce_window(
            v, init_v, reducer, window, strides, padding=pad_full
        )

    return unary(f, x, "pool")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        return _max_pool_with_index(x, kernel_size, stride, padding, 1,
                                    ceil_mode, data_format)
    out = _pool_nd(x, kernel_size, stride, padding, 1, jax.lax.max,
                   lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                   ceil_mode, data_format)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_index(x, kernel_size, stride, padding, 2,
                                    ceil_mode, data_format)
    out = _pool_nd(x, kernel_size, stride, padding, 2, jax.lax.max,
                   lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                   ceil_mode, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_index(x, kernel_size, stride, padding, 3,
                                    ceil_mode, data_format)
    return _pool_nd(x, kernel_size, stride, padding, 3, jax.lax.max,
                    lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                    ceil_mode, data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, jax.lax.add, lambda d: 0,
                    ceil_mode, data_format, count_include_pad=not exclusive, average=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, jax.lax.add, lambda d: 0,
                    ceil_mode, data_format, count_include_pad=not exclusive, average=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, jax.lax.add, lambda d: 0,
                    ceil_mode, data_format, count_include_pad=not exclusive, average=True)


def _adaptive_sizes(in_size, out_size):
    # start/end indices per output cell (paddle adaptive pooling semantics)
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, n, average, data_format):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"
    spatial_axes = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
    out_sizes = _tuplize(output_size, n)

    def f(v):
        out = v
        for ax, osz in zip(spatial_axes, out_sizes):
            isz = out.shape[ax]
            if isz % osz == 0:
                # uniform: reshape + reduce (fast path)
                k = isz // osz
                new_shape = list(out.shape)
                new_shape[ax : ax + 1] = [osz, k]
                r = out.reshape(new_shape)
                out = jnp.mean(r, axis=ax + 1) if average else jnp.max(r, axis=ax + 1)
            else:
                starts, ends = _adaptive_sizes(isz, osz)
                slices = []
                for s, e in zip(starts, ends):
                    sl = jax.lax.slice_in_dim(out, s, e, axis=ax)
                    red = jnp.mean(sl, axis=ax, keepdims=True) if average else jnp.max(sl, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out

    return unary(f, x, "adaptive_pool")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, True, "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, True, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, True, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, False, "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, False, "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, False, "NCDHW")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Inverse of max_pool2d(return_mask=True) (reference
    unpool_kernel.h): scatter pooled values back to their argmax
    positions."""
    from ...ops._dispatch import nary
    import jax.numpy as jnp

    if stride is None:
        stride = kernel_size
    kh, kw = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else tuple(kernel_size))
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)

    def f(v, idx):
        n, c, hin, win = v.shape
        if output_size is not None:
            ho, wo = output_size[-2], output_size[-1]
        else:
            ho = (hin - 1) * sh - 2 * ph + kh
            wo = (win - 1) * sw - 2 * pw + kw
        flat = jnp.zeros((n, c, ho * wo), v.dtype)
        ii = idx.reshape(n, c, -1).astype(jnp.int32)
        vv = v.reshape(n, c, -1)
        out = jax.vmap(jax.vmap(
            lambda fl, i, val: fl.at[i].set(val)))(flat, ii, vv)
        return out.reshape(n, c, ho, wo)

    import jax

    return nary(f, [x, indices], name="max_unpool2d")


def _scalar1d(v):
    """Paddle's 1-D pooling APIs accept an int OR a 1-element list/tuple
    for kernel/stride/padding; normalize to the scalar before lifting to
    the 2-D helper (a nested tuple would mis-shape it)."""
    return v[0] if isinstance(v, (list, tuple)) else v


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    from ...framework.tensor import Tensor

    kernel_size = _scalar1d(kernel_size)
    stride = _scalar1d(stride)
    padding = _scalar1d(padding)
    x3 = x.unsqueeze(-2)
    i3 = indices.unsqueeze(-2)
    out = max_unpool2d(x3, i3, (1, kernel_size),
                       (1, stride if stride is not None else kernel_size),
                       (0, padding),
                       output_size=(1, output_size[-1])
                       if output_size is not None else None)
    return out.squeeze(-2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    from ...ops._dispatch import nary
    import jax
    import jax.numpy as jnp

    if stride is None:
        stride = kernel_size
    k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)

    def f(v, idx):
        n, c, d, h, w = v.shape
        if output_size is not None:
            do, ho, wo = output_size[-3:]
        else:
            do = (d - 1) * s[0] - 2 * p[0] + k[0]
            ho = (h - 1) * s[1] - 2 * p[1] + k[1]
            wo = (w - 1) * s[2] - 2 * p[2] + k[2]
        flat = jnp.zeros((n, c, do * ho * wo), v.dtype)
        ii = idx.reshape(n, c, -1).astype(jnp.int32)
        vv = v.reshape(n, c, -1)
        out = jax.vmap(jax.vmap(
            lambda fl, i, val: fl.at[i].set(val)))(flat, ii, vv)
        return out.reshape(n, c, do, ho, wo)

    return nary(f, [x, indices], name="max_unpool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    from ...ops._dispatch import unary
    import jax.numpy as jnp

    kernel_size = _scalar1d(kernel_size)
    stride = _scalar1d(stride)
    padding = _scalar1d(padding)
    out = lp_pool2d(x.unsqueeze(-2), norm_type, (1, kernel_size),
                    (1, stride if stride is not None else kernel_size),
                    (0, padding), ceil_mode=ceil_mode)
    return out.squeeze(-2)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    """Lp-norm pooling (reference lp_pool2d): (sum |x|^p)^(1/p) over
    windows — expressed via avg_pool on |x|^p (count-scaled)."""
    from ...ops._dispatch import unary
    import jax.numpy as jnp

    if stride is None:
        stride = kernel_size
    kh, kw = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else tuple(kernel_size))
    p = float(norm_type)

    def f(v):
        from ...framework.tensor import Tensor

        ct = jnp.promote_types(v.dtype, jnp.float32)
        powed = jnp.power(jnp.abs(v.astype(ct)), p)
        pooled = avg_pool2d(Tensor._wrap(powed), kernel_size, stride,
                            padding, ceil_mode=ceil_mode,
                            exclusive=False)._data
        return jnp.power(pooled * (kh * kw), 1.0 / p).astype(v.dtype)

    return unary(f, x, "lp_pool2d")


def _max_pool_with_index(x, kernel, stride, padding, nd, ceil_mode=False,
                         data_format=None):
    """(pooled, indices): indices are flat positions in the UNPADDED
    input plane (reference max_pool2d_with_index_kernel.h convention).
    Differentiable through the pooled values (routed via the op
    dispatcher like every other op)."""
    from ...ops._dispatch import nary

    if ceil_mode:
        raise NotImplementedError(
            "max_pool(return_mask=True) with ceil_mode=True is not "
            "supported; pad the input explicitly")
    channels_last = data_format in ("NHWC", "NDHWC", "NLC")
    k = (kernel,) * nd if isinstance(kernel, int) else tuple(kernel)
    s = ((stride,) * nd if isinstance(stride, int)
         else tuple(stride)) if stride is not None else k
    p = (padding,) * nd if isinstance(padding, int) else tuple(padding)

    def f(v):
        if channels_last:
            v = jnp.moveaxis(v, -1, 1)
        n, c = v.shape[0], v.shape[1]
        spatial = v.shape[2:]
        neg = (-jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
               else jnp.iinfo(v.dtype).min)
        pad_cfg = [(0, 0), (0, 0)] + [(pi, pi) for pi in p]
        vp = jnp.pad(v, pad_cfg, constant_values=neg)
        # extract windows: [N*C, prod(k), *out_spatial]
        patches = jax.lax.conv_general_dilated_patches(
            vp.reshape((n * c, 1) + vp.shape[2:]), k, s, "VALID")
        out_sp = patches.shape[2:]
        patches = patches.reshape((n, c, int(np.prod(k))) + out_sp)
        pooled = jnp.max(patches, axis=2)
        win_idx = jnp.argmax(patches, axis=2)          # [N, C, *out_sp]
        # window-local -> global unpadded flat index
        k_coords = jnp.stack(jnp.unravel_index(
            jnp.arange(int(np.prod(k))), k), -1)       # [K, nd]
        base = jnp.stack(jnp.meshgrid(
            *[jnp.arange(o) * si for o, si in zip(out_sp, s)],
            indexing="ij"), -1)                        # [*out_sp, nd]
        coords = base[None, None] + k_coords[win_idx]  # [N, C, *out, nd]
        for d in range(nd):
            coords = coords.at[..., d].add(-p[d])
            coords = coords.at[..., d].set(
                jnp.clip(coords[..., d], 0, spatial[d] - 1))
        flat = coords[..., 0]
        for d in range(1, nd):
            flat = flat * spatial[d] + coords[..., d]
        if channels_last:
            pooled = jnp.moveaxis(pooled, 1, -1)
            flat = jnp.moveaxis(flat, 1, -1)
        return pooled, flat.astype(jnp.int64)

    return nary(f, [x], name="max_pool_with_index")
