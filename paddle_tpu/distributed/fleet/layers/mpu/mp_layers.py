"""Megatron-style tensor-parallel layers.

Reference parity: fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding
(:47), ColumnParallelLinear (:334), RowParallelLinear (:541),
ParallelCrossEntropy (:742), and the comm PyLayers _c_identity/_c_split/
_c_concat/_mp_allreduce (mp_ops.py:91-341).

TPU-first: weights carry a NamedSharding over the "mp" mesh axis; the
forward is a plain matmul/gather with sharding constraints on activations.
XLA GSPMD then inserts the identity/allreduce/allgather collectives the
reference writes by hand — including the backward-pass transposes. The comm
PyLayers therefore reduce to sharding-constraint helpers (`_constrain`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..... import nn
from .....framework.tensor import Tensor
from .....framework.autograd import apply_op
from .....nn import functional as F
from .....nn.initializer import XavierUniform, Constant
from .... import env
from ...topology import get_hybrid_communicate_group


def _mp_axis_and_mesh(mp_group=None):
    if mp_group is not None:
        return mp_group.axes[0], mp_group.mesh
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return "mp", hcg.mesh
    mesh = env.get_mesh()
    ax = "mp" if "mp" in mesh.axis_names else mesh.axis_names[-1]
    return ax, mesh


def _constrain(t: Tensor, mesh, spec: P) -> Tensor:
    """Sharding constraint: with_sharding_constraint under trace, device_put
    in eager (the TPU equivalent of the reference's _c_identity markers)."""
    sharding = NamedSharding(mesh, spec)

    def f(x):
        return env.pin_sharding(x, sharding)

    return apply_op(f, [t], name="sharding_constraint")


def _shard_param(param, mesh, spec: P):
    param._data = jax.device_put(param._data, NamedSharding(mesh, spec))
    param.split_axis = next((i for i, a in enumerate(spec) if a is not None),
                            None)
    return param


class VocabParallelEmbedding(nn.Layer):
    """Reference mp_layers.py:47 — embedding table sharded on the vocab dim."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._axis, self._mesh = _mp_axis_and_mesh(mp_group)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform(),
        )
        if num_embeddings % self._mesh.shape[self._axis] == 0:
            _shard_param(self.weight, self._mesh, P(self._axis, None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out, self._mesh, P())


class ColumnParallelLinear(nn.Layer):
    """Reference mp_layers.py:334 — weight [in, out] sharded on out."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._axis, self._mesh = _mp_axis_and_mesh(mp_group)
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.is_mp = self._mesh.shape[self._axis] > 1
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform(),
        )
        if out_features % self._mesh.shape[self._axis] == 0:
            _shard_param(self.weight, self._mesh, P(None, self._axis))
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], is_bias=True,
                default_initializer=Constant(0.0),
            )
            if out_features % self._mesh.shape[self._axis] == 0:
                _shard_param(self.bias, self._mesh, P(self._axis))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, self._mesh, P())
        spec = P(*([None] * (out.ndim - 1) + [self._axis]))
        return _constrain(out, self._mesh, spec)


class RowParallelLinear(nn.Layer):
    """Reference mp_layers.py:541 — weight [in, out] sharded on in; partial
    results all-reduced (GSPMD emits the psum from the contraction)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._axis, self._mesh = _mp_axis_and_mesh(mp_group)
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform(),
        )
        if in_features % self._mesh.shape[self._axis] == 0:
            _shard_param(self.weight, self._mesh, P(self._axis, None))
        if has_bias:
            # bias is applied after the reduce — replicated
            self.bias = self.create_parameter(
                [out_features], is_bias=True,
                default_initializer=Constant(0.0),
            )
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            spec = P(*([None] * (x.ndim - 1) + [self._axis]))
            x = _constrain(x, self._mesh, spec)
        out = F.linear(x, self.weight, None)
        out = _constrain(out, self._mesh, P())
        if self.bias is not None:
            out = out + self.bias
        return out


def _pmax_nograd(x, axis):
    """Cross-device max treated as a constant by AD (lax.pmax has no
    differentiation rule; zero gradient is exact here — the logsumexp
    shift cancels in the CE gradient since softmax rows sum to 1)."""
    @jax.custom_vjp
    def f(v):
        return jax.lax.pmax(v, axis)

    f.defvjp(lambda v: (jax.lax.pmax(v, axis), None),
             lambda _, g: (jnp.zeros_like(g),))
    return f(x)


def vocab_parallel_cross_entropy(logits, label, *, mesh, axis,
                                 ignore_index=-100):
    """Explicit sharded-logsumexp CE over vocab-sharded logits (reference
    mp_layers.py:742 ParallelCrossEntropy — which also computes the
    sharded max/sumexp/gather by hand rather than materializing the full
    logits row).

    The whole computation runs inside a shard_map manual over the mp
    axis, so per-device memory is O(V / mp) BY CONSTRUCTION — no
    replicated [.., V] buffer can exist, whatever GSPMD would have
    guessed (tests/test_distributed.py asserts the compiled HLO carries
    no full-vocab shape). Three scalar-per-token collectives (max, two
    psums) replace the reference's c_allreduce calls; gradients flow
    through psum's transpose (softmax - onehot, computed shard-local).
    """
    def run(x, y):
        return vocab_parallel_ce_pure(x, y, mesh=mesh, axis=axis,
                                      ignore_index=ignore_index)

    return apply_op(run, [logits, label], name="vocab_parallel_ce")


def vocab_parallel_ce_pure(x, y, *, mesh, axis, ignore_index=-100):
    """The pure-jax sharded-logsumexp CE (see
    `vocab_parallel_cross_entropy` for the Tensor-level entry)."""
    in_spec = P(*((None,) * (x.ndim - 1) + (axis,)))
    lab_spec = P(*((None,) * y.ndim))

    def local(xl, yl):
        lv = xl.shape[-1]
        off = jax.lax.axis_index(axis) * lv
        xf = xl.astype(jnp.float32)
        gmax = _pmax_nograd(jnp.max(xf, axis=-1), axis)
        gse = jax.lax.psum(
            jnp.sum(jnp.exp(xf - gmax[..., None]), axis=-1), axis)
        rel = yl - off
        in_range = (rel >= 0) & (rel < lv)
        safe = jnp.clip(rel, 0, lv - 1).astype(jnp.int32)
        pred = jnp.take_along_axis(xf, safe[..., None], -1)[..., 0]
        pred = jax.lax.psum(jnp.where(in_range, pred, 0.0), axis)
        loss = jnp.log(gse) + gmax - pred
        if ignore_index is not None:
            loss = jnp.where(yl == ignore_index, 0.0, loss)
        return loss

    return jax.shard_map(
        local, mesh=mesh, in_specs=(in_spec, lab_spec),
        out_specs=lab_spec, axis_names=frozenset({axis}),
        check_vma=False,
    )(x, y)


class ParallelCrossEntropy(nn.Layer):
    """Reference mp_layers.py:742 — CE over vocab-sharded logits via the
    explicit sharded logsumexp (`vocab_parallel_cross_entropy`). Falls
    back to plain CE when no mp axis is active or the vocab does not
    split evenly.

    The mesh resolves at FORWARD time (an instance built before
    fleet.init — or surviving a denv.reset() — must see the current
    mesh, not a stale or absent one), and only an axis literally named
    "mp" routes to the sharded path: guessing another axis (e.g. a
    dp-only mesh's last axis) would reshard batch-sharded logits into
    vocab shards and silently regress memory/traffic."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self._ignore_index = ignore_index
        self._mp_group = mp_group

    def _resolve(self):
        if self._mp_group is not None:
            return self._mp_group.axes[0], self._mp_group.mesh
        hcg = get_hybrid_communicate_group()
        mesh = (hcg.mesh if hcg is not None
                else env.get_mesh() if env.is_initialized() else None)
        if mesh is None or "mp" not in mesh.axis_names:
            return None, None
        return "mp", mesh

    def forward(self, input, label):
        axis, mesh = self._resolve()
        degree = (int(mesh.shape[axis])
                  if mesh is not None and axis in mesh.axis_names else 1)
        vocab = input.shape[-1]
        if degree > 1 and vocab % degree == 0:
            return vocab_parallel_cross_entropy(
                input, label, mesh=mesh, axis=axis,
                ignore_index=self._ignore_index)
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self._ignore_index)
