"""paddle.vision.ops tests (reference python/paddle/vision/ops.py):
nms/matrix_nms/box_coder/roi family/yolo_box/deform_conv2d — numerics
checked against straightforward numpy references.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


def _np_iou(a, b):
    ix1 = max(a[0], b[0]); iy1 = max(a[1], b[1])
    ix2 = min(a[2], b[2]); iy2 = min(a[3], b[3])
    inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
    ua = ((a[2] - a[0]) * (a[3] - a[1])
          + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / ua if ua > 0 else 0.0


class TestNMS:
    def test_matches_greedy_reference(self):
        rng = np.random.default_rng(0)
        xy = rng.uniform(0, 50, (30, 2))
        wh = rng.uniform(5, 20, (30, 2))
        boxes = np.concatenate([xy, xy + wh], 1).astype(np.float32)
        scores = rng.uniform(0, 1, 30).astype(np.float32)

        # greedy numpy reference
        order = np.argsort(-scores)
        keep = []
        for i in order:
            if all(_np_iou(boxes[i], boxes[j]) <= 0.4 for j in keep):
                keep.append(i)
        got = vops.nms(paddle.to_tensor(boxes), iou_threshold=0.4,
                       scores=paddle.to_tensor(scores))
        np.testing.assert_array_equal(np.asarray(got._data), keep)

    def test_no_scores_uses_input_order(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11],
                          [50, 50, 60, 60]], np.float32)
        got = vops.nms(paddle.to_tensor(boxes), iou_threshold=0.3)
        np.testing.assert_array_equal(np.asarray(got._data), [0, 2])

    def test_top_k(self):
        boxes = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                          [50, 50, 60, 60]], np.float32)
        got = vops.nms(paddle.to_tensor(boxes), iou_threshold=0.3,
                       scores=paddle.to_tensor(
                           np.array([0.9, 0.8, 0.7], np.float32)),
                       top_k=2)
        assert len(np.asarray(got._data)) == 2


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.default_rng(1)
        priors = np.array([[0, 0, 10, 10], [10, 10, 30, 30]], np.float32)
        targets = np.array([[2, 2, 12, 14], [8, 8, 28, 24]], np.float32)
        enc = vops.box_coder(paddle.to_tensor(priors), [0.1, 0.1, 0.2, 0.2],
                             paddle.to_tensor(targets),
                             code_type="encode_center_size")
        # decode back: deltas [N=2 targets, M=2 priors, 4] — take diagonal
        dec = vops.box_coder(paddle.to_tensor(priors), [0.1, 0.1, 0.2, 0.2],
                             enc, code_type="decode_center_size")
        d = np.asarray(dec._data)
        np.testing.assert_allclose(d[0, 0], targets[0], rtol=1e-4,
                                   atol=1e-3)
        np.testing.assert_allclose(d[1, 1], targets[1], rtol=1e-4,
                                   atol=1e-3)


class TestRoiOps:
    def _feat(self):
        # deterministic ramp feature [1, 2, 8, 8]
        base = np.arange(64, dtype=np.float32).reshape(8, 8)
        return np.stack([base, base * 10])[None]

    def test_roi_align_center_value(self):
        x = self._feat()
        boxes = np.array([[2.0, 2.0, 6.0, 6.0]], np.float32)
        out = vops.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                             paddle.to_tensor(np.array([1], np.int32)),
                             output_size=1, aligned=True)
        # aligned=True: region [1.5,5.5]^2; ratio-2 samples at 2.5/4.5 on
        # each axis -> mean = ramp value at (3.5, 3.5) = 3.5*8 + 3.5
        v = np.asarray(out._data)
        assert v.shape == (1, 2, 1, 1)
        np.testing.assert_allclose(v[0, 0, 0, 0], 31.5, atol=1e-4)

    def test_roi_pool_max(self):
        x = self._feat()
        boxes = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
        out = vops.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                            paddle.to_tensor(np.array([1], np.int32)),
                            output_size=2)
        v = np.asarray(out._data)
        assert v.shape == (1, 2, 2, 2)
        # region rows/cols 0..3 split 2x2: maxes at (1,1),(1,3),(3,1),(3,3)
        np.testing.assert_allclose(v[0, 0], [[9, 11], [25, 27]])

    def test_psroi_pool_shape_and_mean(self):
        # C = oc * ph * pw = 1*2*2
        x = np.ones((1, 4, 8, 8), np.float32)
        for ch in range(4):
            x[0, ch] = ch
        boxes = np.array([[0.0, 0.0, 8.0, 8.0]], np.float32)
        out = vops.psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                              paddle.to_tensor(np.array([1], np.int32)),
                              output_size=2)
        v = np.asarray(out._data)
        assert v.shape == (1, 1, 2, 2)
        np.testing.assert_allclose(v[0, 0], [[0, 1], [2, 3]])


class TestYoloBox:
    def test_shapes_and_range(self):
        n, na, cls, h, w = 1, 2, 3, 4, 4
        x = np.random.default_rng(2).standard_normal(
            (n, na * (5 + cls), h, w)).astype(np.float32)
        boxes, scores = vops.yolo_box(
            paddle.to_tensor(x),
            paddle.to_tensor(np.array([[128, 128]], np.int32)),
            anchors=[10, 13, 16, 30], class_num=cls, conf_thresh=0.0,
            downsample_ratio=32)
        assert np.asarray(boxes._data).shape == (1, na * h * w, 4)
        assert np.asarray(scores._data).shape == (1, na * h * w, cls)
        s = np.asarray(scores._data)
        assert (s >= 0).all() and (s <= 1).all()


class TestDistributeFpn:
    def test_levels(self):
        rois = np.array([[0, 0, 10, 10],        # small -> low level
                         [0, 0, 300, 300]], np.float32)  # large -> high
        outs, restore, nums = vops.distribute_fpn_proposals(
            paddle.to_tensor(rois), 2, 5, 4, 224,
            rois_num=paddle.to_tensor(np.array([2], np.int32)))
        sizes = [np.asarray(o._data).shape[0] for o in outs]
        assert sum(sizes) == 2
        assert np.asarray(outs[0]._data).shape[0] == 1   # small at lvl 2
        r = np.asarray(restore._data).reshape(-1)
        assert sorted(r.tolist()) == [0, 1]


class TestDeformConv:
    def test_zero_offset_matches_conv(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(3)
        x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
        w = rng.standard_normal((3, 2, 3, 3)).astype(np.float32)
        kh = kw = 3
        oh = ow = 4
        offset = np.zeros((1, 2 * kh * kw, oh, ow), np.float32)
        got = vops.deform_conv2d(paddle.to_tensor(x),
                                 paddle.to_tensor(offset),
                                 paddle.to_tensor(w))
        want = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w))
        np.testing.assert_allclose(np.asarray(got._data),
                                   np.asarray(want._data), atol=1e-4)


class TestMatrixNMS:
    """Numerics vs an independent numpy model of the reference decay
    (matrix_nms_kernel.cc NMSMatrix; numpy model in
    test_matrix_nms_op.py): suppressor-side compensation cmax=ious.max(0)
    broadcast per-row, gaussian decay exp((cmax^2-iou^2)*sigma),
    score_threshold filtering before decay."""

    @staticmethod
    def _np_iou(b):
        n = b.shape[0]
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        ix1 = np.maximum(x1[:, None], x1[None, :])
        iy1 = np.maximum(y1[:, None], y1[None, :])
        ix2 = np.minimum(x2[:, None], x2[None, :])
        iy2 = np.minimum(y2[:, None], y2[None, :])
        inter = (np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0))
        union = area[:, None] + area[None, :] - inter
        return np.where(union > 0, inter / np.maximum(union, 1e-10), 0.0)

    def _np_one_class(self, boxes, s, score_threshold, top_k,
                      use_gaussian, sigma):
        keep = np.where(s > score_threshold)[0]
        order = keep[np.argsort(-s[keep], kind="stable")][:top_k]
        b_s, s_s = boxes[order], s[order]
        ious = np.triu(self._np_iou(b_s), k=1)
        cmax = np.repeat(ious.max(0)[:, None], ious.shape[0], axis=1)
        if use_gaussian:
            decay = np.exp((cmax ** 2 - ious ** 2) * sigma)
        else:
            decay = (1 - ious) / np.maximum(1 - cmax, 1e-9)
        return s_s * decay.min(0), b_s

    def _check(self, use_gaussian):
        rng = np.random.default_rng(7)
        m, c = 12, 3
        wh = rng.uniform(0.1, 0.5, (m, 2))
        xy = rng.uniform(0.0, 0.5, (m, 2))
        boxes = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        scores = rng.uniform(0.0, 1.0, (c, m)).astype(np.float32)
        st, sigma = 0.25, 2.0
        out = vops.matrix_nms(
            paddle.to_tensor(boxes[None]), paddle.to_tensor(scores[None]),
            score_threshold=st, post_threshold=0.0, nms_top_k=-1,
            keep_top_k=-1, use_gaussian=use_gaussian,
            gaussian_sigma=sigma, background_label=-1,
            return_rois_num=False)
        got = np.asarray(out._data)     # rows: [label, score, x1..y2]
        want_rows = []
        for ci in range(c):
            s_dec, b_s = self._np_one_class(boxes, scores[ci], st, m,
                                            use_gaussian, sigma)
            for sc_v, bx in zip(s_dec, b_s):
                want_rows.append((ci, sc_v, *bx))
        want_rows.sort(key=lambda r: -r[1])
        assert got.shape[0] == len(want_rows), (got.shape, len(want_rows))
        for grow, wrow in zip(got, want_rows):
            assert int(grow[0]) == wrow[0]
            np.testing.assert_allclose(grow[1], wrow[1], rtol=1e-5,
                                       atol=1e-6)
            np.testing.assert_allclose(grow[2:], wrow[2:], rtol=1e-5)

    def test_linear_decay(self):
        self._check(use_gaussian=False)

    def test_gaussian_decay(self):
        self._check(use_gaussian=True)


class TestOpsClassWrappers:
    def test_roi_align_layer_matches_functional(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(
            rng.standard_normal((1, 2, 8, 8)).astype(np.float32))
        boxes = paddle.to_tensor(
            np.asarray([[0.0, 0.0, 4.0, 4.0]], np.float32))
        bn = paddle.to_tensor(np.asarray([1], np.int32))
        got = vops.RoIAlign(2)(x, boxes, bn)
        want = vops.roi_align(x, boxes, bn, 2)
        np.testing.assert_allclose(np.asarray(got._data),
                                   np.asarray(want._data))

    def test_deform_conv_layer(self):
        rng = np.random.default_rng(1)
        layer = vops.DeformConv2D(2, 3, 3)
        x = paddle.to_tensor(
            rng.standard_normal((1, 2, 6, 6)).astype(np.float32))
        offset = paddle.to_tensor(np.zeros((1, 18, 4, 4), np.float32))
        out = layer(x, offset)
        assert tuple(out.shape) == (1, 3, 4, 4)

    def test_read_file_and_decode_jpeg(self, tmp_path):
        p = tmp_path / "blob.bin"
        p.write_bytes(b"\x01\x02\x03")
        t = vops.read_file(str(p))
        np.testing.assert_array_equal(np.asarray(t._data), [1, 2, 3])
        import pytest as _p
        with _p.raises(NotImplementedError, match="JPEG"):
            vops.decode_jpeg(t)
