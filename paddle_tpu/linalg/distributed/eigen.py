"""Power / subspace iteration eigensolvers on the distributed grid.

The paper's route to spectra at TPU scale (PAPERS.md, arXiv 2112.09017):
never factor the big matrix — multiply it. The subspace basis V [n, k]
(k small) stays REPLICATED; only A is 2-D sharded. One iteration is a
distributed A @ V (each rank contracts its block against V's matching
row slice, one psum over ``cols``, one [n/r, k] all_gather along
``rows``) followed by a replicated thin-QR re-orthonormalization — so
the wire moves n·k panels, never n·n. The Rayleigh–Ritz step at the end
(k×k projected problem, solved redundantly) rotates the basis to
eigenvector estimates and reads off the eigenvalues.

`power_iteration` is the k=1 case, returned as scalars.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._grid import (
    COLS, ROWS, as_array, cached_jit, default_grid, grid_shape, pad2,
    place, wrap_like,
)

__all__ = ["eigsh", "power_iteration", "eigsh_lowered"]


def _mv(a, v, r, c):
    """Distributed W = A @ V for replicated V: local block contraction,
    psum over cols, all_gather along rows -> replicated [n, k]."""
    j = lax.axis_index(COLS)
    nb_c = a.shape[1]
    vj = lax.dynamic_slice_in_dim(v, j * nb_c, nb_c, 0)
    w_i = jnp.dot(a, vj, preferred_element_type=jnp.float32)
    w_i = lax.psum(w_i, COLS)                      # [n/r, k]
    return lax.all_gather(w_i, ROWS, axis=0, tiled=True)   # [n, k]


def _eigsh_fn(r, c, iters):
    def fn(a, v0):
        v = v0.astype(jnp.float32)
        v, _ = jnp.linalg.qr(v, mode="reduced")
        for _ in range(iters):
            w = _mv(a, v, r, c)
            v, _ = jnp.linalg.qr(w, mode="reduced")
        # Rayleigh–Ritz on the k-dim subspace (replicated k×k problem)
        av = _mv(a, v, r, c)
        h = jnp.dot(v.T, av, preferred_element_type=jnp.float32)
        h = 0.5 * (h + h.T)
        evals, rot = jnp.linalg.eigh(h)
        # descending order (dominant first — power-iteration convention)
        evals = evals[::-1]
        vecs = jnp.dot(v, rot[:, ::-1],
                       preferred_element_type=jnp.float32)
        return evals, vecs

    return fn


def _build_eigsh(grid, iters):
    r, c = grid_shape(grid)
    return jax.jit(jax.shard_map(
        _eigsh_fn(r, c, iters), mesh=grid,
        in_specs=(P(ROWS, COLS), P()), out_specs=(P(), P()),
        check_vma=False))


def _prepare_eigsh(a, k, grid, seed):
    if grid is None:
        grid = default_grid()
    r, c = grid_shape(grid)
    mult = (r * c) // np.gcd(r, c)
    a_p, (n, n2) = pad2(a, mult, mult)
    if n != n2:
        raise ValueError(f"eigsh needs a square symmetric matrix, "
                         f"got {a.shape}")
    # the zero pad keeps symmetry; its eigenvalues are exact 0s, which
    # subspace iteration never confuses with the dominant k as long as
    # the sought eigenvalues are nonzero (the generic case)
    rng = np.random.default_rng(seed)
    v0 = jnp.asarray(rng.standard_normal((a_p.shape[0], k)), jnp.float32)
    a_p = place(a_p, grid, P(ROWS, COLS))
    v0 = place(v0, grid, P())
    return grid, a_p, v0, n


def eigsh(x, k=1, iters=50, grid=None, seed=0):
    """Top-k eigenpairs of a symmetric matrix by distributed subspace
    iteration (largest |λ| first). Returns (evals [k], evecs [n, k]).

    Convergence is geometric in |λ_{k+1}/λ_k| per iteration — size
    ``iters`` to the spectral gap. Eigenvector signs follow the
    Rayleigh–Ritz rotation and are not canonical (same contract as
    jnp.linalg.eigh up to sign).
    """
    a, wrap = as_array(x)
    grid, a_p, v0, n = _prepare_eigsh(a, k, grid, seed)
    fn = cached_jit(
        ("eigsh", grid, a_p.shape, k, iters, str(a_p.dtype)),
        lambda: _build_eigsh(grid, iters))
    evals, vecs = fn(a_p, v0)
    return wrap_like(evals, wrap), wrap_like(vecs[:n], wrap)


def power_iteration(x, iters=50, grid=None, seed=0):
    """Dominant eigenpair (λ₁, v₁) by distributed power iteration —
    `eigsh(k=1)` with scalar outputs."""
    evals, vecs = eigsh(x, k=1, iters=iters, grid=grid, seed=seed)
    return evals[0], vecs[:, 0]


def eigsh_lowered(n, k=1, iters=8, grid=None, dtype=jnp.float32):
    a = jnp.zeros((n, n), dtype)
    grid, a_p, v0, _ = _prepare_eigsh(a, k, grid, seed=0)
    return _build_eigsh(grid, iters).lower(a_p, v0)
