"""paddle.incubate.nn.functional — fused functional ops.

Reference parity: python/paddle/incubate/nn/functional/ (swiglu,
fused_softmax_mask, fused_linear, ...). On TPU these are jnp
compositions XLA fuses into single kernels — the reference's
hand-written CUDA fusions exist because its eager mode can't fuse;
whole-program XLA does it for free (SURVEY.md §7 design stance).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops._dispatch import nary, unary
from ...nn import functional as F

__all__ = [
    "swiglu", "fused_linear", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "fused_dropout_add",
    "fused_bias_act",
 "fused_moe", "fused_ec_moe",]


def swiglu(x, y=None, name=None):
    """SwiGLU activation (reference swiglu_kernel.h): silu(x) * y, with
    x split in half when y is omitted."""
    if y is None:
        def f(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * b

        return unary(f, x, "swiglu")

    def f2(a, b):
        return jax.nn.silu(a) * b

    return nary(f2, [x, y], name="swiglu")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Reference fused_gemm_epilogue: linear with the bias add fused (XLA
    fuses it regardless)."""
    w = weight
    if transpose_weight:
        from ...framework.tensor import Tensor

        w = Tensor._wrap(jnp.swapaxes(
            w._data if isinstance(w, Tensor) else jnp.asarray(w), -1, -2))
    return F.linear(x, w, bias)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) (reference fused_softmax_mask_kernel.h)."""
    def f(v, m):
        return jax.nn.softmax(v.astype(jnp.float32) + m.astype(jnp.float32),
                              axis=-1).astype(v.dtype)

    return nary(f, [x, mask], name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax (reference
    fused_softmax_mask_upper_triangle_kernel.h): upper triangle is
    masked out."""
    def f(v):
        s = v.shape[-1]
        mask = jnp.tril(jnp.ones((v.shape[-2], s), bool))
        vf = jnp.where(mask, v.astype(jnp.float32), -jnp.inf)
        return jax.nn.softmax(vf, axis=-1).astype(v.dtype)

    return unary(f, x, "softmax_mask_fuse_upper_triangle")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y (reference fused_dropout_add_kernel.h)."""
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_bias_act(x, bias=None, act_method="gelu", name=None, **kw):
    """bias + activation (reference fused_bias_act_kernel.h)."""
    out = x if bias is None else x + bias
    act = getattr(F, act_method, None)
    if act_method == "swiglu":
        return swiglu(out)
    if act is None:
        raise ValueError(f"unknown act_method {act_method!r}")
    return act(out)


def fused_moe(x, gate_weight, ffn1_weight, ffn1_bias, ffn2_weight,
              ffn2_bias, ffn1_scale=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True,
              name=None):
    """Fused Mixtral-style MoE FFN (reference
    incubate/nn/functional/fused_moe.py, fused_moe_kernel.cu): softmax
    router over ALL experts → top-k (optionally renormalized) →
    per-expert SwiGLU FFN → combine.

    TPU-first formulation: instead of the reference's CUTLASS
    grouped-GEMM over gathered rows, the experts run as ONE batched
    einsum over the expert dim with the combine weights zeroing
    unselected experts — static shapes, MXU-batched, fully
    differentiable. This is the functional parity surface for
    moderate `num_experts`; the scalable capacity-based dispatch (and
    expert parallelism) is `incubate.distributed.models.moe.MoELayer`.

    Shapes (reference contract): x [b, s, d]; gate_weight [d, E];
    ffn1_weight [E, d, 2*ff] (SwiGLU gate+up fused);
    ffn1_bias [E, 1, 2*ff]; ffn2_weight [E, ff, d]; ffn2_bias [E, 1, d].
    Returns [b, s, d].
    """
    if quant_method != "None":
        raise NotImplementedError(
            "quantized fused_moe weights are not supported (use "
            "nn.quant.weight_only_linear per expert)")
    k = int(moe_topk)

    def f(xv, gw, w1, b1, w2, b2):
        b, s, d = xv.shape
        t = b * s
        xt = xv.reshape(t, d)
        logits = (xt.astype(jnp.float32)
                  @ gw.astype(jnp.float32))          # [t, E]
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = jax.lax.top_k(probs, k)          # [t, k]
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
        n_e = gw.shape[-1]
        # combine weights [t, E]: routing prob on the selected experts,
        # exactly zero elsewhere — the einsum mask
        comb = jnp.zeros((t, n_e), jnp.float32).at[
            jnp.arange(t)[:, None], topi].add(topv)
        h1 = jnp.einsum("td,edg->teg", xt, w1) + b1.reshape(
            1, n_e, -1)                                # [t, E, 2ff]
        g, u = jnp.split(h1, 2, axis=-1)
        hs = jax.nn.silu(g) * u                        # [t, E, ff]
        h2 = jnp.einsum("tef,efd->ted", hs, w2) + b2.reshape(
            1, n_e, -1)                                # [t, E, d]
        out = jnp.einsum("te,ted->td", comb.astype(h2.dtype), h2)
        return out.reshape(b, s, d).astype(xv.dtype)

    return nary(f, [x, gate_weight, ffn1_weight, ffn1_bias, ffn2_weight,
                    ffn2_bias], "fused_moe")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """Expert-choice MoE (reference incubate/nn/functional/fused_ec_moe.py,
    fused_ec_moe kernel; semantics from test_fused_ec_moe_op.py's
    baseline): each EXPERT selects its top-(seq_len // 16) tokens by gate
    logit, applies its two-layer FFN, and scatter-adds prob-weighted
    outputs back over a residual connection.

    TPU-first formulation: per-expert token gather + one batched einsum
    pair + a scatter-add — static shapes (capacity fixed by seq_len), all
    MXU-batched, differentiable end to end.

    Shapes: x [b, s, d]; gate [b, s, e] (logits);
    bmm0_weight [e, d, ff]; bmm0_bias [e, 1, ff];
    bmm1_weight [e, ff, d]; bmm1_bias [e, 1, d]. Returns [b, s, d].
    """
    if act_type not in ("gelu", "relu"):
        raise ValueError("act_type must be 'gelu' or 'relu'")
    from ...ops._dispatch import nary

    def f(xv, g, w0, b0, w1, b1):
        b, s, d = xv.shape
        e = g.shape[-1]
        cap = max(s // 16, 1)
        gates = jax.nn.softmax(g.astype(jnp.float32), axis=-1)
        # per-expert top-capacity TOKENS, ranked by raw logits (the
        # reference gating ranks logits, weights by softmax prob)
        _, top_idx = jax.lax.top_k(
            jnp.swapaxes(g, 1, 2), cap)               # [b, e, cap]
        xg = jnp.take_along_axis(
            xv[:, None], top_idx[..., None], axis=2)  # [b, e, cap, d]
        h = jnp.einsum("becd,edf->becf", xg, w0) + b0[None, :, 0, None]
        h = (jax.nn.gelu(h, approximate=False) if act_type == "gelu"
             else jax.nn.relu(h))
        o = jnp.einsum("becf,efd->becd", h, w1) + b1[None, :, 0, None]
        prob = jnp.take_along_axis(
            jnp.swapaxes(gates, 1, 2), top_idx, axis=-1)  # [b, e, cap]
        contrib = prob[..., None].astype(o.dtype) * o
        out = jnp.zeros_like(xv)
        bidx = jnp.broadcast_to(jnp.arange(b)[:, None, None],
                                top_idx.shape)
        out = out.at[bidx, top_idx].add(contrib)
        return out + xv

    return nary(f, [x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                    bmm1_bias], "fused_ec_moe")
