"""Whole-step compilation of the eager dygraph tape.

This is the TPU answer to the reference's per-op dispatch hot loop
(SURVEY.md §3.1, §7 "hard parts: eager-on-XLA latency"): instead of launching
one XLA computation per op like Paddle launches one CUDA kernel per op, the
entire train step — forward, tape backward, grad clip, optimizer update —
is traced ONCE into a single jitted function over a state pytree, then
executed as one fused XLA program per step with donated buffers.

It works because the eager engine is already trace-transparent: `Tensor._data`
is a jax value, every op is a jnp call recorded through `jax.vjp`, and the
optimizer's update rules are jnp expressions. We thread all mutable state
(parameters, buffers, optimizer accumulators, master weights, step count, RNG
offset) through the traced function as explicit inputs/outputs, temporarily
binding tracers into the live objects during tracing.

Reference parity: replaces the roles of StandaloneExecutor/PirInterpreter
(paddle/fluid/framework/new_executor/pir_interpreter.h:32) and the CINN
compiler entry (paddle/fluid/pir/transforms/build_cinn_pass.cc) — XLA is the
compiler, PJRT the executor.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework import random as _random
from ..observability import RetraceSentinel
from ..profiler import RecordEvent


def _tree_data(x):
    """Map Tensors (possibly nested in lists/tuples/dicts) to jax arrays."""
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_tree_data(v) for v in x)
    if isinstance(x, dict):
        return {k: _tree_data(v) for k, v in x.items()}
    return x


def _tree_wrap(x):
    if isinstance(x, jax.Array):
        return Tensor._wrap(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_tree_wrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _tree_wrap(v) for k, v in x.items()}
    return x


def _commit_uncommitted(state):
    """Single-device flavor of the layout canonicalization: a checkpoint
    restore leaves the params committed to their device while freshly
    created scalars (guard state, rng offset, step count) are uncommitted.
    jit keys committed and uncommitted arguments differently, and every
    output of the first call comes back committed — so the second call
    after a restore would compile one extra executable. Returns the state
    with the uncommitted leaves committed to the same device, or None when
    nothing is committed (fresh run: leave everything uncommitted, jit
    outputs then stay uncommitted too and the cache key is stable)."""
    leaves = [l for l in jax.tree_util.tree_leaves(state)
              if isinstance(l, jax.Array)]
    dev = next((next(iter(l.devices())) for l in leaves
                if getattr(l, "_committed", False)), None)
    if dev is None or not all(
            len(l.devices()) == 1 for l in leaves):   # mesh programs: no-op
        return None

    def _commit(leaf):
        if isinstance(leaf, jax.Array) and not getattr(
                leaf, "_committed", True):
            return jax.device_put(leaf, dev)
        return leaf

    return jax.tree_util.tree_map(_commit, state)


def _unwrap_optimizer(opt):
    """Follow wrapper chains (HybridParallelOptimizer, sharding wrappers) to
    the Optimizer that owns the state dicts."""
    seen = set()
    while hasattr(opt, "_inner_opt") and id(opt) not in seen:
        seen.add(id(opt))
        opt = opt._inner_opt
    return opt


class TrainStep:
    """Compile `(batch) -> loss` + backward + optimizer into one XLA program.

    Usage::

        step = TrainStep(model, loss_fn, optimizer)     # loss_fn(model, *batch)
        for batch in loader:
            loss = step(*batch)                          # one fused XLA launch

    `loss_fn(model, *batch_tensors)` must return a scalar loss Tensor. All
    batch entries with a given set of shapes/dtypes compile once (shape-keyed
    executable cache — jax.jit's own).
    """

    def __init__(self, model, loss_fn, optimizer, donate=True,
                 accumulate_steps=1, accum_steps=None, scaler=None,
                 guard_nonfinite=None, numerics=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer             # outer (may be a wrapper)
        self._opt = _unwrap_optimizer(optimizer)  # state owner
        # in-graph non-finite guard (jit/nonfinite_guard.py): gate the
        # whole state update on a traced found_inf so one NaN/inf step
        # cannot destroy the only copy of the donated params; a bound
        # GradScaler additionally runs its dynamic loss scale as traced
        # state (zero host syncs, zero retraces)
        from .nonfinite_guard import GuardSpec

        self._guard = (GuardSpec(scaler)
                       if (scaler is not None or guard_nonfinite)
                       else None)

        self._params = None   # resolved lazily: optimizer may create accums on 1st step
        self._buffers = None
        self._jitted = None
        self._step_count = 0
        # training-numerics observatory (ISSUE 15): the generic tape
        # path has no layer chunks, so each trainable PARAMETER is its
        # own stats row (grad/param sq-norm, update ratio, finite flag;
        # no scanned activations). Monitor built lazily in _build once
        # the param set is resolved.
        self._numerics_opt = numerics
        self._numerics = None
        # retrace sentinel (ISSUE 12): every dispatch records its
        # abstract signature; an unexpected executable-cache miss is
        # attributed to the argument leaf that changed
        self._sentinel = RetraceSentinel(type(self).__name__)
        # donation is a pure perf lever (aliased state buffers) — on the
        # legacy jaxlib (0.4.x CPU) it CORRUPTS memory under conv-sized
        # programs on a host mesh (NaN losses, then hard aborts in later
        # jits — measured via tests/test_vision.py), so it is forced off
        # there
        import sys as _sys

        _legacy = getattr(_sys.modules.get("paddle_tpu"),
                          "jax_compat_legacy", False)
        self._donate = donate and not _legacy
        # gradient accumulation INSIDE the fused program (the reference's
        # no_sync/gradient-merge loop, compiled): the batch's dim 0 splits
        # into `accumulate_steps` micro-batches; micro backwards accumulate
        # on the tape's leaf grads and the optimizer steps once. Gradient
        # COMM happens only at the boundary: after the last microbatch the
        # model wrapper's apply_collective_grads() issues the bucket
        # collectives (stage-2 bucketer), so under GSPMD the per-bucket
        # reduce-scatters overlap the optimizer/next-step compute instead
        # of serializing after every microbatch.
        if accum_steps is not None:
            if int(accumulate_steps) not in (1, int(accum_steps)):
                raise ValueError(
                    f"conflicting accumulate_steps={accumulate_steps} "
                    f"and accum_steps={accum_steps}")
            accumulate_steps = accum_steps
        self.accumulate_steps = int(accumulate_steps)

    # -- input pipeline -------------------------------------------------
    def input_sharding(self):
        """The placement batches should be staged on so the compiled step
        never reshards its inputs: on a dp/sharding mesh, dim 0 split 1/N
        over the data axis; on one chip, None (default-device placement —
        identical to what `paddle.to_tensor` produces, so prefetched and
        hand-fed batches hit the same executable)."""
        from jax.sharding import NamedSharding

        from ..distributed import env as denv

        mesh = next(
            (p._data.sharding.mesh for p in self.model.parameters()
             if isinstance(getattr(p._data, "sharding", None),
                           NamedSharding)), None)
        if mesh is None:
            return None
        return denv.data_sharding(mesh=mesh)

    def prefetch(self, loader, depth=2, **kw):
        """Wrap `loader` in an `io.DevicePrefetcher` bound to this step's
        input sharding — batches land on device, already placed, while
        the previous step computes (zero-stall input delivery)::

            step = TrainStep(model, loss_fn, opt)
            for ids, labels in step.prefetch(loader):
                loss = step(ids, labels)
        """
        from ..io.device_prefetcher import DevicePrefetcher

        kw.setdefault("sharding", self.input_sharding())
        return DevicePrefetcher(loader, depth=depth, **kw)

    # -- state plumbing -------------------------------------------------
    def _resolve_slots(self):
        self._params = [p for p in self.model.parameters() if p.trainable]
        self._buffers = list(self.model.buffers())

    def _extract_state(self):
        state = {
            "params": [p._data for p in self._params],
            "buffers": [b._data for b in self._buffers],
            "opt": self._opt.opt_state_pytree(),
            "rng_offset": jnp.asarray(_random.default_generator()._offset, jnp.int64
                                      if jax.config.jax_enable_x64 else jnp.int32),
        }
        if self._guard is not None:
            state["guard"] = self._guard.init_state()
        return state

    def _inject_state(self, state):
        for p, d in zip(self._params, state["params"]):
            p._data = d
        for b, d in zip(self._buffers, state["buffers"]):
            b._data = d
        self._opt.load_opt_state_pytree(state["opt"])
        _random.default_generator()._offset = state["rng_offset"]
        if self._guard is not None and "guard" in state:
            self._guard.writeback(state["guard"])

    # -- the traced step ------------------------------------------------
    def _build(self, example_batch):
        self._resolve_slots()
        opt = self.optimizer        # outer wrapper drives the step
        inner = self._opt           # state owner gets the lr patch
        from ..observability.numerics import (
            NumericsMonitor, monitor_enabled,
        )

        if (bool(self._numerics_opt) if self._numerics_opt is not None
                else monitor_enabled()) and self._params:
            self._numerics = NumericsMonitor(
                type(self).__name__, len(self._params),
                row_labels=[p.name or f"param{i}"
                            for i, p in enumerate(self._params)])
        nm = self._numerics is not None

        # pin state OUTPUT layouts to the input layouts: without this,
        # GSPMD may choose a different sharding for an updated param than
        # the one the user placed, so call 2 sees new input layouts and
        # recompiles (one stray executable per divergent layout)
        from jax.sharding import NamedSharding, PartitionSpec

        # ... and canonicalize the INPUT layouts first: on a mesh program
        # every output lands mesh-committed, so any state leaf that starts
        # uncommitted/single-device (fresh optimizer scalars, rng offset)
        # would key one extra executable on call 2. Replicate those onto
        # the params' mesh up front.
        mesh = next((p._data.sharding.mesh for p in self._params
                     if isinstance(getattr(p._data, "sharding", None),
                                   NamedSharding)), None)
        if mesh is not None:
            def _canon(leaf):
                if not isinstance(leaf, jax.Array):
                    return leaf
                sh = getattr(leaf, "sharding", None)
                if not isinstance(sh, NamedSharding):
                    return jax.device_put(leaf, NamedSharding(
                        mesh, PartitionSpec()))
                # normalize trailing Nones: P('mp', None) and P('mp')
                # are the same placement but UNEQUAL jit cache keys, and
                # compiled outputs come back in the stripped form
                axes = list(sh.spec)
                while axes and axes[-1] is None:
                    axes.pop()
                norm = PartitionSpec(*axes)
                if norm != sh.spec:
                    return jax.device_put(leaf,
                                          NamedSharding(sh.mesh, norm))
                return leaf

            canon_state = jax.tree_util.tree_map(_canon,
                                                 self._extract_state())
            self._inject_state(canon_state)
        else:
            canon_state = _commit_uncommitted(self._extract_state())
            if canon_state is not None:
                self._inject_state(canon_state)

        ref_state = self._extract_state()
        ref_shardings = jax.tree_util.tree_map(
            lambda leaf: leaf.sharding
            if isinstance(leaf, jax.Array)
            and isinstance(getattr(leaf, "sharding", None), NamedSharding)
            else None, ref_state)

        def _repin(new_state):
            return jax.tree_util.tree_map(
                lambda leaf, sh: jax.lax.with_sharding_constraint(leaf, sh)
                if sh is not None else leaf,
                new_state, ref_shardings)

        acc = self.accumulate_steps
        if acc > 1:
            # every top-level batch Tensor splits along dim 0; a mixed bag
            # of batch-major tensors and e.g. [seq, seq] masks would be
            # silently mis-sliced, so insist on one shared batch size
            sizes = {d.shape[0] for d in example_batch
                     if hasattr(d, "shape") and d.ndim > 0}
            if len(sizes) > 1:
                raise ValueError(
                    f"accumulate_steps={acc} needs all batch tensors "
                    f"batch-major with one shared dim-0 size; got {sizes}")
            if sizes and next(iter(sizes)) % acc:
                raise ValueError(
                    f"batch size {next(iter(sizes))} is not divisible by "
                    f"accumulate_steps={acc}")

        guard = self._guard
        scaling = guard is not None and guard.scaling

        def step_fn(state, lr, batch):
            self._inject_state(state)
            gst = state.get("guard")
            scale_t = gst["scale"] if scaling else None
            batch_t = _tree_wrap(batch)

            def backward(loss_tensor):
                # dynamic loss scaling: backward through loss*scale, so
                # small bf16 grads survive; the unscale happens on the
                # grads below, fused into the same program
                if scale_t is None:
                    loss_tensor.backward()
                else:
                    (loss_tensor
                     * Tensor._wrap(scale_t.astype(
                         loss_tensor._data.dtype))).backward()

            if acc > 1:
                losses = []
                for m in range(acc):
                    micro = [
                        Tensor._wrap(t._data.reshape(
                            (acc, t._data.shape[0] // acc)
                            + tuple(t._data.shape[1:]))[m])
                        if isinstance(t, Tensor) else t for t in batch_t]
                    ml = self.loss_fn(self.model, *micro) * (1.0 / acc)
                    backward(ml)
                    losses.append(ml._data)
                loss = Tensor._wrap(sum(losses))
            else:
                loss = self.loss_fn(self.model, *batch_t)
                backward(loss)
            # gradient-comm boundary: all microbatch backwards are done,
            # flush the deferred bucket collectives (one per bucket)
            sync = getattr(self.model, "apply_collective_grads", None)
            if callable(sync):
                sync()
            # the in-graph guard: ONE fused finiteness reduction over
            # the (still scaled) grads; unscale in the same program
            found = None
            if guard is not None:
                from .nonfinite_guard import all_finite

                grads = [p.grad._data for p in self._params
                         if p.grad is not None]
                found = ~all_finite(grads)
                if scale_t is not None:
                    inv = 1.0 / scale_t
                    for p in self._params:
                        if p.grad is None:
                            continue
                        g = p.grad._data
                        p.grad._data = (g.astype(jnp.float32)
                                        * inv).astype(g.dtype)
            # numerics rows read the (unscaled) tape grads — captured
            # before opt.step()/clear_grad consumes them
            nm_grads = None
            if nm:
                nm_grads = [p.grad._data if p.grad is not None else None
                            for p in self._params]
            # freeze lr at the traced scalar for this step (declared
            # protocol: Optimizer.get_lr honors _lr_override)
            with inner.lr_frozen(lr):
                if inner.get_lr() is not lr:
                    raise RuntimeError(
                        f"{type(inner).__name__}.get_lr() ignores "
                        "_lr_override — it would bake a stale host lr "
                        "into the compiled step; honor the traced-step "
                        "protocol (call super().get_lr() or check "
                        "self._lr_override)")
                opt.step()
            opt.clear_grad()
            new_state = _repin(self._extract_state())
            if guard is not None:
                from .nonfinite_guard import gate

                core = {k: v for k, v in new_state.items()
                        if k != "guard"}
                old = {k: v for k, v in state.items() if k != "guard"}
                new_state = gate(found, core, old)
                new_state["guard"] = guard.update(gst, found)
            if not nm:
                return loss._data, new_state
            # ---- per-parameter numerics rows (ISSUE 15): grads were
            # unscaled above, updates read the GATED new params (zero
            # on a guard-skipped step); no scanned activations here
            rows = []
            f32 = jnp.float32
            for i in range(len(self._params)):
                g = nm_grads[i]
                old_p = state["params"][i].astype(f32)
                new_p = new_state["params"][i].astype(f32)
                if g is not None and jnp.issubdtype(g.dtype,
                                                    jnp.floating):
                    g32 = g.astype(f32)
                    g_sq = jnp.sum(jnp.square(g32))
                    # finiteness DERIVES from the square-sum like the
                    # scan paths (DECISIONS §21) — no second O(params)
                    # pass; the guard keeps its own exact fold
                    g_bad = (~jnp.isfinite(g_sq)).astype(f32)
                else:
                    g_sq = f32(0.0)
                    g_bad = f32(0.0)
                rows.append(jnp.stack([
                    g_sq, jnp.sum(jnp.square(old_p)),
                    jnp.sum(jnp.square(new_p - old_p)),
                    f32(0.0), f32(0.0), g_bad, f32(0.0), f32(0.0)]))
            return loss._data, new_state, jnp.stack(rows)

        donate = (0,) if self._donate else ()
        # persistent AOT executable cache (ISSUE 17): with
        # PADDLE_TPU_COMPILE_CACHE set, a warm process deserializes the
        # previously compiled step instead of retracing+recompiling;
        # unset, this IS jax.jit
        from .compile_cache import cached_jit

        self._jitted = cached_jit(step_fn, donate_argnums=donate,
                                  label=type(self).__name__)
        # live-buffer attribution (ISSUE 14): params/opt-state/buffers
        # claim their resident bytes at mem.live scrape time (weakly
        # tracked — a dropped step stops claiming)
        from ..observability.memory import live_registry

        live_registry().track(self)

    def __call__(self, *batch):
        batch_data = _tree_data(list(batch))
        if self._jitted is None:
            # the global generator offset may be a device array committed to
            # another step's mesh (jit outputs rebind it); a foreign sharding
            # on the first call would key one extra executable, so drop the
            # commitment before the initial trace
            gen = _random.default_generator()
            if isinstance(gen._offset, jax.Array):
                gen._offset = int(gen._offset)
            # run optimizer accumulator creation eagerly once so the state
            # pytree is complete before tracing (Optimizer.warmup_state —
            # the declared dry-run protocol)
            self._warmup_accumulators()
            self._build(batch_data)
        state = self._extract_state()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        self._sentinel.observe((state, lr, batch_data),
                               names=("state", "lr", "batch"))
        try:
            # comm watchdog (reference comm_task_manager.h:37): the dispatch
            # blocks when the device queue is full behind a dead collective,
            # so guard it — without forcing a sync that would break async
            # dispatch pipelining
            from ..distributed import comm_watchdog

            with RecordEvent("TrainStep"), \
                    comm_watchdog.watch(f"TrainStep#{self._step_count}"):
                out = self._jitted(state, lr, batch_data)
            if self._numerics is not None:
                loss_data, new_state, nstats = out
                self._numerics.on_step(nstats)   # deferred readback
            else:
                loss_data, new_state = out
            self._step_count += 1
        except Exception as e:
            # OOM forensics (ISSUE 14): a RESOURCE_EXHAUSTED at the
            # dispatch boundary dumps the live-buffer attribution + the
            # step's compiled memory profile through the flight
            # recorder before propagating (AOT analysis — re-lowering
            # reads only avals, so consumed donated buffers are fine)
            from ..observability import memory as _mem

            if _mem.is_oom_error(e):
                _mem.dump_oom(
                    e, step=type(self).__name__,
                    profile=lambda: _mem.CompiledMemoryProfile
                    .from_jitted(self._jitted, state, lr, batch_data))
            # a tracing error leaves tracers bound in the live objects;
            # restore the concrete state so the model stays usable
            self._inject_state(state)
            raise
        self._inject_state(new_state)
        # advance host-side schedulers
        sched = getattr(self._opt, "_learning_rate", None)
        if hasattr(sched, "step"):
            sched.step()
        return Tensor._wrap(loss_data)

    # -- telemetry surface ----------------------------------------------
    def retrace_stats(self):
        """The sentinel's receipt: {'signatures', 'calls', 'hits',
        'unexpected', 'events'} — signatures is the trace/compile count
        the old hand-written probes asserted on."""
        return self._sentinel.stats()

    def cost_analysis(self, *batch):
        """HLO-derived per-step accounting (ISSUE 12): flops and bytes
        per step from ``compiled.cost_analysis()`` plus the per-axis
        collective byte census, published as ``hlo.*`` registry gauges.
        Requires the step to have run (or at least traced) once."""
        if self._jitted is None:
            raise RuntimeError(
                "cost_analysis needs a built step — call the step once "
                "(or warm it up) first")
        from ..observability.hlo_costs import cost_analysis_of

        state = self._extract_state()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        return cost_analysis_of(self._jitted, state, lr,
                                _tree_data(list(batch)))

    def memory_profile(self, *batch, top_k=8, publish=True):
        """Compiled-step HBM accounting (ISSUE 14): AOT lower+compile
        this step for ``batch`` and read the XLA buffer-assignment
        stats — argument/output/temp/alias bytes, the peak they imply,
        and the top-K largest buffers with shapes and op provenance —
        WITHOUT executing anything. Publishes ``mem.compiled.<step>.*``
        gauges; with the persistent compile cache warm this is cheap.
        Requires the step to have run (or at least traced) once."""
        if self._jitted is None:
            raise RuntimeError(
                "memory_profile needs a built step — call the step "
                "once (or warm it up) first")
        from ..observability.memory import CompiledMemoryProfile

        state = self._extract_state()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        prof = CompiledMemoryProfile.from_jitted(
            self._jitted, state, lr, _tree_data(list(batch)),
            top_k=top_k)
        if publish:
            prof.publish(name=type(self).__name__)
        return prof

    def _mem_owners(self):
        """Live-buffer attribution providers (observability.memory):
        which resident arrays this step's state accounts for."""
        if self._params is None:
            self._resolve_slots()
        # shard-backed params (owned by a sharded-storage scan step)
        # are skipped: reading them would gather on scrape
        owners = {"params": [p._data for p in self._params
                             if not getattr(type(p), "_shard_backed",
                                            False)],
                  "buffers": [b._data for b in self._buffers]}
        try:
            owners["opt_state"] = jax.tree_util.tree_leaves(
                self._opt.opt_state_pytree())
        except Exception:
            pass
        return owners

    def _warmup_accumulators(self):
        """Complete the optimizer state pytree before tracing via the
        declared Optimizer.warmup_state protocol (no monkeypatching — a
        subclass overriding step()/_append_optimize_op keeps working as
        long as it honors the traced-step protocol, optimizer.py)."""
        self._resolve_slots()
        self._opt.warmup_state(self._params)
        # sharded-optimizer wrappers place their state layouts now so the
        # first compile already sees them (ZeRO-1 as sharding annotations)
        outer = self.optimizer
        while outer is not self._opt:
            if hasattr(outer, "reshard_state"):
                outer.reshard_state()
            outer = getattr(outer, "_inner_opt", self._opt)
