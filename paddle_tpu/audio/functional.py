"""paddle.audio.functional parity (reference audio/functional/functional.py
and window_utils.py). Pure jnp — every helper is jit-safe."""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops._dispatch import ensure_tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk=False):
    """reference functional.py:29 (Slaney by default, HTK optional)."""
    is_t = isinstance(freq, Tensor)
    f = freq._data if is_t else freq
    if htk:
        out = 2595.0 * jnp.log10(1.0 + jnp.asarray(f, jnp.float32) / 700.0)
        return Tensor._wrap(out) if is_t else float(out)
    f = jnp.asarray(f, jnp.float32)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    mels = jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(f / min_log_hz) / logstep, mels)
    return Tensor._wrap(mels) if is_t else float(mels)


def mel_to_hz(mel, htk=False):
    """reference functional.py:83."""
    is_t = isinstance(mel, Tensor)
    m = mel._data if is_t else jnp.asarray(mel, jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (jnp.asarray(m, jnp.float32) / 2595.0) - 1.0)
        return Tensor._wrap(out) if is_t else float(out)
    m = jnp.asarray(m, jnp.float32)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    freqs = jnp.where(m >= min_log_mel,
                      min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                      freqs)
    return Tensor._wrap(freqs) if is_t else float(freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    """reference functional.py:126."""
    lo = hz_to_mel(f_min, htk=htk)
    hi = hz_to_mel(f_max, htk=htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return Tensor._wrap(mel_to_hz(Tensor._wrap(mels), htk=htk)._data
                        .astype(dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    """reference functional.py:166."""
    return Tensor._wrap(jnp.linspace(0, sr / 2, 1 + n_fft // 2)
                        .astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]
    (reference functional.py:189)."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)._data
    melfreqs = mel_frequencies(n_mels + 2, f_min, f_max, htk)._data
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor._wrap(weights.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """reference functional.py:262."""
    x = ensure_tensor(spect)._data.astype(jnp.float32)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor._wrap(log_spec)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (reference functional.py:306)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k) * 2.0
    if norm == "ortho":
        dct = dct.at[:, 0].multiply(math.sqrt(1.0 / (4 * n_mels)))
        dct = dct.at[:, 1:].multiply(math.sqrt(1.0 / (2 * n_mels)))
    return Tensor._wrap(dct.astype(dtype))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Window helper (reference window_utils.py get_window)."""
    if isinstance(window, (tuple, list)):
        window, beta = window
    n = win_length if fftbins else win_length - 1
    i = jnp.arange(win_length, dtype=jnp.float32)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * i / n)
    elif window == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * i / n)
    elif window == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * math.pi * i / n)
             + 0.08 * jnp.cos(4 * math.pi * i / n))
    elif window in ("rect", "boxcar", "ones"):
        w = jnp.ones((win_length,), jnp.float32)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor._wrap(w.astype(dtype))
