"""Continuous-batching serving tier tests (ISSUE 6).

Scheduler invariants over the paged KV cache: no slot or page leaks
across admit/evict/retire churn, preempt-then-resume token parity,
chunked-prefill logits parity vs the one-shot prefill, FIFO fairness
under saturation, metrics counters consistent with observed events —
plus the satellite regressions: non-raising capacity probes with
atomic rollback on failed allocate/reserve, and per-request RNG
streams that make a request's tokens independent of its batch
neighbours.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=96,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _prompts(n, seed=0, lens=(5, 11, 19, 8, 14, 26)):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 64, (lens[i % len(lens)],)).astype(np.int32)
            for i in range(n)]


# ---------------------------------------------------------------------------
# satellite: capacity probes + atomic rollback (kv_cache)
# ---------------------------------------------------------------------------

class TestCapacityProbes:
    def _cache(self, num_pages=9, max_slots=2, pages_per_seq=4,
               page_size=8):
        from paddle_tpu.inference.kv_cache import PagedKVCache

        return PagedKVCache(num_layers=1, num_kv_heads=2, head_dim=4,
                            num_pages=num_pages, page_size=page_size,
                            max_slots=max_slots,
                            pages_per_seq=pages_per_seq)

    def test_can_allocate_matches_allocate(self):
        c = self._cache()          # 8 usable pages, 2 slots
        assert c.can_allocate(8 * 4)          # pages_per_seq cap
        assert not c.can_allocate(8 * 4 + 1)  # over per-seq cap
        s0 = c.allocate(8 * 4)                # 4 pages
        assert c.can_allocate(32)             # 4 pages left
        s1 = c.allocate(32)
        assert not c.can_allocate(1)          # no slots left
        c.free(s1)
        assert c.can_allocate(32) and not c.can_allocate(33)
        c.free(s0)

    def test_can_reserve(self):
        c = self._cache()
        s = c.allocate(8)                     # 1 page
        assert c.can_reserve(s, 32)
        assert not c.can_reserve(s, 33)       # pages_per_seq
        assert not c.can_reserve(999, 8)      # unknown slot
        other = c.allocate(8 * 4)
        # pool: 8 - 1 - 4 = 3 free; growing to 4 pages needs 3 more
        assert c.can_reserve(s, 32)
        c.free(other)

    def _snapshot(self, c):
        return (np.array(c.page_tables), np.array(c.seq_lens),
                np.array(c.active), list(c._free_pages),
                list(c._free_slots),
                {k: list(v) for k, v in c._slot_pages.items()})

    def _assert_unchanged(self, c, snap):
        pt, sl, act, fp, fs, sp = snap
        np.testing.assert_array_equal(np.asarray(c.page_tables), pt)
        np.testing.assert_array_equal(np.asarray(c.seq_lens), sl)
        np.testing.assert_array_equal(np.asarray(c.active), act)
        assert c._free_pages == fp
        assert c._free_slots == fs
        assert {k: list(v) for k, v in c._slot_pages.items()} == sp

    def test_failed_allocate_is_atomic(self):
        c = self._cache()
        s = c.allocate(8 * 3)                 # 3 of 8 pages
        snap = self._snapshot(c)
        with pytest.raises(RuntimeError):
            c.allocate(8 * 6)                 # needs 6, only 5 free
        self._assert_unchanged(c, snap)
        with pytest.raises(RuntimeError):
            c.allocate(8 * 4 + 1)             # over pages_per_seq
        self._assert_unchanged(c, snap)
        c.allocate(1)
        snap = self._snapshot(c)
        with pytest.raises(RuntimeError):
            c.allocate(1)                     # no slots
        self._assert_unchanged(c, snap)

    def test_failed_reserve_is_atomic(self):
        c = self._cache()
        s0 = c.allocate(8)                    # 1 page
        s1 = c.allocate(8 * 4)                # 4 pages -> 3 free
        snap = self._snapshot(c)
        with pytest.raises(RuntimeError, match="exceeds"):
            c.reserve(s0, 8 * 4 + 8)          # over pages_per_seq cap
        self._assert_unchanged(c, snap)
        c.free(s1)                            # 7 free
        c2 = self._cache(num_pages=4, pages_per_seq=4)  # 3 usable
        sa = c2.allocate(8)
        sb = c2.allocate(8)
        snap2 = self._snapshot(c2)
        with pytest.raises(RuntimeError, match="exhausted"):
            c2.reserve(sa, 8 * 3)             # needs 2 more, 1 free
        self._assert_unchanged(c2, snap2)

    def test_probes_do_not_mutate(self):
        c = self._cache()
        s = c.allocate(8)
        snap = self._snapshot(c)
        c.can_allocate(64)
        c.can_reserve(s, 64)
        c.pages_needed(100)
        self._assert_unchanged(c, snap)


# ---------------------------------------------------------------------------
# satellite: per-request RNG streams
# ---------------------------------------------------------------------------

class TestPerSlotSampling:
    def test_greedy_is_argmax(self):
        from paddle_tpu.nn.functional.sampling import \
            sample_logits_per_slot

        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((4, 32)), jnp.float32)
        got = sample_logits_per_slot(
            logits, jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int32),
            greedy=True)
        np.testing.assert_array_equal(
            np.asarray(got), np.argmax(np.asarray(logits), -1))

    def test_stream_depends_only_on_seed_and_position(self):
        """Row i's sample is a function of (logits_i, seed_i, pos_i) —
        shuffling the other rows must not change it."""
        from paddle_tpu.nn.functional.sampling import \
            sample_logits_per_slot

        rng = np.random.default_rng(1)
        logits = rng.standard_normal((4, 32)).astype(np.float32)
        seeds = np.asarray([7, 8, 9, 10], np.int32)
        pos = np.asarray([3, 5, 9, 2], np.int32)
        a = np.asarray(sample_logits_per_slot(
            jnp.asarray(logits), seeds, pos, temperature=1.0))
        perm = [2, 0, 3, 1]
        b = np.asarray(sample_logits_per_slot(
            jnp.asarray(logits[perm]), seeds[perm], pos[perm],
            temperature=1.0))
        np.testing.assert_array_equal(a[perm], b)
        # and the same (seed, pos) reproduces bit-identically
        c = np.asarray(sample_logits_per_slot(
            jnp.asarray(logits), seeds, pos, temperature=1.0))
        np.testing.assert_array_equal(a, c)

    def test_position_advances_stream(self):
        from paddle_tpu.nn.functional.sampling import \
            sample_logits_per_slot

        rng = np.random.default_rng(2)
        logits = jnp.asarray(rng.standard_normal((1, 500)), jnp.float32)
        seeds = jnp.zeros(1, jnp.int32)
        draws = {int(np.asarray(sample_logits_per_slot(
            logits, seeds, jnp.asarray([p], jnp.int32)))[0])
            for p in range(8)}
        assert len(draws) > 1    # positions decorrelate the stream


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------

class TestChunkedPrefill:
    def test_chunked_logits_match_one_shot(self, model):
        """Three 8-token chunks of a 19-token prompt produce the same
        next-token logits as the full forward pass."""
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(model, max_slots=2, max_len=64, page_size=8,
                            chunk_size=8, prefill_batch=1)
        prompt = _prompts(1, seed=3, lens=(19,))[0]
        slot = eng.cache.allocate(len(prompt))
        logits = None
        for start in range(0, len(prompt), 8):
            chunk = prompt[start:start + 8]
            bucket = eng._chunk_bucket(len(chunk))
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :len(chunk)] = chunk
            out = eng.prefill_step(
                eng._param_data(), eng._buffers, eng._meta(), ids,
                np.asarray([slot], np.int32),
                np.asarray([start], np.int32),
                np.asarray([start + len(chunk)], np.int32),
                np.asarray([0], np.int32))
            _tok, logits, buffers, meta = out
            eng._commit(buffers, meta)
        want = np.asarray(
            model(paddle.to_tensor(prompt[None].astype(np.int64)))
            ._data, np.float32)[0, -1]
        got = np.asarray(logits, np.float32)[0]
        assert float(np.max(np.abs(got - want))) < 2e-4

    def test_serve_matches_generate(self, model):
        """Greedy continuous serving with mid-flight admission equals
        per-request generate() — and the decode step never retraces."""
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(model, max_slots=3, max_len=64, page_size=8,
                            chunk_size=8)
        handles = []
        for i, p in enumerate(_prompts(5, seed=4)):
            handles.append(eng.submit(p, 5 + (i % 3)))
            eng.step()                        # admissions interleave
        eng.run(max_steps=3000)
        for h in handles:
            ref = model.generate(
                np.asarray(h.request.prompt)[None],
                max_new_tokens=h.request.max_new_tokens,
                use_cache="paged")
            assert np.asarray(ref._data)[0].tolist() == h.output_tokens
        assert eng.compile_counts()["decode_traces"] == 1
        leaks = eng.leak_check()
        assert leaks["free_pages"] == leaks["total_pages"]
        assert leaks["free_slots"] == leaks["total_slots"]
        assert leaks["resident_slot_pages"] == 0


# ---------------------------------------------------------------------------
# scheduler invariants
# ---------------------------------------------------------------------------

class TestSchedulerInvariants:
    def _serve(self, model, num_pages=None, seeds=True, max_new=10,
               slots=4, burst=1):
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(model, max_slots=slots, max_len=48,
                            page_size=8, chunk_size=8,
                            num_pages=num_pages, do_sample=True,
                            temperature=1.0, decode_burst=burst)
        hs = [eng.submit(p, max_new, seed=100 + i)
              for i, p in enumerate(_prompts(4, seed=5))]
        eng.run(max_steps=5000)
        return eng, hs

    def test_preempt_resume_token_parity(self, model):
        full_eng, full = self._serve(model, num_pages=None)
        tight_eng, tight = self._serve(model, num_pages=9)
        assert tight_eng.metrics.preemptions >= 1
        assert full_eng.metrics.preemptions == 0
        for a, b in zip(full, tight):
            assert a.output_tokens == b.output_tokens
        # preempted requests recorded a resume admission
        assert tight_eng.metrics.resumed == sum(
            h.preemptions for h in tight)

    def test_no_leaks_after_churn_with_preemptions(self, model):
        eng, hs = self._serve(model, num_pages=9, burst=2)
        assert all(h.done for h in hs)
        leaks = eng.leak_check()
        assert leaks["free_pages"] == leaks["total_pages"]
        assert leaks["free_slots"] == leaks["total_slots"]
        assert leaks["resident_slot_pages"] == 0
        assert eng.compile_counts()["decode_traces"] == 1

    def test_fifo_under_saturation(self, model):
        """Equal-length requests on a saturated engine finish in
        arrival order — nobody bypasses the queue head."""
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(model, max_slots=2, max_len=32, page_size=8,
                            chunk_size=8)
        hs = [eng.submit(p, 6) for p in _prompts(6, seed=6, lens=(9,))]
        eng.run(max_steps=3000)
        finish = [(h.finish_time, h.request.rid) for h in hs]
        assert [rid for _, rid in sorted(finish)] == \
            [h.request.rid for h in hs]

    def test_priority_picks_victim(self, model):
        """When the pool dries up, the LOW priority sequence is the one
        preempted."""
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(model, max_slots=2, max_len=48, page_size=8,
                            chunk_size=8, num_pages=7)   # 6 usable
        lo = eng.submit(_prompts(1, seed=7, lens=(16,))[0], 20,
                        priority=0)
        hi = eng.submit(_prompts(1, seed=8, lens=(16,))[0], 20,
                        priority=1)
        eng.run(max_steps=4000)
        assert lo.preemptions >= 1
        assert hi.preemptions == 0
        assert lo.done and hi.done

    def test_metrics_consistency(self, model):
        eng, hs = self._serve(model, num_pages=9)
        snap = eng.metrics_snapshot()
        assert snap["submitted"] == len(hs) == snap["finished"]
        assert snap["generated_tokens"] == sum(
            len(h.output_tokens) for h in hs)
        assert snap["admitted"] == snap["finished"] + snap["resumed"]
        assert snap["preemptions"] == sum(h.preemptions for h in hs)
        assert snap["queue_depth"] == 0 and snap["running"] == 0
        assert snap["ttft_p50_s"] is not None
        assert snap["ttft_p99_s"] >= snap["ttft_p50_s"]

    def test_burst_matches_single_step(self, model):
        """decode_burst only changes scheduling granularity, never the
        tokens."""
        a_eng, a = self._serve(model, burst=1)
        b_eng, b = self._serve(model, burst=4)
        for x, y in zip(a, b):
            assert x.output_tokens == y.output_tokens

    def test_burst_lookahead_respects_budget(self, model):
        """A pool sized exactly for prompt+budget never preempts: the
        burst lookahead is capped by the remaining token budget, so no
        pages are reserved for post-retirement garbage tokens
        (regression: that used to force a self-preemption + full
        re-prefill on the last burst)."""
        from paddle_tpu.serving import ServingEngine

        p = _prompts(1, seed=14, lens=(5,))[0]
        # prompt 5 + budget 6 = 11 tokens = 3 pages of 4 — the pool
        # has exactly those 3 (+ trash), while max_len leaves room for
        # the uncapped lookahead to ask for a 4th
        eng = ServingEngine(model, max_slots=1, max_len=32, page_size=4,
                            num_pages=4, chunk_size=8, decode_burst=4)
        h = eng.submit(p, 6)
        eng.run(max_steps=500)
        assert h.done and len(h.output_tokens) == 6
        assert eng.metrics.preemptions == 0

    def test_priority_never_inverted_on_growth(self, model):
        """ensure_token_capacity must not evict a higher-priority
        neighbour to grow a lower-priority slot — the low one
        sacrifices itself (regression: priority inversion)."""
        from paddle_tpu.inference.kv_cache import PagedKVCache
        from paddle_tpu.serving.metrics import ServingMetrics
        from paddle_tpu.serving.request import (Request, RequestHandle,
                                                RequestState)
        from paddle_tpu.serving.scheduler import RequestScheduler

        cache = PagedKVCache(num_layers=1, num_kv_heads=2, head_dim=4,
                             num_pages=3, page_size=8, max_slots=3,
                             pages_per_seq=4)   # 2 usable pages
        sched = RequestScheduler(cache, ServingMetrics())

        def resident(prio, seq):
            h = RequestHandle(Request(seq, np.ones(8, np.int32), 8,
                                      priority=prio))
            h.arrival_seq = seq
            h.slot = cache.allocate(8)        # 1 page, context full
            h.state = RequestState.RUNNING
            h.output_tokens = [1]             # context = 8
            sched.running[h.slot] = h
            return h

        lo = resident(0, 0)
        hi = resident(1, 1)
        assert not cache.can_reserve(lo.slot, 9)   # pool dry
        # low-priority growth: self-preempt, never evict hi
        assert sched.ensure_token_capacity(lo.slot, 1) is False
        assert lo.state is RequestState.WAITING and lo.preemptions == 1
        assert hi.preemptions == 0 and hi.slot in sched.running
        # converse: high-priority growth DOES evict the low neighbour
        lo2 = resident(0, 2)
        assert sched.ensure_token_capacity(hi.slot, 8) is True
        assert lo2.state is RequestState.WAITING
        assert hi.preemptions == 0


# ---------------------------------------------------------------------------
# streaming + client surface
# ---------------------------------------------------------------------------

class TestStreaming:
    def test_callback_and_poll(self, model):
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(model, max_slots=2, max_len=48, page_size=8,
                            chunk_size=8)
        seen = []
        h = eng.submit(_prompts(1, seed=9)[0], 6,
                       on_token=lambda hh, t: seen.append(t))
        polled = []
        while not h.done:
            eng.step()
            polled.extend(h.new_tokens())
        assert seen == h.output_tokens == polled
        assert len(seen) == 6
        assert h.ttft is not None and h.ttft > 0
        assert len(h.inter_token_latencies) == 5

    def test_stream_iterator(self, model):
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(model, max_slots=2, max_len=48, page_size=8,
                            chunk_size=8)
        h = eng.submit(_prompts(1, seed=10)[0], 5)
        toks = list(eng.stream(h))
        assert toks == h.output_tokens and len(toks) == 5
        assert h.finish_reason is not None

    def test_eos_retires_early(self, model):
        from paddle_tpu.serving import ServingEngine
        from paddle_tpu.serving.request import FinishReason

        eng = ServingEngine(model, max_slots=2, max_len=48, page_size=8,
                            chunk_size=8)
        p = _prompts(1, seed=11)[0]
        probe = eng.submit(p, 8)
        eng.run(max_steps=2000)
        eos = probe.output_tokens[2]
        stop_at = probe.output_tokens.index(eos) + 1   # first hit
        h = eng.submit(p, 8, eos_token_id=int(eos))
        eng.run(max_steps=2000)
        assert h.finish_reason is FinishReason.EOS
        assert len(h.output_tokens) == stop_at
        assert h.output_tokens[-1] == eos
        leaks = eng.leak_check()
        assert leaks["free_pages"] == leaks["total_pages"]

    def test_eager_serving_matches_compiled(self, model):
        """compiled=False runs the same step bodies eagerly over the
        host-numpy cache metadata (regression: `.at[]` on numpy)."""
        from paddle_tpu.serving import ServingEngine

        def serve(compiled):
            eng = ServingEngine(model, max_slots=2, max_len=48,
                                page_size=8, chunk_size=8,
                                compiled=compiled)
            hs = [eng.submit(p, 4) for p in _prompts(2, seed=12)]
            eng.run(max_steps=2000)
            return [h.output_tokens for h in hs]

        assert serve(True) == serve(False)

    def test_eager_paged_generate(self, model):
        """GenerationEngine(kind='paged', compiled=False) still works
        with the host-numpy page tables (regression)."""
        from paddle_tpu.jit.decode_step import GenerationEngine

        prompt = _prompts(1, seed=13)[0]
        eager = GenerationEngine(model, kind="paged", batch=1,
                                 max_len=48, page_size=8,
                                 compiled=False)
        out = eager.generate(prompt[None].astype(np.int64), 5)
        ref = model.generate(np.asarray(prompt)[None], max_new_tokens=5,
                             use_cache="paged")
        np.testing.assert_array_equal(np.asarray(out._data),
                                      np.asarray(ref._data))

    def test_seed_full_width(self, model):
        """Seeds are not masked to 31 bits: s and s + 2**31 are
        distinct RNG streams, and the same seed reproduces."""
        from paddle_tpu.serving import ServingEngine

        def toks(seed):
            eng = ServingEngine(model, max_slots=1, max_len=48,
                                page_size=8, chunk_size=8,
                                do_sample=True, temperature=1.0)
            h = eng.submit(_prompts(1, seed=15)[0], 8, seed=seed)
            eng.run(max_steps=1000)
            return h.output_tokens

        base = toks(123)
        assert toks(123) == base
        assert toks(123 + 2 ** 31) != base

    def test_submit_validation(self, model):
        from paddle_tpu.serving import ServingEngine

        eng = ServingEngine(model, max_slots=2, max_len=32, page_size=8,
                            chunk_size=8)
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(np.ones((30,), np.int32), 8)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(np.ones((4,), np.int32), 0)
