"""Sharded fused-scan train step: weight-update sharding INSIDE the scan.

`FusedScanTrainStep` made the 1.3b north star fit one chip by fusing the
Adam update into a manual per-layer reverse scan. This module is its
multi-chip form, per Xu et al., "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" (PAPERS.md): weights stay
replicated over the dp/sharding axis, but gradients, moments, masters and
the update computation are 1/N-sharded per rank —

  backward scan (reverse, per chunk of K layers):
      dp      = vjp(block chunk)(dy)                 (full, dies here)
      flat    = bucket-pack(dp)   [K, F]             (comm_bucketer layout)
      gshard  = reduce_scatter(flat) over the axis   [K, F/N]  <- survives
      sq     += ||gshard/N||^2                       (in the scan carry)
  one scalar all-reduce:  gnorm = sqrt(psum(sq));  clip = min(c/gnorm, 1)
  update scan (per chunk):
      adam on the 1/N shard (clip applied, moments/masters sharded)
      all_gather(updated shard) -> write the chunk's param slices
  outer params (embed/ln_f/head): same, without the scan.

Because only the 1/N grad shard outlives a scan iteration, the whole
gradient set per rank is full_grads/N — which is what makes the fused
GLOBAL-NORM CLIP affordable here (the single-device step needs a second
backward pass for it, docs/DECISIONS.md §12) and keeps grad memory off
the per-layer OOM cliff. The per-bucket reduce-scatter reuses the
comm_bucketer packing (deterministic entry offsets, FLAGS_comm_bucket_mb
cap, padding to the axis degree) and optionally the EQuARX-style
compressed wire format (FLAGS_comm_quant -> int8/bf16 scatter leg,
collective.quantized_psum_scatter_traced). Inside one scan iteration the
reduce-scatter of bucket b is independent of bucket b+1's packing and of
the norm accumulation, and the update scan's all_gather of bucket b is
independent of bucket b+1's Adam math — with scan_unroll >= 2 adjacent
layers' collectives and compute land in ONE while-loop body where XLA's
latency-hiding scheduler can overlap them (tools/hlo_overlap.py is the
receipt; the multichip lane records its verdict).

Dropout rides the carry-free per-layer PRNG offset scheme of the base
class, with the dp-axis rank folded in so each rank draws distinct masks
for its own batch rows.

Semantics note: the per-rank loss is the criterion's mean over the
rank's batch shard and the returned loss is their mean — equal to the
full-batch mean when every rank holds the same number of unmasked
tokens (the standard data-parallel contract; ragged -100 masks make it
a weighted mean, same as the reference DataParallel).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .fused_scan_step import FusedScanTrainStep, _donate_argnums, _key
from ..utils import flags as _flags


# ---------------------------------------------------------------------------
# flat bucket packing (the comm_bucketer layout, applied per layer chunk)
# ---------------------------------------------------------------------------

def pack_flat(leaf_of_key, bucket, lead=(), dtype=None):
    """Pack per-leaf arrays (each [*lead, *entry.shape]) into the
    bucket's flat layout [*lead, bucket.numel] (zero-padded), matching
    comm_bucketer._flatten_bucket offsets exactly. `dtype` overrides the
    bucket dtype (moment packing)."""
    dt = dtype or bucket.dtype
    parts = []
    for e in bucket.entries:
        parts.append(leaf_of_key(e.key).reshape(lead + (-1,)).astype(dt))
    pad = bucket.numel - sum(e.numel for e in bucket.entries)
    if pad:
        parts.append(jnp.zeros(lead + (pad,), dt))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, -1)


def unpack_flat(flat, bucket):
    """[*lead, bucket.numel] -> {entry.key: [*lead, *entry.shape]}."""
    lead = flat.shape[:-1]
    return {e.key: flat[..., e.offset:e.offset + e.numel]
            .reshape(lead + tuple(e.shape)) for e in bucket.entries}


def scatter_flat(flat, axis, nranks, quant=""):
    """Reduce-scatter a packed flat bucket over `axis` along its LAST
    dim: one collective per bucket (vs one per leaf), bit-identical to
    comm_bucketer.bucketed_reduce_scatter's per-bucket psum_scatter on
    the same packing. `quant` routes the compressed scatter leg."""
    if quant:
        from ..distributed.collective import quantized_psum_scatter_traced

        return quantized_psum_scatter_traced(axis, nranks, quant)(flat)
    return lax.psum_scatter(flat, axis, scatter_dimension=flat.ndim - 1,
                            tiled=True)


def _unwrap_layers(model):
    """Follow wrapper chains (GroupShardedStage2, fleet MetaParallelBase,
    DataParallel) to the Layer that owns the parameters."""
    seen = set()
    while hasattr(model, "_layers") and id(model) not in seen:
        seen.add(id(model))
        model = model._layers
    return model


def _vec_or_scalar(values, entries, numel, pad_value=0.0):
    """Per-entry hyperparameters as ONE flat [numel] fp32 vector — or a
    python float when uniform (padding entries update to zero regardless
    of the hyperparameter, so a uniform scalar is exact)."""
    uniq = set(values)
    if len(uniq) == 1:
        return float(values[0])
    vec = np.full((numel,), pad_value, np.float32)
    for e, v in zip(entries, values):
        vec[e.offset:e.offset + e.numel] = v
    return jnp.asarray(vec)


class ShardedFusedScanTrainStep(FusedScanTrainStep):
    """Multi-chip FusedScanTrainStep over a dp/sharding mesh axis.

    Usage (directly, or via GroupShardedStage2.train_step /
    fleet ShardingParallel.train_step which resolve mesh+axis)::

        mesh = dist.env.build_mesh({"sharding": 8}); dist.env.set_mesh(mesh)
        step = ShardedFusedScanTrainStep(model, opt)   # scan_layers model
        loss = step(ids, labels)       # ids [global_batch, seq]

    Optimizer state (moments + masters) lives as flat bucket-packed
    arrays sharded 1/N over the axis (inspect
    `opt._accumulators["moment1"]["__scan_shard_s0__"]` etc.);
    ClipGradByGlobalNorm costs one scalar all-reduce, ClipGradByValue is
    elementwise on the shard, and dropout is rank-folded per layer.
    """

    def __init__(self, model, optimizer, criterion=None, fused_head=False,
                 compute_dtype=None, layer_chunk=1, scan_unroll=1,
                 mesh=None, axis=None, group=None, comm_bucket_mb=None,
                 comm_quant=None, scaler=None, guard_nonfinite=None):
        model = _unwrap_layers(model)
        super().__init__(model, optimizer, criterion=criterion,
                         fused_head=fused_head,
                         compute_dtype=compute_dtype,
                         layer_chunk=layer_chunk, scan_unroll=scan_unroll,
                         scaler=scaler, guard_nonfinite=guard_nonfinite)
        from ..distributed import env as denv

        if group is not None:
            mesh, axis = group.mesh, group.axes[0]
        if mesh is None:
            mesh = denv.get_mesh()
        if axis is None:
            axis = next((a for a in ("sharding", "dp")
                         if a in mesh.axis_names and mesh.shape[a] > 1),
                        mesh.axis_names[0])
        self._mesh, self._axis = mesh, axis
        self._degree = int(mesh.shape[axis])
        if self._degree <= 1:
            raise ValueError(
                f"axis {axis!r} has degree {self._degree}; weight-update "
                "sharding needs a >1 dp/sharding axis — use "
                "FusedScanTrainStep on one chip")
        # dp-rank folded into the per-layer dropout offsets
        self._rng_nranks = self._degree
        if comm_quant is None:
            comm_quant = _flags.get_flag("FLAGS_comm_quant") or ""
        self._comm_quant = comm_quant
        from ..distributed.collective import QUANT_SCATTER_BLOCK
        from ..distributed.comm_bucketer import MB, build_buckets

        pad = self._degree * (QUANT_SCATTER_BLOCK if comm_quant else 1)
        if comm_bucket_mb is None:
            comm_bucket_mb = int(
                _flags.get_flag("FLAGS_comm_bucket_mb") or 0)
        bucket_bytes = (comm_bucket_mb * MB if comm_bucket_mb > 0
                        else 1 << 62)
        # stacked leaves bucket by their PER-LAYER shard shape (the scan
        # scatters one chunk at a time); outer leaves by full shape
        self._s_train = [(j, p) for j, p in enumerate(self._s_params)
                         if p.trainable]
        self._s_assign = build_buckets(
            [(j, tuple(p.shape[1:]), p._data.dtype)
             for j, p in self._s_train],
            bucket_bytes=bucket_bytes, pad_multiple=pad)
        self._o_assign = build_buckets(
            [(j, tuple(p.shape), p._data.dtype)
             for j, (_, p) in enumerate(self._o_params)],
            bucket_bytes=bucket_bytes, pad_multiple=pad)

    def _rng_rank(self):
        return lax.axis_index(self._axis)

    def input_sharding(self):
        """Batches stage dim-0-sharded 1/N over the dp axis — each device
        receives only its shard of the global batch (the weight-update
        sharding lesson applied to ingestion), and the placement matches
        the step's shard_map batch spec so jit never reshards."""
        return NamedSharding(self._mesh, P(self._axis))

    # -- flat sharded optimizer state -----------------------------------
    def _flat_key(self, grp, index):
        return f"__scan_shard_{grp}{index}__"

    def _bucket_params(self, grp, bucket):
        src = (dict(self._s_train) if grp == "s"
               else {j: p for j, (_, p) in enumerate(self._o_params)})
        return [src[e.key] for e in bucket.entries]

    def _bucket_uses_master(self, grp, bucket):
        return any(self._opt._use_master(p)
                   for p in self._bucket_params(grp, bucket))

    def _materialize_flat_state(self):
        """Build (or repack) the optimizer state as per-bucket flat
        arrays sharded 1/N over the axis. Fresh state is created
        SHARDED from the start (jit with out_shardings — zeros for
        moments, fp32 casts of the params for masters), so the first
        build never materializes the full replicated optimizer state
        the sharding exists to avoid; a continuation from per-param
        state (prior TrainStep run, old checkpoint) packs the existing
        full-shape entries once. Idempotent: an existing flat entry
        (second build, checkpoint restore) is reused as-is."""
        opt = self._opt
        mesh, ax = self._mesh, self._axis
        n_layers = self.model.config.num_layers
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            stacked = grp == "s"
            sharding = NamedSharding(
                mesh, P(None, ax) if stacked else P(ax))
            lead = (n_layers,) if stacked else ()
            for bucket in assign.buckets:
                fkey = self._flat_key(grp, bucket.index)
                params = dict(zip([e.key for e in bucket.entries],
                                  self._bucket_params(grp, bucket)))
                use_mw = self._bucket_uses_master(grp, bucket)
                md = self._moment_dtype(bucket, use_mw)

                def packed(leaves, dtype):
                    return jax.jit(
                        lambda lv: pack_flat(lambda k: lv[k], bucket,
                                             lead=lead, dtype=dtype),
                        out_shardings=sharding)(leaves)

                for name in ("moment1", "moment2"):
                    store = opt._accumulators.setdefault(name, {})
                    if fkey not in store:
                        if all(_key(p) in store
                               for p in params.values()):
                            store[fkey] = packed(
                                {k: store[_key(p)]
                                 for k, p in params.items()}, md)
                        else:
                            shape = lead + (bucket.numel,)
                            store[fkey] = jax.jit(
                                lambda s=shape, d=md: jnp.zeros(s, d),
                                out_shardings=sharding)()
                    for p in params.values():
                        store.pop(_key(p), None)
                if use_mw:
                    if fkey not in opt._master_weights:
                        opt._master_weights[fkey] = packed(
                            {k: opt._master_weights.get(_key(p),
                                                        p._data)
                             for k, p in params.items()},
                            jnp.float32)
                    for p in params.values():
                        opt._master_weights.pop(_key(p), None)

    def _moment_dtype(self, bucket, use_mw):
        md = self._opt._moment_dtype
        if md is not None:
            return md
        return jnp.float32 if use_mw else bucket.dtype

    def ensure_built(self):
        if self._jitted is not None:
            return
        self._materialize_flat_state()
        # canonicalize replicated-state layouts BEFORE the first trace:
        # the step's outputs come back mesh-committed, so an uncommitted
        # single-device param on call 1 would key a SECOND executable on
        # call 2 (the TrainStep._build layout lesson — one extra compile
        # is minutes of axon program load at 1.3b)
        rep = NamedSharding(self._mesh, P())
        for p in self._s_params + [p for _, p in self._o_params]:
            p._data = jax.device_put(p._data, rep)
        for b in self._buffers:
            b._data = jax.device_put(b._data, rep)
        self._step_count = jax.device_put(
            jnp.asarray(int(self._opt._step_count), jnp.int32), rep)
        self._opt._step_count = self._step_count
        if self._guard is not None and self._guard.scaler is not None:
            # the scaler's traced mirrors must start mesh-committed too,
            # or call 2 (committed jit outputs) keys a second executable
            self._guard.writeback(jax.tree_util.tree_map(
                lambda v: jax.device_put(v, rep),
                self._guard.init_state()))
        self._build()

    def _extract_state(self):
        opt = self._opt
        self._step_count = opt._step_count   # restore-aware (base class)
        st = {
            "s": {"p": [p._data for p in self._s_params]},
            "o": {"p": [p._data for _, p in self._o_params]},
            "buf": [b._data for b in self._buffers],
            "step": jnp.asarray(self._step_count, jnp.int32),
        }
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            st[grp]["m"] = [opt._accumulators["moment1"]
                            [self._flat_key(grp, b.index)]
                            for b in assign.buckets]
            st[grp]["v"] = [opt._accumulators["moment2"]
                            [self._flat_key(grp, b.index)]
                            for b in assign.buckets]
            st[grp]["mw"] = [opt._master_weights.get(
                self._flat_key(grp, b.index)) for b in assign.buckets]
        if self._guard is not None:
            st["guard"] = self._guard.init_state()
        return st

    def _inject_state(self, state):
        opt = self._opt
        for p, d in zip(self._s_params, state["s"]["p"]):
            p._data = d
        for (_, p), d in zip(self._o_params, state["o"]["p"]):
            p._data = d
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            for b in assign.buckets:
                fkey = self._flat_key(grp, b.index)
                opt._accumulators["moment1"][fkey] = \
                    state[grp]["m"][b.index]
                opt._accumulators["moment2"][fkey] = \
                    state[grp]["v"][b.index]
                mw = state[grp]["mw"][b.index]
                if mw is not None:
                    opt._master_weights[fkey] = mw
        for b, d in zip(self._buffers, state["buf"]):
            b._data = d
        opt._step_count = state["step"]
        self._step_count = state["step"]
        if self._guard is not None and "guard" in state:
            self._guard.writeback(state["guard"])

    def _state_specs(self):
        ax = self._axis
        rep = P()
        specs = {
            "s": {"p": [rep] * len(self._s_params)},
            "o": {"p": [rep] * len(self._o_params)},
            "buf": [rep] * len(self._buffers),
            "step": rep,
        }
        if self._guard is not None:
            specs["guard"] = {"scale": rep, "good": rep, "bad": rep,
                              "found": rep}
        for grp, assign in (("s", self._s_assign), ("o", self._o_assign)):
            sp = P(None, ax) if grp == "s" else P(ax)
            nb = len(assign.buckets)
            specs[grp]["m"] = [sp] * nb
            specs[grp]["v"] = [sp] * nb
            specs[grp]["mw"] = [
                sp if self._bucket_uses_master(grp, b) else None
                for b in assign.buckets]
        return specs

    # -- the compiled sharded step --------------------------------------
    def _build(self):
        opt = self._opt
        mesh, ax, N = self._mesh, self._axis, self._degree
        K = self._layer_chunk
        n_layers = self.model.config.num_layers
        C = n_layers // K
        quant = self._comm_quant
        s_assign, o_assign = self._s_assign, self._o_assign
        inv_n = 1.0 / N

        def hyper(p):
            return (float(opt._decoupled_wd(p)), float(opt._l2_coeff(p)),
                    float(opt._param_lr_scale(p)))

        def bucket_hp(grp, bucket):
            params = self._bucket_params(grp, bucket)
            hs = [hyper(p) for p in params]
            ent = bucket.entries
            wd = _vec_or_scalar([h[0] for h in hs], ent, bucket.numel)
            l2 = _vec_or_scalar([h[1] for h in hs], ent, bucket.numel)
            lrs = _vec_or_scalar([h[2] for h in hs], ent, bucket.numel,
                                 pad_value=1.0)
            ncs = [1.0 if getattr(p, "need_clip", True) else 0.0
                   for p in params]
            # None = "everything clips" (the common case, no masking);
            # a uniform 0.0 or a mixed vector masks the clip per entry
            nc = (None if all(v == 1.0 for v in ncs)
                  else _vec_or_scalar(ncs, ent, bucket.numel))
            return wd, l2, lrs, nc

        s_hp = [bucket_hp("s", b) for b in s_assign.buckets]
        o_hp = [bucket_hp("o", b) for b in o_assign.buckets]
        s_mw = [self._bucket_uses_master("s", b) for b in s_assign.buckets]
        o_mw = [self._bucket_uses_master("o", b) for b in o_assign.buckets]
        t_idx = {j: tj for tj, (j, _) in enumerate(self._s_train)}
        cv = self._clip_value
        clip_norm = self._clip_global
        guard = self._guard
        scaling = guard is not None and guard.scaling

        def shard_of(vec, rank, shard_len):
            """Own-rank slice of a replicated flat [F] constant (no-op
            for uniform scalars)."""
            if vec is None or isinstance(vec, float):
                return vec
            return lax.dynamic_slice_in_dim(vec, rank * shard_len,
                                            shard_len, 0)

        chunk_apply = self._chunk_apply

        def g_shard_f32(gs, nc_shard, scale, inv_s=None):
            """Scatter output -> the fp32 gradient the update consumes:
            1/N for the data-parallel mean, loss-scale unscale, value
            clip, global-norm scale (need_clip-masked)."""
            g32 = gs.astype(jnp.float32) * inv_n
            if inv_s is not None:
                g32 = g32 * inv_s
            if cv is not None:
                clipped = jnp.clip(g32, cv[0], cv[1])
                g32 = (clipped if nc_shard is None
                       else nc_shard * clipped + (1 - nc_shard) * g32)
            if scale is not None:
                eff = (scale if nc_shard is None
                       else nc_shard * scale + (1 - nc_shard))
                g32 = g32 * eff
            return g32

        def sq_of(gs, nc_shard):
            g32 = gs.astype(jnp.float32) * inv_n
            if nc_shard is not None:
                g32 = g32 * nc_shard
            return jnp.sum(jnp.square(g32))

        def adam_shard(pv, g32, m, v, lr_lrs, tf, wd, l2):
            if not (isinstance(l2, float) and l2 == 0.0):
                g32 = g32 + l2 * pv.astype(jnp.float32)
            return opt._adam_math(pv, g32, m, v, None, lr_lrs, tf, wd)

        from ..nn.functional.flash_attention import attention_segments

        def step_fn(state, lr, ids, labels, seg=None):
            s, o = state["s"], state["o"]
            saved_buf = self._bind(self._buffers, state["buf"])
            # packed-sequence segment ids (local batch rows, sharded
            # like ids) published to the in-scan attention layers
            seg_ctx = attention_segments(seg)
            seg_ctx.__enter__()
            try:
                gst = state.get("guard")
                inv_s = (1.0 / gst["scale"]) if scaling else None
                t = state["step"] + 1
                tf = t.astype(jnp.float32)
                t32 = t.astype(jnp.int32)
                rank = lax.axis_index(ax)
                b, seq = ids.shape          # LOCAL batch rows
                pos = jnp.arange(seq, dtype=ids.dtype)[None, :]

                # ---- forward (replicated params, local batch shard)
                x0 = self._embed_fn(o["p"], ids, pos,
                                    rng_off=self._rng_base(t32, n_layers))
                sp_c = tuple(a.reshape((C, K) + tuple(a.shape[1:]))
                             for a in s["p"])

                def fwd_body(h, scanned):
                    p_chunk, i = scanned
                    return chunk_apply(p_chunk, h,
                                       self._rng_chunk_base(t32, i)), h

                xL, xs = lax.scan(fwd_body, x0, (sp_c, jnp.arange(C)),
                                  unroll=self._scan_unroll)

                loss, head_vjp = jax.vjp(
                    lambda od, x: self._head_fn(od, x, labels),
                    o["p"], xL)
                ct = (gst["scale"].astype(loss.dtype) if scaling
                      else jnp.ones((), loss.dtype))
                d_o_head, dxL = head_vjp(ct)

                # ---- backward scan: vjp one chunk, reduce-scatter its
                # bucket-packed grads; ONLY the 1/N shard, the running
                # squared norm, and the finiteness fold survive the
                # iteration. Unlike the single-device step, the guard
                # needs NO second backward here: the shards it must
                # inspect all outlive the scan anyway (sum-reductions
                # preserve non-finiteness, so checking the post-scatter
                # 1/N shard covers every element at 1/N the cost).
                from .nonfinite_guard import all_finite

                G0 = tuple(jnp.zeros((C, K, bkt.numel // N), bkt.dtype)
                           for bkt in s_assign.buckets)

                def bwd_body(carry, scanned):
                    dy, sq, fin, G = carry
                    x_i, i = scanned
                    p_i = tuple(
                        lax.dynamic_index_in_dim(a, i, keepdims=False)
                        for a in sp_c)
                    rng0 = self._rng_chunk_base(t32, i)
                    _, vjp = jax.vjp(
                        lambda pl, xx: chunk_apply(pl, xx, rng0),
                        p_i, x_i)
                    dp, dx = vjp(dy)
                    newG = []
                    for bkt in s_assign.buckets:
                        flat = pack_flat(lambda j: dp[j], bkt, lead=(K,))
                        gs = scatter_flat(flat, ax, N, quant)  # [K,F/N]
                        if clip_norm is not None:
                            nc = shard_of(s_hp[bkt.index][3], rank,
                                          bkt.numel // N)
                            sq = sq + sq_of(gs, nc)
                        if guard is not None:
                            fin = fin & all_finite([gs])
                        newG.append(lax.dynamic_update_index_in_dim(
                            G[bkt.index], gs, i, 0))
                    return (dx, sq, fin, tuple(newG)), None

                (dx0, sq, fin, G), _ = lax.scan(
                    bwd_body,
                    (dxL, jnp.float32(0.0), jnp.bool_(True), G0),
                    (xs, jnp.arange(C)), reverse=True,
                    unroll=self._scan_unroll)

                # ---- outer grads: same pack + reduce-scatter
                _, emb_vjp = jax.vjp(
                    lambda od: self._embed_fn(
                        od, ids, pos,
                        rng_off=self._rng_base(t32, n_layers)), o["p"])
                (d_o_emb,) = emb_vjp(dx0)
                o_gs = []
                for bkt in o_assign.buckets:
                    flat = pack_flat(
                        lambda j: (d_o_head[j].astype(jnp.float32)
                                   + d_o_emb[j].astype(jnp.float32)),
                        bkt)
                    gs = scatter_flat(flat, ax, N, quant)      # [F/N]
                    if clip_norm is not None:
                        nc = shard_of(o_hp[bkt.index][3], rank,
                                      bkt.numel // N)
                        sq = sq + sq_of(gs, nc)
                    if guard is not None:
                        fin = fin & all_finite([gs])
                    o_gs.append(gs)

                # ---- the fused global-norm clip + cross-rank found_inf:
                # still ONE scalar all-reduce (a length-2 psum when the
                # guard is on — norm and finiteness ride together)
                scale = None
                found = None
                if clip_norm is not None or guard is not None:
                    bad_local = (jnp.float32(0.0) if guard is None
                                 else (~fin).astype(jnp.float32))
                    tot = lax.psum(jnp.stack([sq, bad_local]), ax)
                    if guard is not None:
                        found = tot[1] > 0
                    if clip_norm is not None:
                        # shard grads carry the loss scale: true norm is
                        # sqrt(psum(sq))/loss_scale
                        gnorm = jnp.sqrt(tot[0])
                        if inv_s is not None:
                            gnorm = gnorm * inv_s
                        scale = jnp.minimum(
                            jnp.float32(clip_norm)
                            / jnp.maximum(gnorm, 1e-12), 1.0)

                # ---- update scan: sharded Adam on each chunk's grad
                # shard, then all_gather the updated shard back into the
                # replicated param stacks. Bucket b's gather is
                # independent of bucket b+1's math (and, under
                # scan_unroll>=2, of the next chunk's) — the overlap the
                # HLO probe checks for.
                sM = [m.reshape((C, K, -1)) for m in s["m"]]
                sV = [v.reshape((C, K, -1)) for v in s["v"]]
                sMW = [mw.reshape((C, K, -1)) if mw is not None else None
                       for mw in s["mw"]]
                P_tr0 = tuple(sp_c[j] for j, _ in self._s_train)

                def upd_body(carry, i):
                    P_tr, M, V, MW = carry
                    for bkt in s_assign.buckets:
                        bi = bkt.index
                        shard_len = bkt.numel // N
                        wd, l2, lrs, nc = (shard_of(h, rank, shard_len)
                                           for h in s_hp[bi])
                        g32 = g_shard_f32(
                            lax.dynamic_index_in_dim(G[bi], i,
                                                     keepdims=False),
                            nc, scale, inv_s)
                        m_i = lax.dynamic_index_in_dim(M[bi], i,
                                                       keepdims=False)
                        v_i = lax.dynamic_index_in_dim(V[bi], i,
                                                       keepdims=False)
                        if MW[bi] is not None:
                            pv = lax.dynamic_index_in_dim(
                                MW[bi], i, keepdims=False)
                        else:
                            # fp32-stored params ARE the master: slice
                            # this rank's shard out of the replicated
                            # chunk (bit-exact round trip via the
                            # gather below)
                            flat_p = pack_flat(
                                lambda j: lax.dynamic_index_in_dim(
                                    P_tr[t_idx[j]], i, keepdims=False),
                                bkt, lead=(K,))
                            pv = lax.dynamic_slice_in_dim(
                                flat_p, rank * shard_len, shard_len, 1)
                        out32, mn, vn, _ = adam_shard(
                            pv, g32, m_i, v_i, lr * lrs, tf, wd, l2)
                        if found is not None:
                            # bad step: shard passes through bit-
                            # identical; the gather below then rebuilds
                            # the OLD params exactly (astype(master) is
                            # the same deterministic cast that produced
                            # them)
                            out32 = jnp.where(found, pv, out32)
                            mn = jnp.where(found, m_i, mn)
                            vn = jnp.where(found, v_i, vn)
                        M[bi] = lax.dynamic_update_index_in_dim(
                            M[bi], mn.astype(M[bi].dtype), i, 0)
                        V[bi] = lax.dynamic_update_index_in_dim(
                            V[bi], vn.astype(V[bi].dtype), i, 0)
                        if MW[bi] is not None:
                            MW[bi] = lax.dynamic_update_index_in_dim(
                                MW[bi], out32, i, 0)
                        full = lax.all_gather(
                            out32.astype(bkt.dtype), ax, axis=1,
                            tiled=True)                     # [K, F]
                        for e_key, leaf in unpack_flat(full, bkt).items():
                            tj = t_idx[e_key]
                            P_tr = P_tr[:tj] + (
                                lax.dynamic_update_index_in_dim(
                                    P_tr[tj],
                                    leaf.astype(P_tr[tj].dtype), i, 0),
                            ) + P_tr[tj + 1:]
                    return (P_tr, M, V, MW), None

                (P_tr, sM, sV, sMW), _ = lax.scan(
                    upd_body, (P_tr0, list(sM), list(sV), list(sMW)),
                    jnp.arange(C), unroll=self._scan_unroll)

                new_sp = list(s["p"])
                for tj, (j, _) in enumerate(self._s_train):
                    new_sp[j] = P_tr[tj].reshape(
                        (-1,) + tuple(P_tr[tj].shape[2:]))

                # ---- outer update (no scan)
                new_op = list(o["p"])
                new_om, new_ov, new_omw = [], [], []
                for bkt in o_assign.buckets:
                    bi = bkt.index
                    shard_len = bkt.numel // N
                    wd, l2, lrs, nc = (shard_of(h, rank, shard_len)
                                       for h in o_hp[bi])
                    g32 = g_shard_f32(o_gs[bi], nc, scale, inv_s)
                    m_i, v_i = o["m"][bi], o["v"][bi]
                    if o["mw"][bi] is not None:
                        pv = o["mw"][bi]
                    else:
                        flat_p = pack_flat(lambda j: o["p"][j], bkt)
                        pv = lax.dynamic_slice_in_dim(
                            flat_p, rank * shard_len, shard_len, 0)
                    out32, mn, vn, _ = adam_shard(
                        pv, g32, m_i, v_i, lr * lrs, tf, wd, l2)
                    if found is not None:
                        out32 = jnp.where(found, pv, out32)
                        mn = jnp.where(found, m_i, mn)
                        vn = jnp.where(found, v_i, vn)
                    new_om.append(mn.astype(m_i.dtype))
                    new_ov.append(vn.astype(v_i.dtype))
                    new_omw.append(out32 if o["mw"][bi] is not None
                                   else None)
                    full = lax.all_gather(out32.astype(bkt.dtype), ax,
                                          axis=0, tiled=True)
                    for e_key, leaf in unpack_flat(full, bkt).items():
                        new_op[e_key] = leaf.astype(
                            o["p"][e_key].dtype)

                new_state = {
                    "s": {"p": new_sp,
                          "m": [m.reshape((n_layers, -1)) for m in sM],
                          "v": [v.reshape((n_layers, -1)) for v in sV],
                          "mw": [mw.reshape((n_layers, -1))
                                 if mw is not None else None
                                 for mw in sMW]},
                    "o": {"p": new_op, "m": new_om, "v": new_ov,
                          "mw": new_omw},
                    "buf": state["buf"],
                    "step": (t if found is None
                             else jnp.where(found, state["step"], t)),
                }
                if guard is not None:
                    new_state["guard"] = guard.update(gst, found)
                return lax.psum(loss, ax) * inv_n, new_state
            finally:
                seg_ctx.__exit__(None, None, None)
                self._bind(self._buffers, saved_buf)

        specs = self._state_specs()
        batch_spec = P(ax, None)
        # the trailing batch_spec covers the optional segment-id arg —
        # a None there is an empty pytree, so the spec binds no leaves
        wrapped = jax.shard_map(
            step_fn, mesh=mesh,
            in_specs=(specs, P(), batch_spec, batch_spec, batch_spec),
            out_specs=(P(), specs), check_vma=False)
        self._jitted = jax.jit(wrapped,
                               donate_argnums=_donate_argnums())

    def __call__(self, ids, labels, segment_ids=None):
        shape = getattr(ids, "shape", None)
        if shape and shape[0] % self._degree:
            raise ValueError(
                f"global batch {shape[0]} is not divisible by the "
                f"{self._axis!r} degree {self._degree}")
        return super().__call__(ids, labels, segment_ids=segment_ids)


# ---------------------------------------------------------------------------
# selection wiring (group_sharded / fleet distributed_model entry points)
# ---------------------------------------------------------------------------

def select_train_step(model, optimizer, criterion=None, mesh=None,
                      axis=None, **kw):
    """The train-step chooser GroupShardedStage2 / ShardingParallel use:
    scan_layers GPT on a >1 sharding/dp axis -> ShardedFusedScanTrainStep;
    degree 1 -> FusedScanTrainStep; anything else -> the generic
    TrainStep over `criterion` (or model.loss)."""
    from ..distributed import env as denv
    from ..models.gpt import GPTStackedBlocks

    layers = _unwrap_layers(model)
    blocks = getattr(getattr(layers, "gpt", None), "blocks", None)
    scan = isinstance(blocks, GPTStackedBlocks)
    if mesh is None and denv.is_initialized():
        mesh = denv.get_mesh()
    degree = 1
    if mesh is not None:
        if axis is None:
            axis = next((a for a in ("sharding", "dp")
                         if a in mesh.axis_names and mesh.shape[a] > 1),
                        None)
        if axis is not None:
            degree = int(mesh.shape[axis])
    if scan and degree > 1:
        return ShardedFusedScanTrainStep(layers, optimizer,
                                         criterion=criterion, mesh=mesh,
                                         axis=axis, **kw)
    if scan:
        return FusedScanTrainStep(layers, optimizer, criterion=criterion,
                                  **{k: v for k, v in kw.items()
                                     if k in ("fused_head",
                                              "compute_dtype",
                                              "layer_chunk",
                                              "scan_unroll")})
    from .train_step import TrainStep

    if criterion is not None:
        return TrainStep(model, lambda m, a, b: criterion(m(a), b),
                         optimizer)
    return TrainStep(model, lambda m, a, b: m.loss(a, b), optimizer)


# ---------------------------------------------------------------------------
# HLO probe program (tools/hlo_overlap.py --probe, bench --multichip)
# ---------------------------------------------------------------------------

def build_probe_lowered(n_devices=8, scan_unroll=2, layer_chunk=1):
    """Lower (not run) the sharded step for a tiny scan GPT on an
    n-device host mesh — the program the overlap checker inspects."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as popt
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    devs = jax.devices("cpu")[:n_devices] if jax.default_backend() == \
        "cpu" else jax.devices()[:n_devices]
    if len(devs) < n_devices:
        raise RuntimeError(
            f"{len(devs)} devices < {n_devices} "
            "(set --xla_force_host_platform_device_count)")
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devs), ("sharding",))
    denv.set_mesh(mesh)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                    num_attention_heads=2, max_position_embeddings=32,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    scan_layers=True)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(learning_rate=1e-3, parameters=model.parameters(),
                     grad_clip=nn.ClipGradByGlobalNorm(1.0))
    step = ShardedFusedScanTrainStep(model, opt, mesh=mesh,
                                     axis="sharding",
                                     scan_unroll=scan_unroll,
                                     layer_chunk=layer_chunk)
    step.ensure_built()
    state = step._extract_state()
    lr = jnp.float32(1e-3)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (n_devices, 16)),
                      jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size,
                                      (n_devices, 16)), jnp.int32)
    return step._jitted.lower(state, lr, ids, labels, None)
