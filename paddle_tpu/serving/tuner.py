"""Online knob tuner: the serving engine's closed loop (ISSUE 17).

PRs 12–13 gave the engine eyes — SLO burn-rate gauges, queue-depth
gauges, per-request latency rings. This module is the hands: a small
hysteretic controller that nudges three engine knobs from those live
measurements:

- ``admit_watermark`` (host-only): free-page headroom held back before
  admitting. Lowered when the queue is deep and the pool has slack
  (admit more aggressively), raised when preemption churn shows
  admission outran capacity.
- ``prefill_chunks_per_step`` / ``chunk_size`` (host-only): prefill
  aggressiveness per loop iteration. Raised under TTFT burn, lowered
  under ITL burn. The chunk cap only moves along the engine's
  ALREADY-COMPILED bucket ladder, so a move never traces.
- ``decode_burst`` (RETRACE-TRIGGERING: the burst is unrolled inside
  the compiled decode step). Moves happen only at a safe boundary —
  between engine steps, by REBUILDING the decode step object with a
  fresh retrace sentinel (`ServingEngine.set_decode_burst`), so the
  sentinel stays strict-clean: the new program's first trace is a
  first signature, not an unexpected recompile. With the persistent
  compile cache warm, a revisited burst value deserializes instead of
  recompiling.

Actuation policy (DECISIONS.md §23): every knob moves ONE bounded step
at a time; a move requires ``hysteresis`` consecutive intervals
agreeing on the signal; after any move the controller holds for
``cooldown`` intervals. The tuner is OFF by default — an engine
without a tuner executes exactly the PR-16 code path. Every decision
lands on the flight recorder (``tuner_move`` events), the
``tuner.<knob>`` gauges, and the ``decisions`` list.
"""
from __future__ import annotations

__all__ = ["OnlineTuner", "TunerLimits"]


class TunerLimits:
    """Bounds for every tunable knob. Defaults derive from the engine's
    construction-time values (the tuner may never exceed what the
    operator provisioned — e.g. the chunk ladder only has buckets up
    to the constructed chunk_size)."""

    def __init__(self, engine, max_decode_burst=8,
                 max_prefill_chunks=4, max_watermark=None):
        self.min_decode_burst = 1
        self.max_decode_burst = int(max_decode_burst)
        self.min_prefill_chunks = 1
        self.max_prefill_chunks = int(max_prefill_chunks)
        self.chunk_ladder = tuple(engine.chunk_buckets)
        self.min_watermark = 0
        self.max_watermark = (int(max_watermark) if max_watermark
                              is not None else 2 * engine.max_slots)


class OnlineTuner:
    """One controller bound to one engine. The engine calls
    ``on_step()`` once per `ServingEngine.step`; every ``interval``
    steps the tuner reads the gauges and maybe moves ONE knob."""

    def __init__(self, engine, interval=32, hysteresis=3, cooldown=4,
                 burn_high=1.0, burn_low=0.25, queue_high=None,
                 limits=None, tune_decode_burst=True):
        self.engine = engine
        self.interval = max(1, int(interval))
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown = max(0, int(cooldown))
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        # queue deeper than this = admission-bound (default: one full
        # slot generation waiting)
        self.queue_high = (int(queue_high) if queue_high is not None
                           else max(2, engine.max_slots))
        self.limits = limits or TunerLimits(engine)
        self.tune_decode_burst = bool(tune_decode_burst)
        self._steps = 0
        self._streak = {}          # signal name -> consecutive count
        self._hold = 0             # cooldown countdown
        self._last_preemptions = 0
        self.decisions = []        # every move, newest last
        self.evaluations = 0
        reg = engine.metrics.registry
        self._bind_gauges(reg)

    def _bind_gauges(self, reg):
        reg.gauge("tuner.decode_burst").set_fn(
            lambda: self.engine.decode_burst)
        reg.gauge("tuner.prefill_chunks_per_step").set_fn(
            lambda: self.engine.prefill_chunks_per_step)
        reg.gauge("tuner.chunk_size").set_fn(
            lambda: self.engine.chunk_size)
        reg.gauge("tuner.admit_watermark").set_fn(
            lambda: self.engine.scheduler._watermark())
        reg.gauge("tuner.moves").set_fn(lambda: len(self.decisions))

    # -- signal collection -----------------------------------------------
    def _signals(self):
        eng = self.engine
        burns = {"ttft": 0.0, "itl": 0.0}
        for st in eng.slo.snapshot().values():
            m = st.get("metric", "")
            if m == "ttft_s":
                burns["ttft"] = max(burns["ttft"], st["burn_rate"])
            elif m == "itl_s":
                burns["itl"] = max(burns["itl"], st["burn_rate"])
        new_preempt = eng.metrics.preemptions - self._last_preemptions
        self._last_preemptions = eng.metrics.preemptions
        return {
            "queue_depth": eng.metrics.queue_depth,
            "free_pages": eng.cache.free_page_count,
            "ttft_burn": burns["ttft"],
            "itl_burn": burns["itl"],
            "preemptions_delta": new_preempt,
        }

    def _bump(self, name):
        """Consecutive-interval streak for one signal; competing
        signals reset each other so the controller cannot oscillate
        between two half-built streaks."""
        for k in list(self._streak):
            if k != name:
                self._streak[k] = 0
        self._streak[name] = self._streak.get(name, 0) + 1
        return self._streak[name]

    # -- the engine-facing hook ------------------------------------------
    def on_step(self):
        self._steps += 1
        if self._steps % self.interval:
            return None
        return self.evaluate()

    def evaluate(self):
        """One control decision from the live signals. Returns the move
        record (also appended to ``decisions``) or None."""
        self.evaluations += 1
        if self._hold > 0:
            self._hold -= 1
            return None
        sig = self._signals()
        move = self._decide(sig)
        if move is None:
            return None
        knob, new, reason = move
        old = self._apply(knob, new)
        if old is None or old == new:
            return None
        record = {"knob": knob, "from": old, "to": new,
                  "reason": reason, "signals": sig,
                  "step": self._steps}
        self.decisions.append(record)
        del self.decisions[:-256]
        self._streak.clear()
        self._hold = self.cooldown
        try:
            from ..observability import recorder

            recorder().note("tuner_move", **{
                k: v for k, v in record.items() if k != "signals"})
        except Exception:
            pass
        return record

    # -- policy -----------------------------------------------------------
    def _decide(self, sig):
        eng, lim = self.engine, self.limits
        # 1. admission churn: preemptions inside the interval mean the
        # watermark let admissions outrun page capacity — back off
        if sig["preemptions_delta"] > 0:
            if self._bump("churn") >= self.hysteresis:
                wm = eng.scheduler._watermark()
                if wm < lim.max_watermark:
                    return ("admit_watermark", wm + 1,
                            "preemption churn: hold more free pages")
            return None
        # 2. TTFT pressure: prefill/admission-bound
        if sig["ttft_burn"] > self.burn_high \
                or sig["queue_depth"] > self.queue_high:
            if self._bump("ttft") >= self.hysteresis:
                pc = eng.prefill_chunks_per_step
                if pc < lim.max_prefill_chunks:
                    return ("prefill_chunks_per_step", pc + 1,
                            "ttft burn/queue depth: more prefill per "
                            "step")
                nxt = self._ladder_next(eng.chunk_size, up=True)
                if nxt is not None:
                    return ("chunk_size", nxt,
                            "ttft burn: larger prefill chunks")
                wm = eng.scheduler._watermark()
                if wm > lim.min_watermark and sig["free_pages"] > 0:
                    return ("admit_watermark", wm - 1,
                            "queue depth with pool slack: admit "
                            "sooner")
            return None
        # 3. ITL pressure: decode-bound — coarser bursts amortize the
        # per-dispatch host cost (retrace-triggering; safe-boundary
        # rebuild, cheap under a warm compile cache)
        if sig["itl_burn"] > self.burn_high:
            if self._bump("itl") >= self.hysteresis:
                pc = eng.prefill_chunks_per_step
                if pc > lim.min_prefill_chunks:
                    return ("prefill_chunks_per_step", pc - 1,
                            "itl burn: fewer prefill chunks per step")
                k = eng.decode_burst
                if (self.tune_decode_burst and eng.spec_step is None
                        and k < lim.max_decode_burst):
                    return ("decode_burst", k + 1,
                            "itl burn: amortize decode dispatch")
            return None
        # 4. calm: drift the burst back down so streaming/admission
        # granularity recovers when the load does
        if sig["ttft_burn"] < self.burn_low \
                and sig["itl_burn"] < self.burn_low \
                and sig["queue_depth"] == 0:
            if self._bump("calm") >= self.hysteresis:
                k = eng.decode_burst
                if (self.tune_decode_burst and eng.spec_step is None
                        and k > lim.min_decode_burst):
                    return ("decode_burst", k - 1,
                            "calm: finer streaming granularity")
            return None
        self._streak.clear()
        return None

    def _ladder_next(self, cur, up):
        """Neighbouring chunk bucket on the engine's compiled ladder
        (never leaves it — a value off the ladder would compile a new
        prefill program mid-serve)."""
        ladder = self.limits.chunk_ladder
        try:
            i = ladder.index(cur)
        except ValueError:
            return None
        j = i + (1 if up else -1)
        if 0 <= j < len(ladder):
            return ladder[j]
        return None

    # -- actuation ---------------------------------------------------------
    def _apply(self, knob, value):
        eng = self.engine
        if knob == "admit_watermark":
            old = eng.scheduler._watermark()
            eng.scheduler.admit_watermark = int(value)
            return old
        if knob == "prefill_chunks_per_step":
            old = eng.prefill_chunks_per_step
            eng.prefill_chunks_per_step = int(value)
            return old
        if knob == "chunk_size":
            old = eng.chunk_size
            eng.chunk_size = int(value)
            return old
        if knob == "decode_burst":
            old = eng.decode_burst
            eng.set_decode_burst(int(value))   # safe-boundary rebuild
            return old
        raise ValueError(f"unknown knob {knob!r}")
