"""Optimizer base class.

Reference parity: python/paddle/optimizer/optimizer.py (grad clip, regularizer,
multi-precision master weights) with fused phi kernels
(paddle/phi/kernels/gpu/adamw_kernel.cu) replaced by jnp update rules that XLA
fuses into one kernel per parameter; the jit train-step path fuses across
parameters too.
"""
from __future__ import annotations

from contextlib import contextmanager as _contextmanager

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework import no_grad
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None,
                 offload_master_weights=False):
        self._learning_rate = learning_rate
        # param groups (reference optimizer.py:140: list of dicts whose
        # 'learning_rate' is a SCALE of the base lr and whose
        # 'weight_decay' overrides the optimizer default for that group) —
        # flattened here; per-param attrs carry the overrides
        self._lr_scale = 1.0
        # group overrides live on THIS optimizer (keyed by param), never on
        # the param objects — params outlive optimizers, and stale attrs
        # would leak group settings into later optimizers over the same
        # params. ParamAttr(learning_rate=...) on the param itself remains
        # the per-param fallback.
        self._group_lr_scale = {}
        self._group_wd = {}
        if parameters is not None:
            flat = []
            for entry in parameters:
                if isinstance(entry, dict):
                    group_params = list(entry["params"])
                    for p in group_params:
                        k = p.name or str(id(p))
                        if "learning_rate" in entry:
                            self._group_lr_scale[k] = float(
                                entry["learning_rate"])
                        if "weight_decay" in entry:
                            wd = entry["weight_decay"]
                            self._group_wd[k] = (
                                float(wd) if isinstance(wd, (int, float))
                                else getattr(wd, "_coeff", 0.0))
                    flat.extend(group_params)
                else:
                    flat.append(entry)
            self._parameter_list = flat
        else:
            self._parameter_list = None
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:
            self._weight_decay = weight_decay  # None or regularizer-like
        self._accumulators = {}  # name -> {param_name: jax array}
        self._master_weights = {}  # param_name -> fp32 jax array
        # pinned-host offload of fp32 master weights (the PERF.md capacity
        # lever for 1.3b-on-one-chip: frees ~4 bytes/param of HBM; the
        # update still runs on device — XLA streams the h2d read and d2h
        # write-back of each master through the step). Shardings are
        # captured at master creation so the traced update can address the
        # host space without reading tracer metadata.
        self._offload_masters = bool(offload_master_weights)
        self._master_shardings = {}  # param_name -> (host_sh, dev_sh)
        self._step_count = 0
        # traced-step protocol fields (see the "traced-step protocol"
        # section): a frozen lr tracer and the dry-run switch
        self._lr_override = None
        self._dry_run = False

    # -- lr ----------------------------------------------------------------
    def get_lr(self):
        if self._lr_override is not None:
            return self._lr_override
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate.get_lr()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- accumulators -------------------------------------------------------
    def _get_accumulator(self, name, param, init=None, dtype=None):
        store = self._accumulators.setdefault(name, {})
        key = param.name or str(id(param))
        if key not in store:
            d = dtype or (jnp.float32 if self._use_master(param) else param._data.dtype)
            store[key] = jnp.zeros(param._data.shape, d) if init is None else init
        return store[key]

    def _set_accumulator(self, name, param, value):
        if self._dry_run:
            return
        key = param.name or str(id(param))
        self._accumulators[name][key] = value

    def _use_master(self, param):
        return self._multi_precision and param._data.dtype in (jnp.float16, jnp.bfloat16)

    def _master_weight(self, param):
        key = param.name or str(id(param))
        if key not in self._master_weights:
            master = param._data.astype(jnp.float32)
            if self._offload_masters:
                import jax

                sh = getattr(master, "sharding", None)
                dev = master.devices().pop()
                # TPU-only: the CPU PJRT backend does not honor pinned_host
                # placements on jit outputs (buffer/sharding memory-kind
                # mismatch aborts the process), so elsewhere the flag is a
                # clean no-op
                if (sh is not None and dev.platform == "tpu"
                        and "pinned_host" in {
                            m.kind for m in dev.addressable_memories()}):
                    host_sh = sh.with_memory_kind("pinned_host")
                    self._master_shardings[key] = (
                        host_sh, sh.with_memory_kind("device"))
                    master = jax.device_put(master, host_sh)
            self._master_weights[key] = master
        return self._master_weights[key]

    def _rehome_offloaded_masters(self):
        """Re-derive the pinned-host placement of every master from its
        CURRENT sharding. Called after a wrapper (ZeRO-1 etc.) reshards the
        master arrays: the new mesh sharding replaces the creation-time
        single-device pair, keeping the offload effective (and the traced
        update's device_puts consistent) under sharded state."""
        if not self._offload_masters or not self._master_shardings:
            return
        import jax

        for key in list(self._master_shardings):
            m = self._master_weights.get(key)
            sh = getattr(m, "sharding", None)
            if m is None or sh is None:
                continue
            host_sh = sh.with_memory_kind("pinned_host")
            dev_sh = sh.with_memory_kind("device")
            self._master_shardings[key] = (host_sh, dev_sh)
            if m.sharding.memory_kind != "pinned_host":
                self._master_weights[key] = jax.device_put(m, host_sh)

    def _write_param(self, param, new_value_f32_or_native):
        if self._dry_run:
            return
        key = param.name or str(id(param))
        if self._use_master(param):
            new_master = new_value_f32_or_native
            if key in self._master_shardings:
                import jax

                new_master = jax.device_put(new_master,
                                            self._master_shardings[key][0])
            self._master_weights[key] = new_master
            param._data = new_value_f32_or_native.astype(param._data.dtype)
        else:
            param._data = new_value_f32_or_native.astype(param._data.dtype)

    def _param_value(self, param):
        if self._use_master(param):
            master = self._master_weight(param)
            key = param.name or str(id(param))
            if key in self._master_shardings:
                import jax

                # read the offloaded master back into HBM for the update
                master = jax.device_put(master,
                                        self._master_shardings[key][1])
            return master
        return param._data

    # -- step ----------------------------------------------------------------
    def _collect_params_grads(self):
        if self._parameter_list is None:
            raise ValueError(
                "optimizer was created without a parameter list; pass parameters="
            )
        pgs = []
        for p in self._parameter_list:
            if p.stop_gradient or p.grad is None:
                continue
            pgs.append((p, p.grad))
        return pgs

    def _param_lr_scale(self, p):
        k = p.name or str(id(p))
        if k in self._group_lr_scale:
            return self._group_lr_scale[k]
        return (getattr(p, "optimize_attr", None) or {}).get(
            "learning_rate", 1.0)

    def _param_group_wd(self, p):
        return self._group_wd.get(p.name or str(id(p)))

    def _cur_lr(self):
        """Base lr times the current param's group scale (set by step())."""
        lr = self.get_lr()
        return lr * self._lr_scale if self._lr_scale != 1.0 else lr

    def _apply_decay(self, param, grad_data):
        """L2 regularization folded into the gradient (reference: the
        regularizer path in optimizer.py; AdamW overrides with decoupled decay)."""
        wd = self._param_group_wd(param)
        if wd is None:
            wd = self._weight_decay
        if wd is None:
            return grad_data
        coeff = wd if isinstance(wd, float) else getattr(wd, "_coeff", 0.0)
        if coeff == 0.0 or getattr(param, "regularizer", None) is not None:
            return grad_data
        return grad_data + coeff * self._param_value(param).astype(grad_data.dtype)

    @no_grad()
    def step(self):
        params_grads = self._collect_params_grads()
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        if self._maybe_fused_step(params_grads):
            return
        for p, g in params_grads:
            g_data = g._data if isinstance(g, Tensor) else g
            if self._use_master(p) and not getattr(p, "layer_stacked",
                                                   False):
                # layer-stacked params skip the whole-stack fp32 upcast:
                # their update is layer-chunked (adam _adam_math upcasts
                # per slice) and a [L, ...] fp32 grad temp OOMs at 1.3b
                g_data = g_data.astype(jnp.float32)
            g_data = self._apply_decay(p, g_data)
            self._lr_scale = self._param_lr_scale(p)
            try:
                self._append_optimize_op(p, g_data)
            finally:
                self._lr_scale = 1.0

    def _maybe_fused_step(self, params_grads):
        """Subclass hook: apply ALL param updates as one jitted program (the
        reference's multi_tensor_adam, python/paddle/optimizer/adam.py
        `use_multi_tensor`). Return True when handled. Base: per-param path."""
        return False

    def _append_optimize_op(self, param, grad_data):
        raise NotImplementedError

    @no_grad()
    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=True):
        if self._parameter_list is not None:
            for p in self._parameter_list:
                if isinstance(p, Tensor):
                    p.clear_grad()

    clear_gradients = clear_grad

    # -- traced-step protocol (the TrainStep contract) ----------------------
    # TrainStep compiles step() into one XLA program by threading ALL
    # numeric optimizer state through the traced function. The contract a
    # subclass must keep for that to work:
    #   * every mutable numeric value lives in `_accumulators`,
    #     `_master_weights`, or `_step_count` (exposed by
    #     `opt_state_pytree`); NAdam's mu_product shows the pattern for
    #     extra scalars — store them in the accumulator dicts.
    #   * `warmup_state(params)` must create every accumulator the real
    #     step will touch, without changing values — the default runs the
    #     update ops with writes disabled (`_dry_run`), so subclasses that
    #     use `_get_accumulator`/`_set_accumulator`/`_write_param` get it
    #     for free. Override it only for exotic state.
    #   * `get_lr()` must respect `_lr_override` (call super or check the
    #     field) so the step's lr can be a traced input.

    def opt_state_pytree(self):
        """The numeric state threaded through a compiled train step."""
        accum = {
            name: {k: v for k, v in per.items()}
            for name, per in self._accumulators.items()
        }
        return {
            "accumulators": accum,
            "master_weights": dict(self._master_weights),
            "step": jnp.asarray(self._step_count, jnp.int32),
        }

    def load_opt_state_pytree(self, state):
        for name, per in state["accumulators"].items():
            self._accumulators.setdefault(name, {}).update(per)
        self._master_weights.update(state["master_weights"])
        self._step_count = state["step"]

    def warmup_state(self, params):
        """Create (at init values) every accumulator/master weight that
        step() will use for `params`, mutating nothing else."""
        self._dry_run = True
        try:
            for p in params:
                if self._use_master(p):
                    self._master_weight(p)
                pv = self._param_value(p)
                self._append_optimize_op(p, jnp.zeros(pv.shape, pv.dtype))
        finally:
            self._dry_run = False

    @_contextmanager
    def lr_frozen(self, lr):
        """Context: step() sees `lr` (typically a traced scalar) from
        get_lr() — the reference's LRScheduler stays host-side."""
        prev = self._lr_override
        self._lr_override = lr
        try:
            yield
        finally:
            self._lr_override = prev

    # -- state dict -----------------------------------------------------------
    def state_dict(self):
        import numpy as np

        state = {"accumulators": {}, "master_weights": {}, "step": self._step_count}
        for name, store in self._accumulators.items():
            state["accumulators"][name] = {k: np.asarray(v) for k, v in store.items()}
        state["master_weights"] = {
            k: np.asarray(v) for k, v in self._master_weights.items()
        }
        if isinstance(self._learning_rate, LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        return state

    def set_state_dict(self, state_dict):
        for name, store in state_dict.get("accumulators", {}).items():
            tgt = self._accumulators.setdefault(name, {})
            for k, v in store.items():
                tgt[k] = jnp.asarray(v)
        for k, v in state_dict.get("master_weights", {}).items():
            self._master_weights[k] = jnp.asarray(v)
        self._step_count = state_dict.get("step", 0)
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    load_state_dict = set_state_dict
