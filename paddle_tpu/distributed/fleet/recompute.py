"""Activation recomputation.

Reference parity: RecomputeFunction / recompute / recompute_sequential
(python/paddle/distributed/fleet/recompute/recompute.py:124,438,602) — a
PyLayer that reruns the forward during backward instead of saving
activations. TPU-first: `jax.checkpoint` (remat) expresses exactly this to
XLA, which then schedules the recompute inside the fused step program; no
manual RNG state save/restore is needed because dropout keys are traced
values threaded through the step state (framework/random.py).

Grads must flow to the segment's parameters, not only its inputs, so the
segment's Layer parameters are lifted to explicit tape inputs before
wrapping in jax.checkpoint.
"""
from __future__ import annotations

import jax

from ...framework.tensor import Tensor
from ...framework.autograd import apply_op, no_grad


def _collect_params(function, args):
    """Find the Parameters the segment can reach: Layers/bound methods,
    functools.partial targets, closure cells, and Layer args. Grads must
    flow to these, so they are lifted to explicit tape inputs."""
    import functools

    from ...nn.layer.layers import Layer, Parameter

    layers, params, seen = [], [], set()

    def visit(obj):
        if id(obj) in seen:
            return
        seen.add(id(obj))
        if isinstance(obj, Layer):
            layers.append(obj)
        elif isinstance(obj, Parameter):
            params.append(obj)
        elif isinstance(obj, functools.partial):
            visit(obj.func)
            for a in obj.args:
                visit(a)
            for v in obj.keywords.values():
                visit(v)
        elif callable(obj):
            owner = getattr(obj, "__self__", None)
            if owner is not None:
                visit(owner)
            closure = getattr(obj, "__closure__", None)
            if closure:
                for cell in closure:
                    try:
                        visit(cell.cell_contents)
                    except ValueError:
                        pass
            # globals referenced by name from the function body (co_names
            # covers `lambda a: lin(a)` with module-level `lin`)
            code = getattr(obj, "__code__", None)
            glb = getattr(obj, "__globals__", None)
            if code is not None and glb is not None:
                for name in code.co_names:
                    if name in glb:
                        target = glb[name]
                        from ...nn.layer.layers import Layer as _L

                        if isinstance(target, _L) or isinstance(
                            target, functools.partial
                        ) or (callable(target)
                              and getattr(target, "__self__", None)):
                            visit(target)

    visit(function)
    for a in args:
        if isinstance(a, Layer):
            visit(a)

    out, pseen = [], set()
    for lyr in layers:
        for p in lyr.parameters():
            if id(p) not in pseen:
                pseen.add(id(p))
                out.append(p)
    for p in params:
        if id(p) not in pseen:
            pseen.add(id(p))
            out.append(p)
    buffers = []
    for lyr in layers:
        for b in lyr.buffers():
            buffers.append(b)
    return out, buffers


def recompute(function, *args, **kwargs):
    """Run `function(*args)` without saving its intermediates; recompute them
    during backward (reference recompute.py:438).

    kwargs:
      policy: None (full remat, reference semantics) | "dots" (save matmul
        outputs that have no batch dims — linear/MLP activations persist,
        attention scores are recomputed; the TPU sweet spot: attention is
        the HBM-heavy part, linears are the FLOP-heavy part) | a jax
        checkpoint policy callable.
    """
    use_reentrant = kwargs.pop("use_reentrant", True)  # API parity; unused
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)  # traced RNG
    policy = kwargs.pop("policy", None)
    if isinstance(policy, str):
        try:
            policy = {
                "dots":
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                "nothing": None,
                "full": None,  # alias: save nothing == full recompute
            }[policy]
        except KeyError:
            raise ValueError(
                f"unknown recompute policy {policy!r}; use 'dots', "
                "'nothing'/'full', or a jax checkpoint policy callable"
            ) from None
    if kwargs:
        raise TypeError(f"unsupported recompute kwargs: {sorted(kwargs)}")

    params, buffers = _collect_params(function, args)
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    n_p, n_b, n_t = len(params), len(buffers), len(tensor_args)

    def pure(*datas):
        p_datas = datas[:n_p]
        b_datas = datas[n_p:n_p + n_b]
        a_datas = datas[n_p + n_b:]
        saved_p = [p._data for p in params]
        saved_b = [b._data for b in buffers]
        for p, d in zip(params, p_datas):
            p._data = d
        for b, d in zip(buffers, b_datas):
            b._data = d
        it = iter(a_datas)
        call_args = [Tensor._wrap(next(it)) if isinstance(a, Tensor) else a
                     for a in args]
        try:
            # The outer jax.vjp of the checkpointed fn owns ALL
            # differentiation of this segment; per-op tape vjps inside it
            # are discarded anyway, and worse, an inner jax.vjp CONSUMES
            # custom_vjp ops (flash attention) — their fwd kernels land raw
            # in the remat jaxpr and remat's JVP cannot differentiate them.
            # no_grad makes inner ops bind as plain jax calls.
            with no_grad():
                out = function(*call_args)
        finally:
            for p, d in zip(params, saved_p):
                p._data = d
            for b, d in zip(buffers, saved_b):
                b._data = d
        if isinstance(out, (tuple, list)):
            return tuple(o._data for o in out)
        return out._data

    ckpt = (jax.checkpoint(pure, policy=policy) if policy is not None
            else jax.checkpoint(pure))
    return apply_op(ckpt, params + buffers + tensor_args, name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Segment-wise recompute over a Sequential (reference recompute.py:602).

    ctx: {"segments": N} — split `functions` into N recomputed chunks.
    """
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else int(ctx)
    layers = list(functions)
    if segments <= 0:
        segments = 1
    per = max(1, len(layers) // segments)
    out = args
    i = 0
    while i < len(layers):
        chunk = layers[i:i + per]

        class _Seg:
            def __init__(self, mods):
                self.mods = mods

            def __call__(self, *xs):
                y = xs
                for m in self.mods:
                    y = m(*y) if isinstance(y, tuple) else m(y)
                    y = y if isinstance(y, tuple) else (y,)
                return y if len(y) > 1 else y[0]

        seg = _Seg(chunk)
        # lift all params of the chunk
        from ...nn.layer.layers import Layer

        class _Holder(Layer):
            def __init__(self, mods):
                super().__init__()
                for j, m in enumerate(mods):
                    self.add_sublayer(str(j), m)

        holder = _Holder(chunk)
        seg.__self__ = holder  # route _collect_layer to the chunk's params
        out = recompute(seg, *(out if isinstance(out, tuple) else (out,)))
        out = out if isinstance(out, tuple) else (out,)
        i += per
    return out if len(out) > 1 else out[0]


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Hybrid-parallel recompute (reference recompute_hybrid.py:265):
    recompute with mp-aware RNG state and optional activation
    partitioning/offload hints in `ctx` {mp_group, offload, partition}.

    TPU mapping: jax RNG is functional (key threading reproduces
    dropout exactly on replay — the reference needs its RNGStatesTracker
    for this), activation partitioning is what GSPMD already does to
    sharded intermediates, and offload corresponds to a host
    memory_kind policy. So the ctx keys are accepted and the remat core
    is the same `recompute`; `partition`/`offload` do not change
    numerics, only layout hints the XLA scheduler owns."""
    # ctx hints (mp_group/offload/partition) are deliberately unused:
    # functional RNG + GSPMD + XLA host-offload own those concerns
    return recompute(function, *args, **kwargs)
