"""Sharding stage 1 (ZeRO-1): optimizer-state partitioning.

Reference parity: DygraphShardingOptimizer
(fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:44) —
each sharding rank owns a slice of the optimizer states, updates its slice,
then the updated params are broadcast (V2 :571 fuses buffers into
reduce-scatter/all-gather).

TPU-first: "owning a slice" is a layout, not a code path — the inner
optimizer's accumulators and master weights get a NamedSharding over the
"sharding" mesh axis. XLA then computes each state update shard-locally and
all-gathers the fresh params exactly once per step (the V2 fused behavior),
because params remain replicated while the update operands are sharded.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....optimizer.optimizer import Optimizer


def _shardable_dim(shape, degree):
    for i, s in enumerate(shape):
        if s % degree == 0 and s >= degree:
            return i
    return None


def shard_state_arrays(state_dict_like, mesh, axis="sharding"):
    """Place every array in {key: array} whose shape allows it on the
    sharding axis (dim chosen per-array)."""
    degree = int(mesh.shape[axis])
    if degree <= 1:
        return state_dict_like
    out = {}
    for k, v in state_dict_like.items():
        dim = _shardable_dim(getattr(v, "shape", ()), degree)
        if dim is None:
            out[k] = v
        else:
            axes = [None] * v.ndim
            axes[dim] = axis
            out[k] = jax.device_put(v, NamedSharding(mesh, P(*axes)))
    return out


class DygraphShardingOptimizer:
    """Wraps an inner Optimizer; shards its accumulators + master weights
    over the sharding axis lazily after they are created."""

    def __init__(self, optimizer: Optimizer, hcg=None, group=None):
        self._inner_opt = optimizer
        if group is not None:
            self._mesh, self._axis = group.mesh, group.axes[0]
        else:
            from ... import env as _env

            hcg = hcg
            if hcg is not None:
                self._mesh = hcg.mesh
                self._axis = "sharding"
            else:
                self._mesh = _env.get_mesh()
                self._axis = ("sharding" if "sharding" in
                              self._mesh.axis_names else
                              self._mesh.axis_names[0])
        self._sharded_once = False
        self._comm_bucketer = None

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def attach_comm_bucketer(self, bucketer):
        """Record the stage-2 grad bucketer (its BucketAssignment is the
        deterministic param→bucket map the scatter-back uses). step()
        flushes any still-pending bucket collectives first, so an eager
        `loss.backward(); opt.step()` loop — or a user-jitted step that
        never calls apply_collective_grads — still syncs at the
        microbatch boundary."""
        self._comm_bucketer = bucketer

    def grad_bucket_assignment(self):
        return (self._comm_bucketer.assignment
                if self._comm_bucketer is not None else None)

    def _apply_shardings(self):
        opt = self._inner_opt
        for name, per in opt._accumulators.items():
            opt._accumulators[name] = shard_state_arrays(
                per, self._mesh, self._axis)
        opt._master_weights.update(
            shard_state_arrays(opt._master_weights, self._mesh, self._axis))
        # offloaded masters: shard_state_arrays re-homed them into HBM with
        # a mesh sharding; push them back to pinned host and refresh the
        # host/device sharding pair the traced update addresses
        opt._rehome_offloaded_masters()

    def step(self):
        if (self._comm_bucketer is not None
                and self._comm_bucketer.has_pending()):
            self._comm_bucketer.sync_pending()
        self._inner_opt.step()
        if not self._sharded_once:
            self._apply_shardings()
            self._sharded_once = True

    def reshard_state(self):
        """Apply shardings now (used by TrainStep warmup so the very first
        compiled step already has sharded states)."""
        self._apply_shardings()
        self._sharded_once = True

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        out = self._inner_opt.set_state_dict(sd)
        if self._sharded_once:
            self._apply_shardings()
        return out
