"""Hermetic training-numerics selftest (ISSUE 15 acceptance lane).

Run as ``python -m paddle_tpu.observability.numerics_selftest`` in a
clean JAX_PLATFORMS=cpu subprocess with 8 virtual host devices
(``python bench.py --numerics`` is the CLI; run_selftest wires it into
the BENCH record) and prints ONE JSON line:

* **monitor overhead** — the measured step-time cost of the in-graph
  stats block (FusedScanTrainStep numerics on vs off, min-of-N
  alternating A/B on the gpt selftest config) must stay <= 1%;
* **NaN provenance** — a NaN injected into layer k's params is
  attributed to chunk(k) on FusedScan, ShardedFusedScan (dp8) and
  PipelineScan (dp2×pp2), each with a ``nan_provenance`` flight-
  recorder event AND a crash-style dump file carrying the recent
  per-layer ring; on the fused path the non-finite guard additionally
  proves the interplay (step skipped, params bit-identical);
* **zero added collectives** — the per-axis collective census of the
  compiled dp8 sharded step (ClipGradByGlobalNorm active) is IDENTICAL
  with the monitor on and off: the grad-norm stats ride the clip's
  reductions (the ISSUE 15 dedup satellite's HLO probe — in
  particular, no duplicate norm all-reduce) and the stats block leaves
  the mesh as stacked per-rank partials, never a psum; on the dp2×pp2
  pipeline step the only permitted census delta is the scalar
  input-finiteness flag's per-tick collective-permute riding the ring
  (no added reductions);
* **retrace sentinel** — strict mode active for the whole lane; the
  instrumented fused + sharded steps hold ONE signature with zero
  unexpected recompiles;
* **spike detector** — after a warmed-up clean run (silent: zero
  anomalies) a 50× param inflation at layer 2 fires
  ``numerics.anomaly.count`` naming the spiked chunk;
* **/numericsz** — the debug-server endpoint serves every live
  monitor's per-chunk health table.
"""
from __future__ import annotations

import json
import os
import tempfile
import time

import numpy as np

TINY = dict(vocab_size=96, hidden_size=32, num_layers=4,
            num_attention_heads=2, max_position_embeddings=16,
            hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
            scan_layers=True)


def _model_opt(seed=0, clip=True, cfg_kw=TINY):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as popt
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(**cfg_kw)
    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    opt = popt.AdamW(
        learning_rate=1e-3, parameters=model.parameters(),
        grad_clip=nn.ClipGradByGlobalNorm(1.0) if clip else None)
    return model, opt


def _batch(rows=8, seq=16, seed=0, vocab=96):
    import paddle_tpu as paddle

    rng = np.random.default_rng(seed)
    ids = paddle.to_tensor(rng.integers(0, vocab, (rows, seq)),
                           dtype="int64")
    labels = paddle.to_tensor(rng.integers(0, vocab, (rows, seq)),
                              dtype="int64")
    return ids, labels


def run_probe(n_devices=8):
    import jax
    import paddle_tpu as paddle  # noqa: F401 — jax compat shims
    from paddle_tpu import observability as obs
    from paddle_tpu.models import GPTPretrainingCriterion

    devs = jax.devices("cpu")
    if len(devs) < n_devices:
        return {"numerics": {"check": f"FAIL: {len(devs)} cpu devices"}}
    obs.set_strict_retrace(True)     # active for the WHOLE lane
    rec, fails = {}, []

    def check(name, fn):
        try:
            fn()
            rec[name] = "pass"
        except Exception as e:  # noqa: BLE001 — recorded, not raised
            rec[name] = f"FAIL: {type(e).__name__}: {e}"[:300]
            fails.append(name)

    crit = GPTPretrainingCriterion()

    # -- measured monitor overhead <= 1% of step time ------------------
    def overhead():
        from paddle_tpu.jit import FusedScanTrainStep

        # the gpt selftest overhead config: long enough (s512) that
        # the stats block's cost — one extra pass per chunk output
        # plus O(params) reductions — is resolvable above host-CPU
        # timing noise. The statistic is the MEDIAN of per-round
        # paired (on - off) deltas over alternated rounds: load drift
        # hits both sides of a round equally, so pairing cancels it
        # where a min-of-N would inherit whichever side hit the
        # quieter moment.
        cfg = dict(TINY, vocab_size=256, hidden_size=128,
                   max_position_embeddings=512)
        ids, labels = _batch(rows=4, seq=512, vocab=256)
        steps = {}
        for on in (False, True):
            model, opt = _model_opt(clip=True, cfg_kw=cfg)
            steps[on] = FusedScanTrainStep(model, opt, criterion=crit,
                                           numerics=on)
            steps[on](ids, labels)           # compile outside timing
        def measure():
            times = {False: [], True: []}
            diffs = []
            for _ in range(10):              # alternate: shared noise
                for on in (False, True):
                    t0 = time.perf_counter()
                    loss = steps[on](ids, labels)
                    jax.block_until_ready(loss._data)
                    times[on].append(time.perf_counter() - t0)
                diffs.append(times[True][-1] - times[False][-1])
            off_ms = min(times[False]) * 1e3
            delta_ms = sorted(diffs)[len(diffs) // 2] * 1e3
            return off_ms, delta_ms, max(0.0, delta_ms) / off_ms

        # best of 2: the paired median still carries a few ms of
        # host-scheduler noise on a cpu-shares-capped box — a real >1%
        # overhead fails BOTH attempts, a single noisy window only one
        off_ms, delta_ms, ratio = measure()
        attempts = 1
        if ratio > 0.01:
            off_ms, delta_ms, ratio = measure()
            attempts = 2
        rec["overhead"] = {"step_ms_off": round(off_ms, 3),
                           "paired_median_delta_ms": round(delta_ms, 3),
                           "ratio": round(ratio, 5),
                           "attempts": attempts}
        assert ratio <= 0.01, rec["overhead"]
        # the monitor's own host cost per step is one deque append —
        # the deferred readback happens at flush, not per step
        mon = steps[True]._numerics
        assert mon.summary()["finite"] is True

    check("monitor_overhead", overhead)

    # -- NaN provenance on all three scan paths ------------------------
    def provenance(kind, bad_layer=2):
        import jax.numpy as jnp
        from paddle_tpu.distributed import env as denv
        from paddle_tpu.jit import (
            FusedScanTrainStep, ShardedFusedScanTrainStep,
        )
        from paddle_tpu.jit.pipeline_step import PipelineScanTrainStep

        with tempfile.TemporaryDirectory() as d:
            os.environ["PADDLE_FLIGHT_DIR"] = d
            try:
                model, opt = _model_opt(clip=True)
                if kind == "fused":
                    step = FusedScanTrainStep(
                        model, opt, criterion=crit,
                        guard_nonfinite=True)
                elif kind == "sharded":
                    mesh = denv.build_mesh({"sharding": n_devices})
                    denv.set_mesh(mesh)
                    step = ShardedFusedScanTrainStep(
                        model, opt, criterion=crit, mesh=mesh,
                        axis="sharding")
                else:
                    mesh = denv.build_mesh({"dp": 2, "pp": 2})
                    denv.set_mesh(mesh)
                    step = PipelineScanTrainStep(
                        model, opt, criterion=crit, mesh=mesh,
                        axis="dp", pp_axis="pp", num_micro=2)
                ids, labels = _batch()
                step(ids, labels)            # one clean step
                mon = step._numerics
                assert mon.summary()["finite"] is True
                # poison ONE layer's params: the forward origin is
                # chunk(bad_layer); everything downstream is poisoned
                # output, everything upstream sees NaN cotangents —
                # provenance must still name bad_layer
                p = step._s_params[0]
                before = np.asarray(p._data)
                p._data = p._data.at[bad_layer].set(jnp.float32("nan"))
                step(ids, labels)
                s = mon.summary()
                assert s["finite"] is False, s
                assert s["first_bad_chunk"] == bad_layer, s
                prov = mon.provenance()
                assert prov["first_bad_chunk"] == bad_layer, prov
                assert prov["origin"] == "activation", prov
                # flight recorder: the nan_provenance event is in the
                # ring AND a dump file landed
                events = [e for e in obs.recorder().snapshot()
                          if e.get("kind") == "nan_provenance"]
                assert events and events[-1]["first_bad_chunk"] == \
                    bad_layer, events[-1:]
                dumps = [f for f in os.listdir(d)
                         if f.startswith("crash_")]
                assert dumps, "no flight-recorder dump written"
                if kind == "fused":
                    # guard interplay: the bad step was SKIPPED — the
                    # clean layers' params are bit-identical and the
                    # skip counter advanced
                    after = np.asarray(step._s_params[0]._data)
                    ok = [i for i in range(TINY["num_layers"])
                          if i != bad_layer]
                    assert np.array_equal(before[ok], after[ok])
                    assert int(np.asarray(
                        jnp.asarray(step._guard._skipped))) == 1
                rec[f"provenance_{kind}"] = {
                    "first_bad_chunk": s["first_bad_chunk"],
                    "origin": prov["origin"], "dump": bool(dumps)}
            finally:
                os.environ.pop("PADDLE_FLIGHT_DIR", None)

    check("nan_provenance_fused", lambda: provenance("fused"))
    check("nan_provenance_sharded", lambda: provenance("sharded"))
    check("nan_provenance_pipeline", lambda: provenance("pipeline"))

    # -- zero added collectives (census on/off identical) --------------
    def collective_census():
        import jax.numpy as jnp
        from paddle_tpu.distributed import env as denv
        from paddle_tpu.jit import ShardedFusedScanTrainStep
        from paddle_tpu.observability.hlo_costs import load_hlo_overlap

        from paddle_tpu.jit.pipeline_step import PipelineScanTrainStep

        mod = load_hlo_overlap()

        def census(build, degrees):
            counts = {}
            for on in (False, True):
                step = build(on)
                step.ensure_built()
                state = step._extract_state()
                ids, labels = _batch()
                with step._step_guard():
                    text = step._jitted.lower(
                        state, jnp.float32(1e-3), ids._data,
                        labels._data, None).as_text()
                v = mod.analyze(text, axis_degrees=degrees)
                counts[on] = dict(v.get("counts", {}))
            return counts

        mesh = denv.build_mesh({"sharding": n_devices})
        denv.set_mesh(mesh)
        counts = census(
            lambda on: ShardedFusedScanTrainStep(
                *_model_opt(clip=True), criterion=crit, mesh=mesh,
                axis="sharding", numerics=on),
            {"sharding": n_devices})
        assert counts[True] == counts[False], counts
        # pipeline: the ONLY permitted delta is the scalar input-
        # finiteness flag riding the ring as a collective-permute per
        # tick (numerics.py docstring) — no added reductions
        pmesh = denv.build_mesh({"dp": 2, "pp": 2})
        denv.set_mesh(pmesh)
        pcounts = census(
            lambda on: PipelineScanTrainStep(
                *_model_opt(clip=True), criterion=crit, mesh=pmesh,
                axis="dp", pp_axis="pp", num_micro=2, numerics=on),
            {"dp": 2, "pp": 2})
        differing = {k for k in set(pcounts[False]) | set(pcounts[True])
                     if pcounts[False].get(k, 0) != pcounts[True].get(k, 0)}
        assert differing <= {"collective-permute"}, pcounts
        rec["collective_census"] = {
            "monitor_off": counts[False], "monitor_on": counts[True],
            "identical": True,
            "pipeline_off": pcounts[False], "pipeline_on": pcounts[True],
            "pipeline_delta_kinds": sorted(differing)}

    check("collective_census", collective_census)

    # -- retrace sentinel: strict + 1 signature with the monitor on ----
    def retrace_clean():
        from paddle_tpu.distributed import env as denv
        from paddle_tpu.jit import (
            FusedScanTrainStep, ShardedFusedScanTrainStep,
        )

        ids, labels = _batch()
        model, opt = _model_opt(clip=True)
        fstep = FusedScanTrainStep(model, opt, criterion=crit)
        for _ in range(3):
            fstep(ids, labels)
        st = fstep.retrace_stats()
        assert st["signatures"] == 1 and st["unexpected"] == 0, st
        if hasattr(fstep._jitted, "_cache_size"):
            assert fstep._jitted._cache_size() == 1
        mesh = denv.build_mesh({"sharding": n_devices})
        denv.set_mesh(mesh)
        model, opt = _model_opt(clip=True)
        sstep = ShardedFusedScanTrainStep(
            model, opt, criterion=crit, mesh=mesh, axis="sharding")
        for _ in range(3):
            sstep(ids, labels)
        st = sstep.retrace_stats()
        assert st["signatures"] == 1 and st["unexpected"] == 0, st
        rec["retrace"] = {"fused": fstep.retrace_stats()["signatures"],
                          "sharded": st["signatures"]}

    check("retrace_clean", retrace_clean)

    # -- spike detector: fires on a 50x spike, silent on clean ---------
    def spike():
        import jax.numpy as jnp
        from paddle_tpu.jit import FusedScanTrainStep

        model, opt = _model_opt(clip=False)
        step = FusedScanTrainStep(model, opt, criterion=crit)
        mon = step._numerics
        mon._warmup = 8
        ids, labels = _batch()
        base = obs.registry().counter("numerics.anomaly.count").value
        for _ in range(14):
            step(ids, labels)
        mon.flush()
        clean = obs.registry().counter("numerics.anomaly.count").value
        assert clean == base, f"anomaly on a clean run: {clean - base}"
        p = step._s_params[0]
        p._data = p._data.at[2].set(p._data[2] * 50.0)
        step(ids, labels)
        mon.flush()
        fired = obs.registry().counter("numerics.anomaly.count").value
        assert fired > base, "no anomaly on a 50x spike"
        chunks = {a["chunk"] for a in mon.anomalies()}
        assert 2 in chunks, mon.anomalies()
        rec["spike"] = {"anomalies": int(fired - base),
                        "chunks": sorted(chunks)}

    check("spike_detector", spike)

    # -- /numericsz endpoint -------------------------------------------
    def numericsz():
        import urllib.request

        from paddle_tpu.jit import FusedScanTrainStep

        model, opt = _model_opt(clip=True)
        step = FusedScanTrainStep(model, opt, criterion=crit)
        ids, labels = _batch()
        step(ids, labels)
        with obs.DebugServer() as srv:
            body = urllib.request.urlopen(
                f"{srv.url}/numericsz", timeout=10).read()
        payload = json.loads(body)
        mine = [m for m in payload["monitors"]
                if m.get("name") == "FusedScanTrainStep"
                and m.get("per_chunk")]
        assert mine, payload
        m = mine[-1]
        assert m["summary"]["finite"] is True
        assert len(m["per_chunk"]) == TINY["num_layers"] + 1
        assert all("grad_norm" in r and "update_ratio" in r
                   for r in m["per_chunk"])
        rec["numericsz_rows"] = len(m["per_chunk"])

    check("numericsz_endpoint", numericsz)

    summary = obs.retrace_summary()
    rec["retrace_summary"] = {
        "total_unexpected": summary["total_unexpected"],
        "strict": obs.strict_retrace(),
    }
    rec["check"] = ("pass" if not fails
                    else "FAIL: " + ", ".join(fails))
    return {"numerics": rec}


if __name__ == "__main__":
    print(json.dumps(run_probe()))
