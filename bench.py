"""Driver benchmark: flagship GPT training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-tree numbers (BASELINE.md), so vs_baseline is
reported against the north-star target qualitatively as null.

Runs a bf16 GPT (350M-class by default; override with BENCH_MODEL/BENCH_BS/
BENCH_SEQ env vars) through the whole-step-compiled TrainStep (one fused XLA
program per step: forward + backward + AdamW with fp32 master weights).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_config,
    )

    model_name = os.environ.get("BENCH_MODEL", "gpt3-350m")
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    batch = int(os.environ.get("BENCH_BS", "8"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))

    # recompute default OFF: with bf16 score storage + the logsumexp CE the
    # 350m/bs8/seq1024 step fits in 16G HBM without remat (35.9k tok/s vs
    # 31.9k with it) — PERF.md round-2 sweep
    cfg = gpt_config(model_name, max_position_embeddings=seq,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                     use_recompute=os.environ.get("BENCH_RECOMPUTE", "0") == "1",
                     recompute_policy=os.environ.get("BENCH_REMAT_POLICY",
                                                     "dots") or None)
    model = GPTForCausalLM(cfg)
    # bf16 params + fp32 master weights — the TPU-native AMP O2 layout
    model.bfloat16()
    crit = GPTPretrainingCriterion()
    opt = popt.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                     multi_precision=True,
                     moment_dtype=("bfloat16"
                                   if os.environ.get("BENCH_BF16_MOMENTS",
                                                     "1") == "1"
                                   else None))

    if os.environ.get("BENCH_FUSED_CE", "0") == "1":
        # fused LM head: chunked logsumexp, no [tokens, vocab] logits at
        # all. Measured slower than the dense lse-CE path at every config
        # that fits (PERF.md) — opt-in for vocab/memory regimes that don't
        def loss_fn(m, ids, labels):
            return m.loss(ids, labels)
    else:
        def loss_fn(m, ids, labels):
            return crit(m(ids), labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)), dtype="int64")
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)), dtype="int64")

    # warmup/compile
    loss = step(ids, labels)
    _ = float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    jax.block_until_ready(loss._data)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt

    # MFU: model flops per token = 6N (fwd+bwd matmuls) + attention
    # 12*L*h*s (QK^T + PV, fwd+bwd, causal ~halves but count full per
    # PaLM-appendix convention); peak from the chip generation.
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq
    peaks = {"v5e": 197e12, "v5litepod": 197e12, "v5p": 459e12,
             "v4": 275e12, "v6e": 918e12}
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e").lower()
    peak = next((v for k, v in peaks.items() if gen.startswith(k)), 197e12)
    mfu = tokens_per_sec * flops_per_token / peak
    print(json.dumps({
        "metric": f"{model_name}_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": None,
        "mfu": round(mfu, 4),
        "config": {"batch": batch, "seq": seq, "steps": steps,
                   "params": n_params,
                   "recompute": cfg.use_recompute},
    }))


if __name__ == "__main__":
    main()
