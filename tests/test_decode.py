"""BeamSearchDecoder + dynamic_decode (reference nn/decode.py): exact
agreement with exhaustive search when beam covers the whole lattice, and
a recurrent-cell smoke test."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode


class _TableCell:
    """Stateless cell: logits come from a fixed per-step table (state is
    the step counter), making exhaustive scoring tractable."""

    def __init__(self, table):
        self.table = table                  # [T, V] logits

    def __call__(self, inputs, states):
        t = int(np.asarray(states._data).reshape(-1)[0])
        b = inputs.shape[0]
        logits = paddle.to_tensor(
            np.tile(self.table[min(t, len(self.table) - 1)], (b, 1)))
        return logits, paddle.to_tensor(
            np.full((b,), t + 1, np.int64))


class TestBeamExactness:
    def test_full_beam_matches_exhaustive(self):
        import itertools
        import scipy.special as sps

        rng = np.random.default_rng(0)
        T, V = 3, 4
        end = 0
        table = rng.standard_normal((T, V)).astype(np.float32) * 2
        # forbid the end token so all sequences have length T
        table[:, end] = -50.0
        logp = np.log(sps.softmax(table, -1))

        cell = _TableCell(table)
        beam = V * V  # covers every lattice path at each step
        dec = BeamSearchDecoder(cell, start_token=1, end_token=end,
                                beam_size=beam)
        init = paddle.to_tensor(np.zeros((1,), np.int64))
        out, _ = dynamic_decode(dec, init, max_step_num=T)
        got = np.asarray(out._data)[0]      # [T, beam]

        scores = {}
        for seq in itertools.product(range(V), repeat=T):
            scores[seq] = sum(logp[t, v] for t, v in enumerate(seq))
        best = sorted(scores, key=scores.get, reverse=True)[:4]
        for rank in range(4):
            np.testing.assert_array_equal(got[:, rank], best[rank])

    def test_end_token_freezes_beam(self):
        T, V, end = 5, 3, 0
        table = np.full((T, V), -10.0, np.float32)
        table[0, end] = 10.0                # step 0 strongly prefers end
        dec = BeamSearchDecoder(_TableCell(table), start_token=1,
                                end_token=end, beam_size=2)
        init = paddle.to_tensor(np.zeros((2,), np.int64))
        out, _, lengths = dynamic_decode(dec, init, max_step_num=T,
                                         return_length=True)
        ids = np.asarray(out._data)
        # top beam: end at step 0, frozen to end thereafter, length 1
        assert (ids[:, :, 0] == end).all()
        assert (np.asarray(lengths._data)[:, 0] == 1).all()


class TestRecurrentSmoke:
    def test_gru_cell_decode(self):
        paddle.seed(0)
        V, H = 6, 8
        emb = paddle.nn.Embedding(V, H)
        cell = paddle.nn.GRUCell(H, H)
        proj = paddle.nn.Linear(H, V)

        class Wrap:
            def __call__(self, x, s):
                y, s2 = cell(x, s)
                return y, s2

        dec = BeamSearchDecoder(Wrap(), start_token=1, end_token=0,
                                beam_size=3, embedding_fn=emb,
                                output_fn=proj)
        init = paddle.to_tensor(np.zeros((2, H), np.float32))
        out, _, lengths = dynamic_decode(dec, init, max_step_num=7,
                                         return_length=True)
        ids = np.asarray(out._data)
        assert ids.shape[0] == 2 and ids.shape[2] == 3
        assert ids.shape[1] <= 7
        assert (np.asarray(lengths._data) <= 7).all()
