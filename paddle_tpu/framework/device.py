"""Device / place management.

Reference parity: `paddle.set_device` / `paddle.get_device` and the Place
hierarchy (paddle/phi/common/place.h; python/paddle/device/__init__.py).
TPU-first design: a "place" names a jax.Device; `set_device('tpu')` selects the
PJRT TPU client. There are no streams — XLA's async dispatch plays that role
(SURVEY.md §7 stage 1).
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


class Place:
    """A device place: ('tpu', 0) / ('cpu', 0)."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def is_tpu_place(self):
        return self.device_type == "tpu"

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def jax_device(self):
        return _jax_device_for(self.device_type, self.device_id)


class CPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("cpu", device_id)


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPlace(TPUPlace):
    """Compat shim: reference code constructing CUDAPlace(i) gets the
    accelerator (TPU) place — device_id semantics carry over."""


class CUDAPinnedPlace(CPUPlace):
    """Compat shim: pinned host memory is plain host memory under PJRT."""


# `axon` is the experimental tunnel platform name for the real chip in this
# environment; treat it as TPU.
_TPU_PLATFORMS = ("tpu", "axon")


def _available_platforms():
    plats = set()
    for d in jax.devices():
        plats.add(d.platform.lower())
    return plats


def _jax_device_for(device_type: str, device_id: int = 0):
    if device_type == "tpu":
        for plat in _TPU_PLATFORMS:
            try:
                devs = jax.devices(plat)
            except RuntimeError:
                continue
            if devs:
                return devs[min(device_id, len(devs) - 1)]
        # graceful fallback (tests run with JAX_PLATFORMS=cpu)
        return jax.devices()[min(device_id, len(jax.devices()) - 1)]
    if device_type == "cpu":
        try:
            devs = jax.devices("cpu")
            return devs[min(device_id, len(devs) - 1)]
        except RuntimeError:
            return jax.devices()[0]
    raise ValueError(f"unknown device type {device_type!r}")


def set_device(device: str) -> Place:
    """paddle.set_device parity: 'tpu', 'tpu:0', 'cpu'."""
    if ":" in device:
        dev_type, _, idx = device.partition(":")
        device_id = int(idx)
    else:
        dev_type, device_id = device, 0
    if dev_type == "gpu":
        # the reference's CUDA place; on this framework it aliases tpu
        dev_type = "tpu"
    if dev_type not in ("tpu", "cpu"):
        raise ValueError(
            f"device must be 'tpu' or 'cpu', got {device!r}"
        )
    place = TPUPlace(device_id) if dev_type == "tpu" else CPUPlace(device_id)
    _state.place = place
    return place


def get_device() -> str:
    place = current_place()
    return f"{place.device_type}:{place.device_id}"


def current_place() -> Place:
    place = getattr(_state, "place", None)
    if place is None:
        # default: tpu if a TPU/axon platform is present, else cpu
        plats = _available_platforms()
        if plats & set(_TPU_PLATFORMS):
            place = TPUPlace(0)
        else:
            place = CPUPlace(0)
        _state.place = place
    return place


def default_jax_device():
    return current_place().jax_device()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return bool(_available_platforms() & set(_TPU_PLATFORMS))


def device_count() -> int:
    return len(jax.devices())
