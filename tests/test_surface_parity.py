"""Living surface-parity guard (r5): the reference's public __all__
lists must stay fully covered — any regression (or future reference-
bump gap) fails here with the exact missing names. Skipped when the
reference checkout is not mounted."""
import os
import re

import pytest

import paddle_tpu as paddle

REF = "/root/reference/python/paddle"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference checkout not mounted")


def _ref_names(relpath):
    """Parse the module's literal __all__ via ast (a plain regex over
    the file also matches quoted names in docstrings)."""
    import ast as _ast

    with open(os.path.join(REF, relpath)) as f:
        src = f.read()
    try:
        tree = _ast.parse(src)
        for node in tree.body:
            if isinstance(node, _ast.Assign) and any(
                    isinstance(t, _ast.Name) and t.id == "__all__"
                    for t in node.targets):
                return sorted({e.value for e in node.value.elts
                               if isinstance(e, _ast.Constant)
                               and isinstance(e.value, str)})
    except SyntaxError:
        pass
    return sorted(set(re.findall(r"^\s+'(\w+)',", src, re.M)))


NAMESPACES = [
    ("", "__init__.py"),
    ("nn", "nn/__init__.py"),
    ("nn.functional", "nn/functional/__init__.py"),
    ("distributed", "distributed/__init__.py"),
    ("vision.transforms", "vision/transforms/__init__.py"),
    ("vision.ops", "vision/ops.py"),
    ("io", "io/__init__.py"),
    ("amp", "amp/__init__.py"),
    ("autograd", "autograd/__init__.py"),
    ("optimizer", "optimizer/__init__.py"),
    ("metric", "metric/__init__.py"),
    ("regularizer", "regularizer.py"),
    ("geometric", "geometric/__init__.py"),
    ("audio", "audio/__init__.py"),
    ("jit", "jit/__init__.py"),
    ("incubate", "incubate/__init__.py"),
    ("quantization", "quantization/__init__.py"),
    ("profiler", "profiler/__init__.py"),
    ("fft", "fft.py"),
    ("incubate.nn", "incubate/nn/__init__.py"),
    ("incubate.nn.functional", "incubate/nn/functional/__init__.py"),
    ("nn.utils", "nn/utils/__init__.py"),
    ("nn.initializer", "nn/initializer/__init__.py"),
    ("vision.datasets", "vision/datasets/__init__.py"),
    ("text", "text/__init__.py"),
    ("distributed.fleet", "distributed/fleet/__init__.py"),
    ("hapi.callbacks", "hapi/callbacks.py"),
    ("static", "static/__init__.py"),
    ("static.nn", "static/nn/__init__.py"),
    ("device", "device/__init__.py"),
    ("sparse", "sparse/__init__.py"),
    ("sparse.nn", "sparse/nn/__init__.py"),
    ("distribution", "distribution/__init__.py"),
    ("nn.quant", "nn/quant/__init__.py"),
    ("utils", "utils/__init__.py"),
    ("distributed.checkpoint", "distributed/checkpoint/__init__.py"),
    ("linalg", "linalg.py"),
    ("signal", "signal.py"),
    ("incubate.autograd", "incubate/autograd/__init__.py"),
    ("incubate.optimizer", "incubate/optimizer/__init__.py"),
    ("distributed.rpc", "distributed/rpc/__init__.py"),
    ("distributed.sharding", "distributed/sharding/__init__.py"),
    ("distributed.fleet.utils", "distributed/fleet/utils/__init__.py"),
    ("onnx", "onnx/__init__.py"),
    ("sysconfig", "sysconfig.py"),
    ("incubate.asp", "incubate/asp/__init__.py"),
    ("amp.debugging", "amp/debugging.py"),
    ("device.xpu", "device/xpu/__init__.py"),
    ("distributed.passes", "distributed/passes/__init__.py"),
    ("incubate.distributed.fleet",
     "incubate/distributed/fleet/__init__.py"),
]

# modules whose reference file has no __all__: hand-listed public names
EXPLICIT = [
    ("distributed.fleet.metrics",
     ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc"]),
    ("vision.transforms.functional",
     ["to_tensor", "resize", "pad", "crop", "center_crop", "hflip",
      "vflip", "adjust_brightness", "adjust_contrast",
      "adjust_saturation", "adjust_hue", "affine", "rotate",
      "perspective", "to_grayscale", "normalize", "erase"]),
    ("quantization.config", ["QuantConfig", "SingleLayerConfig"]),
    ("quantization.observers",
     ["AbsmaxObserver", "GroupWiseWeightObserver"]),
    ("quantization.quanters", ["FakeQuanterWithAbsMaxObserver"]),
]


@pytest.mark.parametrize("mod,relpath", NAMESPACES,
                         ids=[m or "paddle" for m, _ in NAMESPACES])
def test_namespace_surface(mod, relpath):
    obj = paddle
    for part in [p for p in mod.split(".") if p]:
        obj = getattr(obj, part)
    missing = [n for n in _ref_names(relpath) if not hasattr(obj, n)]
    assert not missing, f"paddle.{mod or ''} missing: {missing}"


@pytest.mark.parametrize("mod,names", EXPLICIT,
                         ids=[m for m, _ in EXPLICIT])
def test_explicit_surface(mod, names):
    obj = paddle
    for part in mod.split("."):
        obj = getattr(obj, part)
    missing = [n for n in names if not hasattr(obj, n)]
    assert not missing, f"paddle.{mod} missing: {missing}"


def test_tensor_method_surface():
    names = _ref_names("tensor/__init__.py")
    t = paddle.to_tensor([1.0, 2.0])
    missing = [n for n in names if not hasattr(t, n)]
    assert not missing, f"Tensor missing methods: {missing}"


def test_vision_models_families():
    names = _ref_names("vision/models/__init__.py")
    import paddle_tpu.vision.models as M

    missing = [n for n in names if not hasattr(M, n)]
    # LeNet naming etc. covered; any residual must be justified here
    assert not missing, f"vision.models missing: {missing}"
