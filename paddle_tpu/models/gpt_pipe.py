"""GPT pipeline-parallel model — stage-stacked transformer over the pp axis.

Reference parity: the GPTForCausalLMPipe pattern in Paddle's Fleet examples
(PipelineLayer of LayerDescs run by
fleet/meta_parallel/pipeline_parallel.py:231's 1F1B schedule). TPU-first:
the decoder blocks' parameters are STACKED on a leading
[n_stages, (num_chunks,) layers_per_stage, ...] dim sharded over the pp
mesh axis; the forward runs them through `pipeline_spmd`'s ppermute ring
inside the compiled step (spmd_pipeline.py). Embedding and the final
norm/head live outside the ring (classic first/last-stage asymmetry) and
compose with TP/ZeRO-3 through the same sharding-rule mechanism as the
plain GPT model.
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp

from .. import nn
from ..framework.tensor import Tensor
from ..framework.autograd import no_grad, apply_op
from ..nn.layer.layers import Parameter
from ..ops import creation as C
from .gpt import GPTConfig, GPTBlock, GPTPretrainingCriterion  # noqa: F401
from ..distributed.fleet.meta_parallel.spmd_pipeline import (
    pipeline_spmd, microbatch, unmicrobatch,
)


class GPTForCausalLMPipe(nn.Layer):
    """GPT with pipelined decoder blocks.

    Args:
      config: GPTConfig; ``num_layers`` must divide by
        ``num_stages * num_chunks``.
      num_stages: pp degree (mesh axis size).
      num_micro: micro-batches per step (the batch dim must divide by it).
      num_chunks: virtual stages per device (interleave, default 1).
      mesh/axis: the device mesh and its pipeline axis name; taken from the
        ambient distributed env when omitted.
    """

    def __init__(self, config: GPTConfig, num_stages, num_micro,
                 num_chunks=1, mesh=None, axis="pp", use_zero_bubble=False):
        super().__init__()
        self.config = config
        self.num_stages = int(num_stages)
        self.num_micro = int(num_micro)
        self.num_chunks = int(num_chunks)
        # zero-bubble dW-deferred backward (pipeline_spmd_zb): the reverse
        # ring computes dX only; weight grads fold off the critical path
        self.use_zero_bubble = bool(use_zero_bubble)
        if use_zero_bubble and num_chunks != 1:
            raise ValueError("zero-bubble supports num_chunks=1 only")
        if use_zero_bubble and (config.hidden_dropout_prob
                                or config.attention_dropout_prob):
            # the zb backward RE-TRACES the block (dX tick + dW fold);
            # eager dropout draws a fresh PRNG key per trace, so the
            # backward would differentiate forwards that never ran
            raise ValueError(
                "use_zero_bubble requires zero dropout (the hand-written "
                "backward re-traces the block; see pipeline_spmd_zb)")
        self._axis = axis
        self._mesh = mesh
        total = self.num_stages * self.num_chunks
        if config.num_layers % total:
            raise ValueError(
                f"num_layers {config.num_layers} must divide by "
                f"num_stages*num_chunks {total}")
        self.layers_per_stage = config.num_layers // total

        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)

        # template block: gives the param structure + the forward body; its
        # own (per-layer-shaped) params are NOT this model's parameters —
        # the stacked tensors below are. Stored via object.__setattr__ so
        # Layer.__setattr__ doesn't register it as a sublayer.
        object.__setattr__(self, "_template", GPTBlock(config))
        self._stacked_names = []
        lead = ((self.num_stages, self.layers_per_stage)
                if self.num_chunks == 1 else
                (self.num_stages, self.num_chunks, self.layers_per_stage))
        from ..framework.random import host_normal

        std = config.initializer_range
        for pname, p in self._template.named_parameters():
            shape = lead + tuple(p.shape)
            if p.ndim >= 2:
                data = host_normal(shape, std)
                if re.search(r"(out_proj|fc2)\.weight$", pname):
                    data = data / (2.0 * config.num_layers) ** 0.5
            else:
                data = jnp.broadcast_to(p._data, shape)
            flat = "blocks__" + pname.replace(".", "__")
            self.add_parameter(flat, Parameter(jnp.asarray(data)))
            self._stacked_names.append((flat, pname))

    # -- the pipelined middle -------------------------------------------
    def _mesh_axis(self):
        mesh = self._mesh
        if mesh is None:
            from ..distributed import env as denv

            mesh = denv.get_mesh()
        if mesh is None or self._axis not in mesh.axis_names:
            raise RuntimeError(
                f"GPTForCausalLMPipe needs a mesh with a {self._axis!r} axis")
        return mesh, self._axis

    def _block_fn(self):
        template = self._template
        leaves = [p for _, p in template.named_parameters()]
        training = self.training

        def one_layer(x, layer_leaves):
            with no_grad():
                saved = [p._data for p in leaves]
                for p, d in zip(leaves, layer_leaves):
                    p._data = d
                template.training = training
                try:
                    y = template._inner(Tensor._wrap(x))._data
                finally:
                    for p, d in zip(leaves, saved):
                        p._data = d
            return y, None

        if self.config.use_recompute:
            one_layer = jax.checkpoint(one_layer)

        def block_fn(stage_leaves, xmb):
            y, _ = jax.lax.scan(one_layer, xmb, stage_leaves)
            return y

        return block_fn

    def forward(self, input_ids, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = C.arange(0, s, dtype="int64").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)

        mesh, axis = self._mesh_axis()
        block_fn = self._block_fn()
        n_micro, n_chunks = self.num_micro, self.num_chunks
        stacked = [self._parameters[flat] for flat, _ in self._stacked_names]

        use_zb = self.use_zero_bubble

        def pipefn(xa, *leaves):
            xm = microbatch(xa, n_micro)
            if use_zb:
                from ..distributed.fleet.meta_parallel.spmd_pipeline \
                    import pipeline_spmd_zb

                out = pipeline_spmd_zb(block_fn, list(leaves), xm,
                                       mesh=mesh, axis=axis)
            else:
                out = pipeline_spmd(block_fn, list(leaves), xm, mesh=mesh,
                                    axis=axis, num_chunks=n_chunks)
            return unmicrobatch(out)

        hidden = apply_op(pipefn, [x] + stacked, name="pipeline_spmd")
        hidden = self.ln_f(hidden)
        from .. import ops

        return ops.matmul(hidden, self.wte.weight, transpose_y=True)


def gpt_pipe_sharding_rules(tp_axis="mp", fsdp_axis=None, num_chunks=1):
    """Megatron TP/ZeRO-3 specs for the stacked block params + the
    embedding/norm params outside the ring. The stacked leading dims are
    (pp, (chunks,) layers): pp-sharded, chunks/layers replicated."""
    lead = ("pp", None) if num_chunks == 1 else ("pp", None, None)

    def spec(*axes):
        return lead + tuple(axes)

    rules = [
        (r"blocks__attn__qkv__weight$", spec(fsdp_axis, tp_axis)),
        (r"blocks__attn__qkv__bias$", spec(tp_axis)),
        (r"blocks__attn__out_proj__weight$", spec(tp_axis, fsdp_axis)),
        (r"blocks__mlp__fc1__weight$", spec(fsdp_axis, tp_axis)),
        (r"blocks__mlp__fc1__bias$", spec(tp_axis)),
        (r"blocks__mlp__fc2__weight$", spec(tp_axis, fsdp_axis)),
        (r"blocks__", lead),            # remaining stacked (ln etc.)
        (r"\bwte\.weight$", (tp_axis, fsdp_axis)),
        (r"\bwpe\.weight$", (None, fsdp_axis)),
    ]
    return rules
