"""paddle.linalg.distributed — SUMMA / blocked factorizations /
eigensolvers on the 8-device host mesh (ISSUE 9 tentpole).

Contracts under test (ISSUE acceptance):
  * every op matches the single-device jnp.linalg reference at fp32
    tol <= 1e-4 (most are ~1e-6 on these sizes);
  * non-square and non-divisible global shapes work (internal padding);
  * the compiled per-device program of every op contains NO buffer the
    size of a full global matrix (panels move, matrices don't), checked
    over the optimized HLO with the per-axis collective census from
    tools/hlo_overlap.py.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.linalg import distributed as dla
from paddle_tpu.linalg.distributed import probe

TOL = 1e-4


@pytest.fixture(scope="module")
def grid():
    return dla.build_grid(devices=jax.devices("cpu")[:8])


@pytest.fixture(scope="module")
def grid2x2():
    return dla.build_grid(2, 2, devices=jax.devices("cpu")[:8])


def _rand(*shape, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape) * scale).astype(np.float32)


class TestGrid:
    def test_default_factors_all_devices(self, grid):
        r, c = dla.grid_shape(grid)
        assert r * c == 8 and grid.axis_names == ("rows", "cols")

    def test_square_subset(self):
        g = dla.build_grid(square=True, devices=jax.devices("cpu")[:8])
        assert dla.grid_shape(g) == (2, 2)

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="needs"):
            dla.build_grid(16, 16, devices=jax.devices("cpu")[:8])

    def test_block_cyclic_permutation_roundtrip(self):
        idx = dla.block_cyclic_permutation(24, 2, 4)
        inv = np.empty_like(idx)
        inv[idx] = np.arange(24)
        x = np.arange(24)
        np.testing.assert_array_equal(x[idx][inv], x)
        # blocks of 4, alternating owners 0,1,0,1,... -> owner-0 blocks
        # first (0, 2, 4), then owner-1 (1, 3, 5)
        np.testing.assert_array_equal(idx[:4], np.arange(0, 4))
        np.testing.assert_array_equal(idx[4:8], np.arange(8, 12))


class TestSUMMA:
    def test_parity_divisible(self, grid):
        a, b = _rand(64, 48, seed=1), _rand(48, 32, seed=2)
        got = np.asarray(dla.matmul(a, b, grid=grid))
        np.testing.assert_allclose(got, a @ b, atol=TOL)

    def test_parity_non_divisible_non_square(self, grid):
        a, b = _rand(37, 53, seed=3), _rand(53, 29, seed=4)
        got = np.asarray(dla.matmul(a, b, grid=grid))
        np.testing.assert_allclose(got, a @ b, atol=TOL)

    def test_more_panels(self, grid):
        a, b = _rand(32, 64, seed=5), _rand(64, 16, seed=6)
        got = np.asarray(dla.matmul(a, b, grid=grid, panels=16))
        np.testing.assert_allclose(got, a @ b, atol=TOL)

    def test_block_cyclic_layout(self, grid2x2):
        a, b = _rand(40, 24, seed=7), _rand(24, 36, seed=8)
        got = np.asarray(dla.matmul(a, b, grid=grid2x2, block_size=4))
        np.testing.assert_allclose(got, a @ b, atol=TOL)

    def test_block_cyclic_needs_square_grid(self, grid):
        with pytest.raises(ValueError, match="square grid"):
            dla.matmul(_rand(8, 8), _rand(8, 8), grid=grid,
                       block_size=2)

    def test_tensor_in_tensor_out(self, grid):
        a = paddle.to_tensor(_rand(16, 24, seed=9))
        b = paddle.to_tensor(_rand(24, 8, seed=10))
        out = dla.matmul(a, b, grid=grid)
        assert hasattr(out, "_data")
        np.testing.assert_allclose(
            np.asarray(out._data),
            np.asarray(a._data) @ np.asarray(b._data), atol=TOL)

    def test_inner_dim_mismatch_raises(self, grid):
        with pytest.raises(ValueError, match="inner dims"):
            dla.matmul(_rand(8, 9), _rand(8, 9), grid=grid)

    def test_compiled_callable_reused(self, grid):
        from paddle_tpu.linalg.distributed import _grid as G

        a, b = _rand(64, 48, seed=1), _rand(48, 32, seed=2)
        dla.matmul(a, b, grid=grid)
        n = len(G._jit_cache)
        dla.matmul(a + 1, b, grid=grid)      # same signature
        assert len(G._jit_cache) == n


class TestCholesky:
    def _spd(self, n, seed=0):
        x = _rand(n, n, seed=seed)
        return x @ x.T + n * np.eye(n, dtype=np.float32)

    def test_parity(self, grid2x2):
        spd = self._spd(32, seed=11)
        got = np.asarray(dla.cholesky(spd, grid=grid2x2))
        np.testing.assert_allclose(got, np.linalg.cholesky(spd),
                                   atol=TOL)

    def test_parity_non_divisible(self, grid2x2):
        spd = self._spd(37, seed=12)
        got = np.asarray(dla.cholesky(spd, grid=grid2x2))
        np.testing.assert_allclose(got, np.linalg.cholesky(spd),
                                   atol=TOL)

    def test_upper(self, grid2x2):
        spd = self._spd(16, seed=13)
        got = np.asarray(dla.cholesky(spd, upper=True, grid=grid2x2))
        np.testing.assert_allclose(got, np.linalg.cholesky(spd).T,
                                   atol=TOL)

    def test_rect_grid_rejected(self, grid):
        with pytest.raises(ValueError, match="square grid"):
            dla.cholesky(self._spd(16), grid=grid)

    def test_non_square_matrix_rejected(self, grid2x2):
        with pytest.raises(ValueError, match="square matrix"):
            dla.cholesky(_rand(8, 9), grid=grid2x2)


class TestQR:
    def _check(self, a, grid):
        q, r = dla.qr(a, grid=grid)
        q, r = np.asarray(q), np.asarray(r)
        m, n = a.shape
        np.testing.assert_allclose(q @ r, a, atol=TOL)
        np.testing.assert_allclose(q.T @ q, np.eye(n), atol=TOL)
        assert np.abs(np.tril(r, -1)).max() < TOL
        # sign-canonical parity vs the reference R (QR is unique up to
        # per-column sign for full-rank A)
        r_ref = np.linalg.qr(a, mode="reduced")[1]
        s, s_ref = np.sign(np.diag(r)), np.sign(np.diag(r_ref))
        np.testing.assert_allclose(r * s[:, None],
                                   r_ref * s_ref[:, None], atol=TOL)

    def test_parity_divisible(self, grid):
        self._check(_rand(128, 16, seed=14), grid)

    def test_parity_non_divisible(self, grid):
        self._check(_rand(101, 13, seed=15), grid)

    def test_wide_rejected(self, grid):
        with pytest.raises(ValueError, match="tall"):
            dla.qr(_rand(8, 16), grid=grid)

    def test_full_mode_rejected(self, grid):
        with pytest.raises(NotImplementedError, match="reduced"):
            dla.qr(_rand(32, 4), mode="complete", grid=grid)


class TestEigsh:
    def _sym_with_spectrum(self, n, lam, seed=0):
        rng = np.random.default_rng(seed)
        q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        a = (q * lam) @ q.T
        return (0.5 * (a + a.T)).astype(np.float32)

    def test_topk_parity(self, grid):
        # spectral gap λ5/λ4 ~ 0.01 -> ~6-iter convergence; 25 iters is
        # ample and keeps the unrolled-program compile cheap
        lam = np.array([10.0, 8.0, 6.0, 4.5]
                       + list(0.05 * np.random.default_rng(1).random(44)))
        a = self._sym_with_spectrum(48, lam, seed=16)
        w, v = dla.eigsh(a, k=4, iters=25, grid=grid)
        w, v = np.asarray(w), np.asarray(v)
        ref = np.sort(np.linalg.eigvalsh(a))[::-1][:4]
        np.testing.assert_allclose(w, ref, atol=TOL)
        # eigenvector residual ||Av - λv||
        assert np.abs(a @ v - v * w[None, :]).max() < TOL

    def test_non_divisible_n(self, grid):
        lam = np.array([5.0, 3.0] + [0.05] * 41)
        a = self._sym_with_spectrum(43, lam, seed=17)
        w, _ = dla.eigsh(a, k=2, iters=25, grid=grid)
        ref = np.sort(np.linalg.eigvalsh(a))[::-1][:2]
        np.testing.assert_allclose(np.asarray(w), ref, atol=TOL)

    def test_power_iteration(self, grid):
        lam = np.array([7.0] + [0.5] * 31)
        a = self._sym_with_spectrum(32, lam, seed=18)
        ev, vec = dla.power_iteration(a, iters=20, grid=grid)
        assert abs(float(ev) - 7.0) < TOL
        vec = np.asarray(vec)
        assert np.abs(a @ vec - float(ev) * vec).max() < TOL


class TestHLOReceipts:
    """The no-full-gather contract, on the compiled per-device HLO."""

    def test_summa_receipt(self, grid):
        low = dla.summa_lowered(64, 64, 64, grid=grid)
        v = probe.collective_receipt(low, grid, full_elems=64 * 64,
                                     what="matmul operand")
        assert v["no_full_matrix"]
        # one all-reduce per panel per operand, each over exactly ONE
        # mesh axis (lcm(4,2)=4 panels -> 4 + 4)
        pa = v["per_axis_counts"]
        assert pa["rows"]["all-reduce"] == 4
        assert pa["cols"]["all-reduce"] == 4
        assert "other" not in pa

    def test_cholesky_receipt(self, grid2x2):
        low = dla.cholesky_lowered(32, grid=grid2x2)
        v = probe.collective_receipt(low, grid2x2, full_elems=32 * 32,
                                     what="cholesky input")
        assert v["no_full_matrix"]
        # rows-axis panel all_gathers (XLA DCEs the final iteration's —
        # its trailing update is empty) + the diagonal-block broadcasts
        assert v["per_axis_counts"]["rows"]["all-gather"] >= 1
        assert v["per_axis_counts"]["rows"]["all-reduce"] >= 2

    def test_qr_receipt(self, grid):
        # m large so the [w*n, n] R-stack stays well under m*n
        low = dla.qr_lowered(1024, 16, grid=grid)
        v = probe.collective_receipt(low, grid, full_elems=1024 * 16,
                                     what="qr input")
        assert v["no_full_matrix"]
        # TSQR: exactly ONE gather, over the flattened grid
        assert v["counts"] == {"all-gather": 1}
        assert v["per_axis_counts"]["rows+cols"]["all-gather"] == 1

    @pytest.mark.slow
    def test_eigsh_receipt(self, grid):
        """Marked slow: the hermetic `distributed_linalg` selftest lane
        asserts the same census on every bench run."""
        low = dla.eigsh_lowered(64, k=4, iters=8, grid=grid)
        v = probe.collective_receipt(low, grid, full_elems=64 * 64,
                                     what="eigsh input")
        assert v["no_full_matrix"]
        # one cols psum + one rows gather per matvec (iters + 1
        # Rayleigh step)
        assert v["per_axis_counts"]["cols"]["all-reduce"] == 9
        assert v["per_axis_counts"]["rows"]["all-gather"] == 9

    def test_assert_no_full_matrix_flags_dense(self):
        # self-check: the probe actually fires on a full-size buffer
        text = "%p = f32[64,64] parameter(0)"
        with pytest.raises(AssertionError, match="materializes"):
            probe.assert_no_full_matrix(text, 64 * 64)


class TestNamespace:
    def test_paddle_linalg_surface(self):
        assert paddle.linalg.distributed is dla
        # the reference linalg surface rides along
        x = paddle.to_tensor(np.eye(3, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(paddle.linalg.inv(x)._data), np.eye(3))
