"""paddle.dataset (legacy corpus downloaders): every dataset here pulls
from the network; this environment has no egress. Use paddle.vision.
datasets with local files or wrap local data in paddle.io.Dataset."""


def __getattr__(name):
    raise RuntimeError(
        f"paddle.dataset.{name} downloads its corpus; no network egress "
        "here — load local files via paddle.io.Dataset/DataLoader")
