"""Eager dygraph autograd engine.

Reference parity: the eager autograd graph + backward engine
(paddle/fluid/eager/grad_node_info.h:197, paddle/fluid/eager/backward.cc:105).
TPU-first design: instead of hand-written per-op grad kernels, each op records
a `jax.vjp` closure at call time. The closure is itself traceable, so an entire
dygraph step (forward + backward + optimizer) can be wrapped in `jax.jit` — the
shape-keyed-executable-cache bet flagged in SURVEY.md §7 "hard parts".
"""
from __future__ import annotations

import threading
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


class no_grad:
    """Context manager & decorator disabling grad recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class GradNode:
    """One recorded op on the tape.

    `vjp` maps a tuple of output cotangents to a tuple of input cotangents
    (one per recorded input). `inputs` are the input Tensors (kept to route
    cotangents onward / accumulate into leaves).
    """

    __slots__ = ("vjp", "inputs", "outputs_meta", "num_outputs", "name",
                 "outputs", "__weakref__")

    def __init__(self, vjp, inputs, outputs_meta, name=""):
        self.vjp = vjp
        self.inputs = inputs  # list[Tensor]
        # list of (shape, jax_dtype) per output, to build zero cotangents
        self.outputs_meta = outputs_meta
        self.num_outputs = len(outputs_meta)
        self.name = name
        # weakrefs to output Tensors, set by apply_op — used to run grad
        # hooks / retain_grads on the *accumulated* output cotangent
        self.outputs = [None] * self.num_outputs

    def release(self):
        self.vjp = None
        self.inputs = ()


def _is_float0(x):
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _zero_cotangent(meta):
    shape, dtype = meta
    if not jnp.issubdtype(dtype, jnp.floating) and not jnp.issubdtype(
        dtype, jnp.complexfloating
    ):
        return np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def _topo_order(root_nodes):
    """Reverse-topological order (outputs first) over the node graph.

    Mirrors the in-degree BFS of the reference backward engine
    (paddle/fluid/eager/backward.cc:224 getInDegreeMap).
    """
    visited = set()
    order = []
    # iterative DFS postorder, then reverse
    for root in root_nodes:
        if id(root) in visited:
            continue
        stack = [(root, False)]
        while stack:
            node, processed = stack.pop()
            if processed:
                order.append(node)
                continue
            if id(node) in visited:
                continue
            visited.add(id(node))
            stack.append((node, True))
            for t in node.inputs:
                child = t._grad_node
                if child is not None and id(child) not in visited:
                    stack.append((child, False))
    order.reverse()
    return order


def run_backward(
    tensors,
    grad_tensors=None,
    retain_graph=False,
    capture=None,
    accumulate_leaf=True,
):
    """The backward engine (reference: egr::RunBackward, backward.cc:105).

    tensors: list of output Tensors to seed.
    grad_tensors: optional list of seed cotangents (Tensor or None).
    capture: optional dict id(tensor)->tensor; when given, returns the
        accumulated cotangent for each captured tensor (paddle.grad path).
    accumulate_leaf: write `.grad` on leaf tensors (loss.backward path).
    """
    from .tensor import Tensor

    # node -> list of cotangents (one slot per output)
    cotangents: dict[int, list] = {}
    # leaf accumulation buffer: id -> [tensor, cotangent]. Leaves accumulate
    # here so their hooks run ONCE on the total gradient (reference:
    # GradNodeAccumulation fires hooks on the accumulated grad).
    leaf_acc: dict[int, list] = {}
    captured = {} if capture is not None else None

    def seed(node, idx, value):
        slots = cotangents.setdefault(id(node), [None] * node.num_outputs)
        slots[idx] = value if slots[idx] is None else slots[idx] + value

    def route(t, g):
        """Send a cotangent toward tensor t (accumulates at t's node slot or
        the leaf buffer; hooks fire later, on the total)."""
        child = t._grad_node
        if child is None:
            ent = leaf_acc.setdefault(id(t), [t, None])
            ent[1] = g if ent[1] is None else ent[1] + g
        else:
            seed(child, t._out_index, g)

    def apply_hooks(t, g):
        for hook in t._backward_hooks:
            out = hook(Tensor._wrap(g))
            if out is not None:
                g = out._data if isinstance(out, Tensor) else jnp.asarray(out)
        return g

    root_nodes = []
    for i, t in enumerate(tensors):
        if grad_tensors is not None and grad_tensors[i] is not None:
            g = grad_tensors[i]
            g = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        else:
            if t._data.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {list(t._data.shape)}"
                )
            g = jnp.ones_like(t._data)
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                route(t, g)
            elif capture is not None and id(t) in capture:
                captured[id(t)] = g
            continue
        root_nodes.append(node)
        seed(node, t._out_index, g)

    order = _topo_order(root_nodes)

    for node in order:
        slots = cotangents.pop(id(node), None)
        if slots is None:
            continue
        if node.vjp is None:
            raise RuntimeError(
                "trying to backward through the graph a second time; "
                "specify retain_graph=True if needed"
            )
        full = [
            s if s is not None else _zero_cotangent(m)
            for s, m in zip(slots, node.outputs_meta)
        ]
        # each slot now holds the TOTAL cotangent of that output tensor:
        # run its hooks / capture / retain_grads here
        for i in range(node.num_outputs):
            ref = node.outputs[i]
            t = ref() if ref is not None else None
            if t is None:
                continue
            if t._backward_hooks and slots[i] is not None:
                full[i] = apply_hooks(t, full[i])
            if captured is not None and id(t) in capture:
                captured[id(t)] = full[i]
            if accumulate_leaf and t._retain_grads:
                t._accumulate_grad(full[i])
        if node.num_outputs == 1:
            in_cots = node.vjp(full[0])
        else:
            in_cots = node.vjp(tuple(full))
        for t, g in zip(node.inputs, in_cots):
            if _is_float0(g) or t.stop_gradient:
                continue
            route(t, g)
        if not retain_graph:
            node.release()

    for tid, (t, g) in leaf_acc.items():
        g = apply_hooks(t, g)
        if captured is not None and tid in capture:
            captured[tid] = g
        if accumulate_leaf:
            t._accumulate_grad(g)

    return captured


# active (pack, unpack) pair installed by autograd.saved_tensors_hooks
_saved_tensor_hooks = None


_OP_OBSERVER = None     # set by amp.debugging operator-stats collection


def set_op_observer(observer):
    """Install (or clear, with None) a callback `observer(name, inputs)`
    invoked for every apply_op call — checked INSIDE apply_op so every
    module that imported apply_op by value is still observed."""
    global _OP_OBSERVER
    prev = _OP_OBSERVER
    _OP_OBSERVER = observer
    return prev


def apply_op(fn, inputs, attrs=None, name="", num_outputs=None):
    """Execute `fn(*jax_arrays, **attrs)` and record a GradNode if needed.

    Mirrors the generated ad_func pattern
    (paddle/fluid/eager/api/manual/eager_manual/forwards/multiply_fwd_func.cc:40):
    run forward, then wire a grad node if any input requires grad.
    Returns Tensor or tuple of Tensors matching fn's output structure.
    """
    from .tensor import Tensor

    if _OP_OBSERVER is not None:
        _OP_OBSERVER(name or getattr(fn, "__name__", "op"), inputs)
    attrs = attrs or {}
    datas = [t._data for t in inputs]
    needs_grad = is_grad_enabled() and any(not t.stop_gradient for t in inputs)

    hooks = _saved_tensor_hooks
    if needs_grad and hooks is not None:
        # saved_tensors_hooks contract (autograd.saved_tensors_hooks):
        # the tape keeps only pack_hook(input) per input and RECOMPUTES
        # the op's vjp from unpack_hook at backward time — the genuine
        # offload-saved-tensors semantics (recompute trades the fwd once
        # more for whatever memory the pack moved off-device)
        pack, unpack = hooks
        f = (lambda *xs: fn(*xs, **attrs)) if attrs else fn
        packed = [pack(d) for d in datas]
        outs = fn(*datas, **attrs)

        def vjp(cts, _f=f, _packed=packed, _unpack=unpack):
            redone = [_unpack(p) for p in _packed]
            _, inner = jax.vjp(_f, *redone)
            return inner(cts)
    elif needs_grad:
        f = (lambda *xs: fn(*xs, **attrs)) if attrs else fn
        outs, vjp = jax.vjp(f, *datas)
    else:
        outs = fn(*datas, **attrs)
        vjp = None

    single = not isinstance(outs, (tuple, list))
    outs_tuple = (outs,) if single else tuple(outs)

    if needs_grad:
        import weakref

        meta = [(o.shape, o.dtype) for o in outs_tuple]
        node = GradNode(vjp, list(inputs), meta, name=name)
        wrapped = tuple(
            Tensor._wrap(o, stop_gradient=False, grad_node=node, out_index=i)
            for i, o in enumerate(outs_tuple)
        )
        node.outputs = [weakref.ref(t) for t in wrapped]
    else:
        wrapped = tuple(Tensor._wrap(o, stop_gradient=True) for o in outs_tuple)

    return wrapped[0] if single else wrapped
