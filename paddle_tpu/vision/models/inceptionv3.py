"""Inception v3 (Szegedy et al., 2016). Reference parity surface:
python/paddle/vision/models/inceptionv3.py; architecture from the paper
(factorized 7x7, grid-reduction blocks, expanded-filter-bank tail)."""
from __future__ import annotations

from ... import nn


class _ConvBN(nn.Sequential):
    def __init__(self, inp, out, kernel, stride=1, padding=0):
        super().__init__(
            nn.Conv2D(inp, out, kernel, stride=stride, padding=padding,
                      bias_attr=False),
            nn.BatchNorm2D(out), nn.ReLU())


def _cat(parts):
    from ... import ops

    return ops.concat(parts, axis=1)


class _InceptionA(nn.Layer):
    def __init__(self, inp, pool_ch):
        super().__init__()
        self.b1 = _ConvBN(inp, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(inp, 48, 1),
                                _ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBN(inp, 64, 1),
                                _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(inp, pool_ch, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)])


class _ReductionA(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b3 = _ConvBN(inp, 384, 3, stride=2)
        self.b3d = nn.Sequential(_ConvBN(inp, 64, 1),
                                 _ConvBN(64, 96, 3, padding=1),
                                 _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat([self.b3(x), self.b3d(x), self.pool(x)])


class _InceptionB(nn.Layer):
    def __init__(self, inp, mid):
        super().__init__()
        self.b1 = _ConvBN(inp, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(inp, mid, 1),
            _ConvBN(mid, mid, (1, 7), padding=(0, 3)),
            _ConvBN(mid, 192, (7, 1), padding=(3, 0)))
        self.b77 = nn.Sequential(
            _ConvBN(inp, mid, 1),
            _ConvBN(mid, mid, (7, 1), padding=(3, 0)),
            _ConvBN(mid, mid, (1, 7), padding=(0, 3)),
            _ConvBN(mid, mid, (7, 1), padding=(3, 0)),
            _ConvBN(mid, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(inp, 192, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b7(x), self.b77(x), self.bp(x)])


class _ReductionB(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(inp, 192, 1),
                                _ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _ConvBN(inp, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return _cat([self.b3(x), self.b7(x), self.pool(x)])


class _InceptionC(nn.Layer):
    def __init__(self, inp):
        super().__init__()
        self.b1 = _ConvBN(inp, 320, 1)
        self.b3_stem = _ConvBN(inp, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b33_stem = nn.Sequential(_ConvBN(inp, 448, 1),
                                      _ConvBN(448, 384, 3, padding=1))
        self.b33_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b33_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(inp, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        t = self.b33_stem(x)
        return _cat([self.b1(x),
                     _cat([self.b3_a(s), self.b3_b(s)]),
                     _cat([self.b33_a(t), self.b33_b(t)]),
                     self.bp(x)])


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64),
            _ReductionA(288),
            _InceptionB(768, 128), _InceptionB(768, 160),
            _InceptionB(768, 160), _InceptionB(768, 192),
            _ReductionB(768),
            _InceptionC(1280), _InceptionC(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights need egress; load a state_dict instead")
    return InceptionV3(**kwargs)
