"""nn.utils parity (reference python/paddle/nn/utils/):
spectral_norm / weight_norm wrappers, parameter vector helpers."""
from .spectral_norm import SpectralNorm, spectral_norm  # noqa: F401
from .weight_norm import weight_norm, remove_weight_norm  # noqa: F401


def parameters_to_vector(parameters, name=None):
    # built from ops so the result stays on the autograd tape (an
    # L2-over-flattened-params loss must reach the parameters)
    from ...ops import concat, reshape

    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        chunk = vec._data[offset:offset + n].reshape(p._data.shape)
        p._data = chunk.astype(p._data.dtype)   # keep the param's dtype
        offset += n


from ..clip import clip_grad_norm_  # noqa: E402,F401  (stub-era export)
