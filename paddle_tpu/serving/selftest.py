"""Hermetic serving selftest: continuous batching proven on a tiny model.

Run as ``python -m paddle_tpu.serving.selftest`` in a clean
JAX_PLATFORMS=cpu subprocess (bench.py run_selftest wires it through
the same env-strip recipe as the other lanes) and prints ONE JSON line
for BENCH_r*.json:

* **parity/churn** — Poisson arrivals admitted mid-flight produce, per
  request, exactly the tokens `model.generate()` produces for that
  request alone (continuous batching must not change anyone's output);
  zero leaked pages/slots at drain; the decode step stays at ONE trace
  while sequences are admitted, preempted and retired mid-flight.
* **preempt/resume** — an oversubscribed page pool forces preemptions;
  outputs stay identical to the fully-provisioned run (sampled, not
  greedy, so the per-request RNG streams are what is being proven).
* **bounded TTFT** — under saturating load with chunked prefill, p99
  TTFT stays within a budget derived from the measured decode step
  time (chunks interleave with decode, so arrivals never wait for a
  whole long prompt to prefill).
* **traffic A/B** — continuous vs static generate-and-wait batching at
  three concurrency levels: p50/p99 TTFT and aggregate tok/s, with
  continuous required to win on tok/s at the highest level.
* **pool hygiene (ISSUE 14)** — `PagedKVCache.pool_stats()` leak
  assertions on the churn and preemption lanes: after drain the pool
  reads fully free (used 0, per-slot counts empty, fragmentation 0.0,
  used+free == total).
* **trace forensics (ISSUE 13)** — under churn with preemptions every
  retired request's trace is a complete causal timeline (root span
  with >=1 prefill child and >=1 decode child; preempted-then-resumed
  requests show preempt + resume-prefill spans), zero orphan spans
  remain after drain + ``abort_all`` (including an abort taken
  MID-FLIGHT and then drained), tail exemplars populate under a low
  quantile, and the retrace sentinel still reports 0 unexpected
  recompiles with the tracing instrumentation live.
"""
from __future__ import annotations

import json
import time


def _tiny_model():
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=192,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m, cfg


def run_probe():
    import numpy as np

    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.traffic import (poisson_traffic,
                                            run_continuous, run_static)

    # ISSUE 12: strict retrace sentinel for the whole serving lane —
    # the PR-6 silent-recompile class (metadata numpy/device drift)
    # raises instead of silently recompiling; prefill length buckets
    # are declared expected, so a clean lane must not trip
    from paddle_tpu import observability as obs

    obs.set_strict_retrace(True)

    m, cfg = _tiny_model()
    rec, fails = {}, []

    def check(name, fn):
        try:
            fn()
            rec[name] = "pass"
        except Exception as e:  # noqa: BLE001 — recorded, not raised
            rec[name] = f"FAIL: {type(e).__name__}: {e}"[:300]
            fails.append(name)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, (pl,))
               for pl in (5, 11, 19, 26, 8, 14)]

    # -- parity + churn + retrace stability -------------------------------
    def churn_parity():
        eng = ServingEngine(m, max_slots=3, max_len=64, page_size=8,
                            chunk_size=8)
        handles = []
        # staggered submits: later requests join while earlier ones
        # decode (admission mid-flight), slots churn through 6 requests
        for i, p in enumerate(prompts):
            handles.append(eng.submit(p, 6 + (i % 3) * 3))
            for _ in range(2):
                eng.step()
        eng.run(max_steps=5000)
        for h in handles:
            ref = m.generate(np.asarray(h.request.prompt)[None],
                             max_new_tokens=h.request.max_new_tokens,
                             use_cache="paged")
            assert np.asarray(ref._data)[0].tolist() == \
                h.output_tokens, f"rid {h.request.rid} diverged"
        leaks = eng.leak_check()
        assert leaks["free_pages"] == leaks["total_pages"], leaks
        assert leaks["free_slots"] == leaks["total_slots"], leaks
        # pool_stats leak assertions (ISSUE 14): after drain the pool
        # must read fully free, unfragmented, with zero per-slot pages
        # — and the used+free==total invariant must have held
        ps = eng.cache.pool_stats()
        assert ps["used_pages"] == 0 and ps["slot_pages"] == {}, ps
        assert ps["free_pages"] == ps["total_pages"], ps
        assert ps["used_pages"] + ps["free_pages"] == \
            ps["total_pages"], ps
        assert ps["fragmentation"] == 0.0 and \
            ps["max_contiguous_free"] == ps["free_pages"], ps
        rec["pool_stats_after_drain"] = ps
        cc = eng.compile_counts()
        assert cc["decode_traces"] == 1, cc
        assert cc["prefill_traces"] <= len(cc["chunk_buckets"]), cc
        rec["churn_compile"] = cc
        rec["churn_metrics"] = {
            k: eng.metrics_snapshot()[k]
            for k in ("finished", "preemptions", "decode_steps",
                      "prefill_chunks")}

    # -- preempt -> resume bit-parity (sampled) ---------------------------
    def preempt_resume():
        def serve(num_pages):
            eng = ServingEngine(m, max_slots=4, max_len=48, page_size=8,
                                chunk_size=8, num_pages=num_pages,
                                do_sample=True, temperature=1.0)
            hs = [eng.submit(p, 12, seed=100 + i)
                  for i, p in enumerate(prompts[:4])]
            eng.run(max_steps=5000)
            return eng, hs

        full_eng, full = serve(None)
        tight_eng, tight = serve(9)    # 8 usable pages -> pool dries up
        assert tight_eng.metrics.preemptions >= 1, \
            "pool never dried — selftest is not exercising preemption"
        # the preemption-churned pool must also drain leak-free
        ps = tight_eng.cache.pool_stats()
        assert ps["used_pages"] == 0 and ps["slot_pages"] == {}, ps
        assert ps["free_pages"] == ps["total_pages"], ps
        assert full_eng.metrics.preemptions == 0
        for a, b in zip(full, tight):
            assert a.output_tokens == b.output_tokens, \
                f"rid {a.request.rid}: resume changed the stream"
        leaks = tight_eng.leak_check()
        assert leaks["free_pages"] == leaks["total_pages"], leaks
        rec["preemptions"] = tight_eng.metrics.preemptions

    # -- bounded TTFT under load -----------------------------------------
    def bounded_ttft():
        eng = ServingEngine(m, max_slots=4, max_len=128, page_size=8,
                            chunk_size=8).warmup()
        # serve-lane cold start (ISSUE 17): warmup wall + how many of
        # the compiled programs came from the persistent cache
        rec["cold_start"] = eng.warmup_report
        t0 = time.perf_counter()
        eng.submit(prompts[1], 4)
        eng.run(max_steps=400)
        step_s = (time.perf_counter() - t0) / 6
        eng.reset_metrics()
        traffic = poisson_traffic(16, rate_rps=400.0,
                                  vocab_size=cfg.vocab_size,
                                  prompt_lens=(6, 80),
                                  out_lens=(6, 24), seed=2)
        recc, handles = run_continuous(eng, traffic)
        assert recc["finished"] == 16, recc
        assert all(h.done for h in handles)
        # chunked prefill bounds TTFT: even the worst arrival waits at
        # most a queue of bounded chunks + decode steps, never a whole
        # long prefill per resident sequence; 400 engine steps of slack
        # is orders looser than that but catches a stalled scheduler
        budget = max(step_s * 400, 2.0)
        assert recc["ttft_p99_s"] < budget, (recc, step_s)
        leaks = eng.leak_check()
        assert leaks["free_pages"] == leaks["total_pages"], leaks
        assert eng.compile_counts()["decode_traces"] == 1
        rec["ttft_under_load"] = {
            "ttft_p50_s": recc["ttft_p50_s"],
            "ttft_p99_s": recc["ttft_p99_s"],
            "budget_s": round(budget, 3),
            "tok_s": recc["tok_s"],
        }

    # -- continuous vs static A/B at 3 concurrency levels -----------------
    def traffic_ab():
        levels = {}
        win = 0
        for users in (2, 4, 8):
            # realistic serving shape: short prompts, heavy-tailed
            # output budgets — generate-and-wait pays the batch max for
            # every member, continuous batching recycles the slot
            traffic = poisson_traffic(
                3 * users, rate_rps=200.0, vocab_size=cfg.vocab_size,
                prompt_lens=(4, 24), out_lens=(4, 96), seed=10 + users)
            eng = ServingEngine(m, max_slots=users, max_len=120,
                                page_size=8, chunk_size=16,
                                prefill_chunks_per_step=2,
                                decode_burst=4).warmup()
            cont, _ = run_continuous(eng, traffic)
            stat = run_static(m, traffic, concurrency=users,
                              max_len=120, page_size=8)
            win += cont["tok_s"] > stat["tok_s"]
            levels[f"users{users}"] = {
                "continuous": {k: cont[k] for k in
                               ("tok_s", "ttft_p50_s", "ttft_p99_s",
                                "finished", "preemptions")},
                "static": stat,
            }
        rec["traffic_ab"] = levels
        assert levels["users8"]["continuous"]["tok_s"] > \
            levels["users8"]["static"]["tok_s"], levels["users8"]
        rec["continuous_wins"] = f"{win}/3"

    # -- trace completeness under churn with preemptions (ISSUE 13) -------
    def trace_forensics():
        def churn(eng, n_tok=8):
            hs = []
            for i, p in enumerate(prompts):
                hs.append(eng.submit(p, n_tok, seed=50 + i))
                eng.step()
            eng.run(max_steps=5000)
            return hs

        eng = ServingEngine(m, max_slots=3, max_len=48, page_size=8,
                            chunk_size=8, num_pages=10, do_sample=True,
                            exemplar_quantile=50.0,
                            exemplar_min_samples=4)
        handles = churn(eng)
        assert eng.metrics.preemptions >= 1, \
            "pool never dried — forensics lane not exercising preemption"
        for h in handles:
            root = eng.request_trace(h.request.rid)
            assert root is not None and root.closed, h
            assert len(root.find("prefill_chunk")) >= 1, h
            assert len(root.find("decode_burst")) >= 1, h
            assert root.attrs.get("finish") in ("eos", "length"), root
            if h.preemptions:
                pre = root.find("preempt")
                assert len(pre) == h.preemptions, (h.preemptions, pre)
                assert any(c.attrs.get("resume")
                           for c in root.find("prefill_chunk")), h
                assert len(root.find("queue_wait")) == \
                    1 + h.preemptions, h
        # drained: no open spans, no orphans, and abort_all (a no-op
        # now) leaves it that way
        eng.scheduler.abort_all()
        assert not eng.tracer.open_spans(), eng.tracer.open_spans()
        assert not eng.tracer.orphans(), eng.tracer.orphans()
        # tail exemplars populated under the low quantile
        slow = eng.slow_requests()
        assert slow and all("trace" in s and "reason" in s
                            for s in slow), slow
        rec["trace_exemplars"] = len(slow)
        rec["trace_spans"] = eng.tracer.stats()

        # mid-flight abort: every resident request re-queues with a
        # preempt(abort) span and an OPEN queue_wait (alive, waiting —
        # not an orphan); draining closes everything
        eng2 = ServingEngine(m, max_slots=2, max_len=48, page_size=8,
                             chunk_size=8, num_pages=9)
        hs2 = [eng2.submit(p, 6) for p in prompts[:3]]
        for _ in range(3):
            eng2.step()
        aborted = eng2.scheduler.abort_all()
        assert aborted, "abort_all found nothing resident"
        assert not eng2.tracer.orphans(), eng2.tracer.orphans()
        eng2.run(max_steps=5000)
        assert all(h.done for h in hs2)
        assert not eng2.tracer.open_spans() and not eng2.tracer.orphans()
        for h in hs2:
            root = eng2.request_trace(h.request.rid)
            assert root is not None and root.closed
            if any(s.attrs.get("reason") == "abort"
                   for s in root.find("preempt")):
                assert any(c.attrs.get("resume")
                           for c in root.find("prefill_chunk")), h
        # tracing instrumentation added zero unexpected recompiles
        assert obs.retrace_summary()["total_unexpected"] == 0

    # -- closed-loop tuner + persistent cache, strict sentinel (ISSUE 17)
    def tuner_closed_loop():
        import tempfile

        from paddle_tpu.jit.compile_cache import set_cache_dir

        set_cache_dir(tempfile.mkdtemp(prefix="serve_cold_start_"))
        try:
            eng = ServingEngine(
                m, max_slots=3, max_len=64, page_size=8, chunk_size=8,
                tuner=True,
                tuner_kw={"interval": 4, "hysteresis": 2,
                          "cooldown": 1}).warmup()
            hs = [eng.submit(p, 6 + (i % 3) * 3)
                  for i, p in enumerate(prompts)]
            eng.run(max_steps=5000)
            # token parity vs plain generate holds THROUGH tuner moves
            # (every knob is schedule-shaping, never numerics-shaping)
            for h in hs:
                ref = m.generate(
                    np.asarray(h.request.prompt)[None],
                    max_new_tokens=h.request.max_new_tokens,
                    use_cache="paged")
                assert np.asarray(ref._data)[0].tolist() == \
                    h.output_tokens, f"rid {h.request.rid} diverged"
            # every decision is a single bounded step on a known knob
            for d in eng.tuner.decisions:
                assert d["knob"] in ("admit_watermark",
                                     "prefill_chunks_per_step",
                                     "chunk_size", "decode_burst"), d
                if d["knob"] != "chunk_size":
                    assert abs(d["to"] - d["from"]) == 1, d
            leaks = eng.leak_check()
            assert leaks["free_pages"] == leaks["total_pages"], leaks
            rec["tuner"] = {"evaluations": eng.tuner.evaluations,
                            "moves": len(eng.tuner.decisions),
                            "cold_start": eng.warmup_report}
        finally:
            set_cache_dir(None)

    check("serving_churn_parity", churn_parity)
    check("serving_preempt_resume", preempt_resume)
    check("serving_bounded_ttft", bounded_ttft)
    check("serving_traffic_ab", traffic_ab)
    check("serving_trace_forensics", trace_forensics)
    check("serving_tuner_closed_loop", tuner_closed_loop)
    rec["retrace_sentinel"] = {
        "strict": obs.strict_retrace(),
        "total_unexpected": obs.retrace_summary()["total_unexpected"],
    }
    rec["check"] = ("pass" if not fails
                    else "FAIL: " + ", ".join(fails))
    return rec


def run_bench():
    """bench.py --serve lane: p50/p99 TTFT + aggregate tok/s at >= 3
    concurrency levels, continuous batching vs static generate-and-wait
    on the same Poisson traffic, plus the retrace-free proof. Model and
    load are env-tunable (BENCH_SERVE_MODEL, BENCH_SERVE_USERS,
    BENCH_SERVE_REQS_PER_USER, BENCH_SERVE_RATE_PER_USER); the default
    is a
    tiny model because the lane measures the SCHEDULER — admission,
    chunked prefill, slot recycling — not matmul throughput."""
    import os

    import numpy as np

    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.traffic import (poisson_traffic,
                                            run_continuous, run_static)

    model_name = os.environ.get("BENCH_SERVE_MODEL", "tiny")
    if model_name == "tiny":
        m, cfg = _tiny_model()
    else:
        import paddle_tpu as paddle
        from paddle_tpu.models import GPTForCausalLM, gpt_config

        cfg = gpt_config(model_name, max_position_embeddings=256)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
    levels = tuple(int(u) for u in os.environ.get(
        "BENCH_SERVE_USERS", "4,8,16").split(","))
    n_per = int(os.environ.get("BENCH_SERVE_REQS_PER_USER", "6"))
    # offered load scales with the concurrency level, so every level
    # saturates its slots instead of measuring the arrival process
    rate_per = float(os.environ.get("BENCH_SERVE_RATE_PER_USER", "25"))
    max_len = 160
    lanes, wins = {}, 0
    tot = {"continuous": [0, 0.0], "static": [0, 0.0]}  # tokens, secs
    for users in levels:
        traffic = poisson_traffic(
            n_per * users, rate_rps=rate_per * users,
            vocab_size=cfg.vocab_size,
            prompt_lens=(8, 48), out_lens=(8, 96), seed=7 + users)
        eng = ServingEngine(m, max_slots=users, max_len=max_len,
                            page_size=16, chunk_size=32,
                            prefill_chunks_per_step=2,
                            decode_burst=4).warmup()
        cont, _ = run_continuous(eng, traffic)
        stat = run_static(m, traffic, concurrency=users,
                          max_len=max_len, page_size=16)
        wins += cont["tok_s"] > stat["tok_s"]
        tot["continuous"][0] += cont["generated_tokens"]
        tot["continuous"][1] += cont["elapsed_s"]
        tot["static"][0] += stat["generated_tokens"]
        tot["static"][1] += stat["elapsed_s"]
        lanes[f"users{users}"] = {
            "continuous": {k: cont[k] for k in
                           ("tok_s", "ttft_p50_s", "ttft_p99_s",
                            "itl_p50_s", "finished", "preemptions",
                            "decode_steps", "prefill_chunks")},
            "static": stat,
            "tok_s_speedup": round(
                cont["tok_s"] / max(stat["tok_s"], 1e-9), 3),
            "retrace_free": cont["compile"]["decode_traces"] == 1,
        }
        mem_eng = eng
    agg = {side: round(v[0] / max(v[1], 1e-9), 1)
           for side, v in tot.items()}
    # per-lane device-memory receipt (ISSUE 14): the compiled
    # serve-decode-step peak at the highest concurrency level + the
    # live-buffer attribution (params vs KV pools vs untagged) + the
    # drained pool stats — failures must not eat the serving numbers
    try:
        from paddle_tpu.observability.memory import live_buffer_report

        mem = {"compiled": mem_eng.memory_profile(top_k=4).summary(),
               "live": live_buffer_report(),
               "pool": mem_eng.cache.pool_stats()}
    except Exception as e:
        mem = {"error": f"{type(e).__name__}: {e}"[:200]}
    return {
        "metric": "serving_continuous_vs_static",
        "config": {"model": model_name, "levels": list(levels),
                   "reqs_per_user": n_per, "rate_per_user": rate_per,
                   "max_len": max_len,
                   "params": sum(int(np.prod(p.shape))
                                 for p in m.parameters())},
        "continuous_wins": f"{wins}/{len(levels)}",
        "aggregate_tok_s": agg,
        "aggregate_speedup": round(
            agg["continuous"] / max(agg["static"], 1e-9), 3),
        "lanes": lanes,
        "mem": mem,
    }


if __name__ == "__main__":
    import sys

    if "--bench" in sys.argv:
        print(json.dumps(run_bench()))
    else:
        print(json.dumps(run_probe()))
