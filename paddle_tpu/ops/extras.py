"""Long-tail op parity pack (reference python/paddle/tensor/math.py,
manipulation.py, creation.py, search.py — the remaining paddle.* names of
the reference's top-level __all__ not yet covered by the core op modules).

Every op is a jnp expression through the dispatch layer: jit/grad/shard
semantics come for free. In-place variants (`*_`) follow the framework's
functional-rebind convention (`Tensor._inplace_from`).
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

_builtin_abs = abs

from ..framework.tensor import Tensor
from ._dispatch import unary, binary, nary, ensure_tensor


# ---------------------------------------------------------------------------
# special functions (reference tensor/math.py over phi special kernels)
# ---------------------------------------------------------------------------

def gammaln(x, name=None):
    return unary(lambda v: jax.scipy.special.gammaln(
        v.astype(jnp.float32) if jnp.issubdtype(v.dtype, jnp.integer) else v),
        x, "gammaln")


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y)."""
    return binary(lambda a, v: jax.scipy.special.gammainc(a, v), x, y,
                  "gammainc")


def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y)."""
    return binary(lambda a, v: jax.scipy.special.gammaincc(a, v), x, y,
                  "gammaincc")


def multigammaln(x, p, name=None):
    return unary(lambda v: jax.scipy.special.multigammaln(v, int(p)), x,
                 "multigammaln")


def polygamma(x, n, name=None):
    return unary(lambda v: jax.scipy.special.polygamma(int(n), v), x,
                 "polygamma")


def i0(x, name=None):
    return unary(lambda v: jax.scipy.special.i0(v), x, "i0")


def i0e(x, name=None):
    return unary(lambda v: jax.scipy.special.i0e(v), x, "i0e")


def i1(x, name=None):
    return unary(lambda v: jax.scipy.special.i1(v), x, "i1")


def i1e(x, name=None):
    return unary(lambda v: jax.scipy.special.i1e(v), x, "i1e")


def sinc(x, name=None):
    return unary(jnp.sinc, x, "sinc")


def sgn(x, name=None):
    """Sign for real; unit phasor (x/|x|, 0 at 0) for complex."""
    def f(v):
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.where(mag == 0, 1, mag))
        return jnp.sign(v)

    return unary(f, x, "sgn")


def signbit(x, name=None):
    return unary(jnp.signbit, x, "signbit")


def isneginf(x, name=None):
    return unary(jnp.isneginf, x, "isneginf")


def isposinf(x, name=None):
    return unary(jnp.isposinf, x, "isposinf")


def isreal(x, name=None):
    return unary(jnp.isreal, x, "isreal")


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return binary(lambda a, b: jnp.isin(a, b, invert=invert), x, test_x,
                  "isin")


def polar(abs, angle, name=None):
    # complex dtype follows the input (complex128 for float64 inputs)
    return binary(lambda r, th: jax.lax.complex(r * jnp.cos(th),
                                                r * jnp.sin(th)),
                  abs, angle, "polar")


def complex(real, imag, name=None):
    return binary(lambda r, i: jax.lax.complex(r, i), real, imag, "complex")


def log_normal(mean=1.0, std=2.0, shape=None, dtype=None, name=None):
    from ..framework.random import next_key
    from ..framework.dtype import to_jax_dtype

    key = next_key()
    dt = to_jax_dtype(dtype or "float32")
    out = jnp.exp(mean + std * jax.random.normal(key, tuple(shape or ())))
    return Tensor._wrap(out.astype(dt))


def standard_normal(shape, dtype=None, name=None):
    from ..framework.random import next_key
    from ..framework.dtype import to_jax_dtype

    return Tensor._wrap(jax.random.normal(
        next_key(), tuple(shape), to_jax_dtype(dtype or "float32")))


def binomial(count, prob, name=None):
    from ..framework.random import next_key

    # under x64 (the framework default) jax 0.4.x's binomial kernel
    # clamps f32 operands against f64 literals and TypeErrors — run it
    # in f64 there; without x64 skip the cast (it would only warn)
    dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    return nary(lambda n, p: jax.random.binomial(
        next_key(), n.astype(dt), p.astype(dt),
        dtype=dt).astype(jnp.int64),
        [ensure_tensor(count), ensure_tensor(prob)], "binomial")


def standard_gamma(x, name=None):
    from ..framework.random import next_key

    return unary(lambda a: jax.random.gamma(next_key(), a), x,
                 "standard_gamma")


# ---------------------------------------------------------------------------
# manipulation (reference tensor/manipulation.py)
# ---------------------------------------------------------------------------

def tensor_split(x, num_or_indices, axis=0, name=None):
    x = ensure_tensor(x)
    arrs = jnp.array_split(x._data, num_or_indices
                           if isinstance(num_or_indices, int)
                           else list(num_or_indices), axis=axis)
    # route each piece through a slice op so autograd sees them
    outs = []
    offs = 0
    for a in arrs:
        size = a.shape[axis]
        lo = offs
        outs.append(unary(
            lambda v, lo=lo, size=size: jax.lax.slice_in_dim(
                v, lo, lo + size, axis=axis), x, "tensor_split"))
        offs += size
    return outs


def hsplit(x, num_or_indices, name=None):
    x = ensure_tensor(x)
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def column_stack(x, name=None):
    return nary(lambda *vs: jnp.column_stack(vs),
                [ensure_tensor(v) for v in x], "column_stack")


def row_stack(x, name=None):
    return nary(lambda *vs: jnp.vstack(vs), [ensure_tensor(v) for v in x],
                "row_stack")


def atleast_1d(*inputs, name=None):
    outs = [unary(jnp.atleast_1d, ensure_tensor(v), "atleast_1d")
            for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [unary(jnp.atleast_2d, ensure_tensor(v), "atleast_2d")
            for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [unary(jnp.atleast_3d, ensure_tensor(v), "atleast_3d")
            for v in inputs]
    return outs[0] if len(outs) == 1 else outs


def block_diag(inputs, name=None):
    return nary(lambda *vs: jax.scipy.linalg.block_diag(*vs),
                [ensure_tensor(v) for v in inputs], "block_diag")


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def f(v):
        n = v.shape[-1] + _builtin_abs(int(offset))
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-int(offset), 0)
        c = idx + max(int(offset), 0)
        out = out.at[..., r, c].set(v)
        # move the two new axes to dim1/dim2
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
        return out

    return unary(f, input, "diag_embed")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return unary(lambda v: jnp.diagonal(v, offset=int(offset),
                                        axis1=int(axis1), axis2=int(axis2)),
                 x, "diagonal")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(v):
        vals = jnp.sort(v, axis=axis)
        idxs = jnp.argsort(v, axis=axis)
        got = jnp.take(vals, int(k) - 1, axis=axis)
        gi = jnp.take(idxs, int(k) - 1, axis=axis)
        if keepdim:
            got = jnp.expand_dims(got, axis)
            gi = jnp.expand_dims(gi, axis)
        return got, gi.astype(jnp.int64)

    x = ensure_tensor(x)
    vals = unary(lambda v: f(v)[0], x, "kthvalue")
    idxs = unary(lambda v: f(v)[1], x, "kthvalue_idx")
    idxs.stop_gradient = True
    return vals, idxs


def mode(x, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)

    def _mode_vals(v):
        # sort, then run-length count of equal neighbors; ties between
        # equally-frequent values resolve to the SMALLEST (reference
        # test_mode_op.py _mode1D: strictly-greater frequency updates)
        vm = jnp.moveaxis(jnp.sort(v, axis=axis), axis % v.ndim, -1)
        eq = jnp.concatenate([jnp.zeros(vm.shape[:-1] + (1,), bool),
                              vm[..., 1:] == vm[..., :-1]], -1)

        def body(c, e):
            c = jnp.where(e, c + 1, 0)
            return c, c

        _, runs = jax.lax.scan(body, jnp.zeros(vm.shape[:-1], jnp.int32),
                               jnp.moveaxis(eq, -1, 0))
        runs = jnp.moveaxis(runs, 0, -1)
        best = jnp.argmax(runs, -1)
        return jnp.take_along_axis(vm, best[..., None], -1)[..., 0]

    def fv(v):
        md = _mode_vals(v)
        return jnp.expand_dims(md, axis) if keepdim else md

    def fi(v):
        # reference semantics: the ORIGINAL index of the mode's LAST
        # occurrence (stable-sorted run end)
        md = _mode_vals(v)
        n = v.shape[axis % v.ndim]
        eq = jnp.flip(v == jnp.expand_dims(md, axis), axis=axis)
        idx = (n - 1) - jnp.argmax(eq, axis=axis)
        idx = idx.astype(jnp.int64)
        return jnp.expand_dims(idx, axis) if keepdim else idx

    vals = unary(fv, x, "mode")
    idxs = unary(fi, x, "mode_idx")
    idxs.stop_gradient = True
    return vals, idxs


def cummin(x, axis=None, dtype="int64", name=None):
    from ..framework.dtype import to_jax_dtype

    x = ensure_tensor(x)
    ax = axis if axis is not None else None
    idt = to_jax_dtype(dtype)  # reference honors 'int32'/'int64' for indices
    # jnp.minimum is a ufunc with .accumulate only on newer jax; lax
    # cummin is the same scan everywhere
    def _acc_min(v, axis=0):
        if hasattr(jnp.minimum, "accumulate"):
            return jnp.minimum.accumulate(v, axis=axis)
        import jax as _jax

        return _jax.lax.cummin(v, axis=axis)

    if ax is None:
        flat = unary(lambda v: _acc_min(v.reshape(-1)), x,
                     "cummin")
        vals = flat
        idx_f = unary(lambda v: _cummin_idx(v.reshape(-1)).astype(idt), x,
                      "cummin_idx")
    else:
        vals = unary(lambda v: _acc_min(v, axis=ax), x,
                     "cummin")
        idx_f = unary(lambda v: _cummin_idx(v, ax).astype(idt), x,
                      "cummin_idx")
    idx_f.stop_gradient = True
    return vals, idx_f


def _cum_idx(v, axis, cmp):
    """Running arg-extremum along `axis`: index of the first element that
    `cmp`-beats all before it (shared body of cummax/cummin indices)."""
    vm = jnp.moveaxis(v, axis, 0)

    def body(carry, x):
        best, bidx, i = carry
        take = cmp(x, best)
        best = jnp.where(take, x, best)
        bidx = jnp.where(take, i, bidx)
        return (best, bidx, i + 1), bidx

    init = (vm[0], jnp.zeros(vm.shape[1:], jnp.int64), jnp.int64(1))
    _, idxs = jax.lax.scan(body, init, vm[1:])
    idxs = jnp.concatenate(
        [jnp.zeros((1,) + vm.shape[1:], jnp.int64), idxs], 0)
    return jnp.moveaxis(idxs, 0, axis)


def _cummax_idx(v, axis=0):
    return _cum_idx(v, axis, jnp.greater)


def _cummin_idx(v, axis=0):
    return _cum_idx(v, axis, jnp.less)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(v, val):
        idx = [slice(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = slice(int(s), int(e), int(st))
        return v.at[tuple(idx)].set(val)

    return binary(f, ensure_tensor(x), ensure_tensor(value), "slice_scatter")


def select_scatter(x, values, axis, index, name=None):
    def f(v, val):
        idx = [slice(None)] * v.ndim
        idx[axis] = int(index)
        return v.at[tuple(idx)].set(val)

    return binary(f, ensure_tensor(x), ensure_tensor(values),
                  "select_scatter")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(v, val):
        n = min(v.shape[axis1], v.shape[axis2])
        idx = jnp.arange(n - _builtin_abs(int(offset)))
        r = idx + max(-int(offset), 0)
        c = idx + max(int(offset), 0)
        vm = jnp.moveaxis(v, (axis1, axis2), (-2, -1))
        vm = vm.at[..., r, c].set(val)
        return jnp.moveaxis(vm, (-2, -1), (axis1, axis2))

    return binary(f, ensure_tensor(x), ensure_tensor(y), "diagonal_scatter")


def index_fill(x, index, axis, value, name=None):
    def f(v, idx):
        vm = jnp.moveaxis(v, axis, 0)
        vm = vm.at[idx].set(value)
        return jnp.moveaxis(vm, 0, axis)

    return binary(f, ensure_tensor(x), ensure_tensor(index, dtype="int32"),
                  "index_fill")


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions of x with consecutive elements of value
    (row-major), reference tensor/manipulation.py masked_scatter."""
    def f(v, m, val):
        flatm = m.reshape(-1)
        # position of each True among Trues
        order = jnp.cumsum(flatm.astype(jnp.int32)) - 1
        picked = val.reshape(-1)[jnp.clip(order, 0, val.size - 1)]
        out = jnp.where(flatm, picked, v.reshape(-1))
        return out.reshape(v.shape)

    return nary(f, [ensure_tensor(x), ensure_tensor(mask),
                    ensure_tensor(value)], "masked_scatter")


def combinations(x, r=2, with_replacement=False, name=None):
    x = ensure_tensor(x)
    n = x.shape[0]
    import itertools

    pool = (itertools.combinations_with_replacement(range(n), r)
            if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(pool), np.int32).reshape(-1, r)
    return unary(lambda v: v[idx], x, "combinations")


def cartesian_prod(x, name=None):
    tensors = [ensure_tensor(t) for t in x]

    def f(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    return nary(f, tensors, "cartesian_prod")


def multiplex(inputs, index, name=None):
    def f(idx, *vs):
        stacked = jnp.stack(vs, 0)   # [n, batch, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1).astype(jnp.int32), rows]

    return nary(lambda *args: f(args[-1], *args[:-1]),
                [ensure_tensor(v) for v in inputs]
                + [ensure_tensor(index)], "multiplex")


def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = np.tril_indices(row, offset, col)
    return Tensor._wrap(jnp.asarray(np.stack([r, c]), jnp.int64))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor._wrap(jnp.asarray(np.stack([r, c]), jnp.int64))


def unflatten(x, axis, shape, name=None):
    def f(v):
        ax = axis % v.ndim
        return v.reshape(v.shape[:ax] + tuple(shape) + v.shape[ax + 1:])

    return unary(f, x, "unflatten")


def unfold(x, axis, size, step, name=None):
    def f(v):
        ax = axis % v.ndim
        n = v.shape[ax]
        starts = jnp.arange(0, n - size + 1, step)
        idx = starts[:, None] + jnp.arange(size)[None, :]
        out = jnp.take(v, idx.reshape(-1), axis=ax)
        return out.reshape(v.shape[:ax] + (starts.shape[0], size)
                           + v.shape[ax + 1:])

    return unary(f, x, "unfold")


def view_as(x, other, name=None):
    other = ensure_tensor(other)
    return unary(lambda v: v.reshape(other._data.shape), x, "view_as")


def reverse(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return unary(lambda v: jnp.flip(v, axis=ax), x, "reverse")


def reduce_as(x, target, name=None):
    """Sum-reduce x to target's shape (reference tensor/math.py reduce_as)."""
    def f(v, t):
        extra = v.ndim - t.ndim
        if extra:
            v = jnp.sum(v, axis=tuple(range(extra)))
        axes = tuple(i for i, (a, b) in enumerate(zip(v.shape, t.shape))
                     if a != b and b == 1)
        if axes:
            v = jnp.sum(v, axis=axes, keepdims=True)
        return v

    return binary(f, ensure_tensor(x), ensure_tensor(target), "reduce_as")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    x = ensure_tensor(x)
    w = None if weights is None else np.asarray(
        ensure_tensor(weights)._data)
    if ranges is not None and len(ranges) and np.isscalar(ranges[0]):
        # reference contract (tensor/linalg.py:5248): FLAT sequence
        # [l0, r0, l1, r1, ...] — numpy wants per-dim pairs
        ndim = int(x._data.shape[-1])
        if len(ranges) != 2 * ndim:
            raise ValueError(
                f"histogramdd ranges must hold 2*D={2 * ndim} floats "
                f"(leftmost/rightmost per dimension), got {len(ranges)}")
        ranges = [(ranges[i], ranges[i + 1])
                  for i in range(0, len(ranges), 2)]
    hist, edges = np.histogramdd(np.asarray(x._data), bins=bins,
                                 range=ranges, density=density, weights=w)
    return (Tensor._wrap(jnp.asarray(hist)),
            [Tensor._wrap(jnp.asarray(e)) for e in edges])


def pdist(x, p=2.0, name=None):
    def f(v):
        n = v.shape[0]
        iu = np.triu_indices(n, 1)
        # gather the i<j pairs BEFORE the norm: the full n x n distance
        # matrix puts norm(0) on the diagonal, whose backward is
        # 0 * (0/0) = NaN even though triu discards it — grads through
        # pdist were NaN for every input
        return jnp.linalg.norm(v[iu[0], :] - v[iu[1], :], ord=p, axis=-1)

    return unary(f, x, "pdist")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    yt = ensure_tensor(y)

    if x is not None:
        def f(yv, xv):
            dxs = jnp.diff(xv, axis=axis)
            mids = (jnp.take(yv, jnp.arange(1, yv.shape[axis]), axis=axis)
                    + jnp.take(yv, jnp.arange(0, yv.shape[axis] - 1),
                               axis=axis)) / 2
            return jnp.cumsum(mids * dxs, axis=axis)

        return binary(f, yt, ensure_tensor(x), "cumulative_trapezoid")

    step = 1.0 if dx is None else float(dx)

    def f(yv):
        mids = (jnp.take(yv, jnp.arange(1, yv.shape[axis]), axis=axis)
                + jnp.take(yv, jnp.arange(0, yv.shape[axis] - 1),
                           axis=axis)) / 2
        return jnp.cumsum(mids * step, axis=axis)

    return unary(f, yt, "cumulative_trapezoid")


def bitwise_left_shift(x, y, is_arithmetic=True, out=None, name=None):
    return binary(jnp.left_shift, x, y, "bitwise_left_shift")


def bitwise_right_shift(x, y, is_arithmetic=True, out=None, name=None):
    def f(a, b):
        if is_arithmetic:
            return jnp.right_shift(a, b)
        # logical shift: reinterpret as unsigned
        ut = {jnp.int8: jnp.uint8, jnp.int16: jnp.uint16,
              jnp.int32: jnp.uint32, jnp.int64: jnp.uint64}.get(
                  a.dtype.type, None)
        if ut is None:
            return jnp.right_shift(a, b)
        return jnp.right_shift(a.view(ut), b.astype(ut)).view(a.dtype.type)

    return binary(f, x, y, "bitwise_right_shift")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return nary(lambda *vs: sum(vs[1:], vs[0]),
                [ensure_tensor(v) for v in inputs], "add_n")


# ---------------------------------------------------------------------------
# queries / utilities
# ---------------------------------------------------------------------------

def shape(input):
    return Tensor._wrap(jnp.asarray(ensure_tensor(input)._data.shape,
                                    jnp.int32))


def rank(input):
    return Tensor._wrap(jnp.asarray(ensure_tensor(input).ndim, jnp.int32))


def is_complex(x):
    return jnp.issubdtype(ensure_tensor(x)._data.dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(ensure_tensor(x)._data.dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(ensure_tensor(x)._data.dtype, jnp.floating)


def tolist(x):
    return np.asarray(ensure_tensor(x)._data).tolist()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def check_shape(x):
    return shape(x)


def disable_signal_handler():
    return None


def batch(reader, batch_size, drop_last=False):
    """Deprecated reference io helper: wrap a sample reader into batches."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """paddle.create_parameter parity (static+dygraph creation API).
    Initializer precedence mirrors Layer.create_parameter:
    attr.initializer > default_initializer > framework default."""
    from ..nn import initializer as I
    from ..nn.layer.layers import ParamAttr

    attr = ParamAttr._to_attr(attr) if attr is not None else None
    init = (getattr(attr, "initializer", None)
            or default_initializer
            or (I.Constant(0.0) if is_bias else I.XavierNormal()))
    data = init(shape, dtype)
    p = Tensor._wrap(data)
    p.stop_gradient = not getattr(attr, "trainable", True)
    pname = name or getattr(attr, "name", None)
    if pname:
        p.name = pname
    return p


# ---------------------------------------------------------------------------
# random in-place fills (reference tensor/random.py: Tensor.normal_ etc.)
# ---------------------------------------------------------------------------

def normal_(x, mean=0.0, std=1.0, name=None):
    from ..framework.random import next_key

    x = ensure_tensor(x)
    key = next_key()
    out = unary(lambda v: mean + std * jax.random.normal(key, v.shape,
                                                         v.dtype),
                x, "normal_")
    x._inplace_from(out)
    return x


def log_normal_(x, mean=1.0, std=2.0, name=None):
    from ..framework.random import next_key

    x = ensure_tensor(x)
    key = next_key()
    out = unary(lambda v: jnp.exp(mean + std * jax.random.normal(
        key, v.shape, v.dtype)), x, "log_normal_")
    x._inplace_from(out)
    return x


def cauchy_(x, loc=0.0, scale=1.0, name=None):
    from ..framework.random import next_key

    x = ensure_tensor(x)
    key = next_key()
    out = unary(lambda v: loc + scale * jax.random.cauchy(key, v.shape,
                                                          v.dtype),
                x, "cauchy_")
    x._inplace_from(out)
    return x


def geometric_(x, probs, name=None):
    from ..framework.random import next_key

    x = ensure_tensor(x)
    key = next_key()
    out = unary(lambda v: jax.random.geometric(
        key, probs, v.shape).astype(v.dtype), x, "geometric_")
    x._inplace_from(out)
    return x


def bernoulli_(x, p=0.5, name=None):
    from ..framework.random import next_key

    x = ensure_tensor(x)
    key = next_key()
    out = unary(lambda v: jax.random.bernoulli(
        key, p, v.shape).astype(v.dtype), x, "bernoulli_")
    x._inplace_from(out)
    return x


def where_(condition, x, y, name=None):
    """In-place variant of where: writes the selection into x."""
    from .logic import where as _where

    out = _where(condition, x, y)
    x._inplace_from(out)
    return x


__all__ = [
    "top_p_sampling", "fill_diagonal_", "fill_diagonal_tensor", "fill_diagonal_tensor_",
    "l1_norm", "exponential_",
    # special
    "gammaln", "gammainc", "gammaincc", "multigammaln", "polygamma",
    "i0", "i0e", "i1", "i1e", "sinc", "sgn", "signbit", "isneginf",
    "isposinf", "isreal", "isin", "polar", "complex",
    # random
    "log_normal", "standard_normal", "binomial", "standard_gamma",
    "normal_", "log_normal_", "cauchy_", "geometric_", "bernoulli_",
    "where_",
    # manipulation
    "tensor_split", "hsplit", "vsplit", "dsplit", "column_stack",
    "row_stack", "atleast_1d", "atleast_2d", "atleast_3d", "block_diag",
    "diag_embed", "diagonal", "kthvalue", "mode", "cummin",
    "slice_scatter", "select_scatter", "diagonal_scatter", "index_fill",
    "masked_scatter", "combinations", "cartesian_prod", "multiplex",
    "tril_indices", "triu_indices", "unflatten", "unfold", "view_as",
    "reverse", "reduce_as", "histogramdd", "pdist", "cumulative_trapezoid",
    "bitwise_left_shift", "bitwise_right_shift", "add_n",
    # queries / utils
    "shape", "rank", "is_complex", "is_integer", "is_floating_point",
    "tolist", "set_printoptions", "check_shape", "disable_signal_handler",
    "batch", "create_parameter",
]


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    """In-place diagonal fill (reference fill_diagonal_kernel.h):
    functional update rebound through the in-place machinery."""
    from ._dispatch import ensure_tensor

    x = ensure_tensor(x)

    def f(v):
        m, n = v.shape[-2], v.shape[-1]
        if v.ndim == 2 and wrap and m > n:
            if offset:
                raise NotImplementedError(
                    "fill_diagonal_(wrap=True) with offset != 0")
            # numpy fill_diagonal wrap: restart every n+1 flat positions
            idx = jnp.arange(0, m * n, n + 1)
            return v.reshape(-1).at[idx].set(value).reshape(m, n)
        # diagonal length for a rectangular matrix with offset
        k = min(m + min(offset, 0), n - max(offset, 0))
        i = jnp.arange(max(k, 0))
        return v.at[..., i - min(offset, 0), i + max(offset, 0)].set(value)

    from ..framework.autograd import apply_op

    out = apply_op(f, [x], name="fill_diagonal_")
    x._inplace_from(out)
    return x


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Write tensor `y` onto x's (dim1, dim2) diagonal (reference
    fill_diagonal_tensor_kernel.h)."""
    from ._dispatch import nary

    def f(v, w):
        vd = jnp.moveaxis(v, (dim1, dim2), (-2, -1))
        m, n = vd.shape[-2], vd.shape[-1]
        k = min(m + min(offset, 0), n - max(offset, 0))
        i = jnp.arange(max(k, 0))
        rows = i - min(offset, 0)
        cols = i + max(offset, 0)
        vd = vd.at[..., rows, cols].set(w)
        return jnp.moveaxis(vd, (-2, -1), (dim1, dim2))

    return nary(f, [x, y], name="fill_diagonal_tensor")


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    out = fill_diagonal_tensor(x, y, offset=offset, dim1=dim1, dim2=dim2)
    x._inplace_from(out)
    return x


def l1_norm(x, name=None):
    """Sum of absolute values (reference l1_norm_kernel.h)."""
    from ._dispatch import unary

    return unary(lambda v: jnp.sum(jnp.abs(v)), x, "l1_norm")


def exponential_(x, lam=1.0, name=None):
    """In-place exponential-distribution fill (reference
    exponential_kernel.h / Tensor.exponential_)."""
    from ..framework.random import next_key

    x = ensure_tensor(x)
    key = next_key()
    out = unary(lambda v: (jax.random.exponential(key, v.shape, v.dtype)
                           / lam), x, "exponential_")
    x._inplace_from(out)
    return x


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1,
                   k=0, mode="truncated", return_top=False, name=None):
    """Nucleus (top-p) sampling (reference top_p_sampling_kernel.h):
    per row, keep the smallest prefix of descending-probability tokens
    whose mass reaches p, renormalize, sample one. Returns (scores,
    ids)."""
    from ..framework.random import next_key
    from ..framework.tensor import Tensor

    x = ensure_tensor(x)
    ps_t = ensure_tensor(ps)

    def f(probs, p):
        pf = probs.astype(jnp.float32)
        order = jnp.argsort(-pf, axis=-1)
        sorted_p = jnp.take_along_axis(pf, order, -1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        # keep tokens while cumulative mass (exclusive) < p
        keep = (cum - sorted_p) < p[..., None]
        keep = keep.at[..., 0].set(True)
        masked = jnp.where(keep, sorted_p, 0.0)
        norm = masked / jnp.sum(masked, -1, keepdims=True)
        key = next_key()
        choice = jax.random.categorical(key, jnp.log(norm + 1e-30))
        ids = jnp.take_along_axis(order, choice[..., None], -1)
        scores = jnp.take_along_axis(pf, ids, -1)
        return scores.astype(probs.dtype), ids.astype(jnp.int64)

    scores, ids = f(x._data, ps_t._data)
    return Tensor._wrap(scores), Tensor._wrap(ids)
