"""Device-side input prefetching: double-buffered H2D/compute overlap.

The DataLoader stack stops at host batch assembly — without this layer
every step pays a synchronous host→device transfer while the chip idles
(the input-stall gap the reference's DataLoader/buffer-reader stack exists
to close, python/paddle/io/ + fluid's buffered_reader.cc). `DevicePrefetcher`
wraps any `DataLoader`/iterable and keeps a depth-K ring of batches staged
ON DEVICE ahead of the consumer:

- a background thread pulls assembled host batches and stages them via
  sharding-aware `jax.device_put` — on a dp/sharding mesh each device
  receives only its 1/N shard of the batch, placed directly on the step's
  input sharding (so the compiled step never reshards, and no device ever
  sees the full global batch);
- the ring is donation-safe by construction: every stage allocates FRESH
  device buffers (`device_put` never aliases the producer's host memory,
  asserted by tests that mutate a reused host buffer), and a slot is only
  released when the consumer takes the batch — a buffer can never be
  rewritten while an in-flight step may still read it;
- placement is identical for every batch of a stream, so feeding a jitted
  train step adds ZERO retraces (compile-count probe in the selftest).

Instrumented end to end: per-step `input_stall_ms` (how long `next()`
blocked waiting for data — ≈0 when the pipeline keeps up) and `h2d_ms`
(host→device transfer time on the producer thread), exposed via
`get_stats()` and as profiler `RecordEvent` spans
("DevicePrefetcher.h2d" / "DevicePrefetcher.wait").

Usage::

    loader = io.DataLoader(ds, batch_size=32, num_workers=4)
    for ids, labels in io.DevicePrefetcher(loader, depth=2):
        loss = step(ids, labels)         # input delivery fully overlapped
    # or bound to a step's input sharding in one call:
    for ids, labels in step.prefetch(loader):
        loss = step(ids, labels)
"""
from __future__ import annotations

import queue
import threading
import time

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.tensor import Tensor
from ..observability import registry as _obs_registry
from ..profiler import RecordEvent

__all__ = ["DevicePrefetcher"]

_SENTINEL = object()


def _tree_map(fn, obj):
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_map(fn, o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_map(fn, v) for k, v in obj.items()}
    return fn(obj)


def _tree_leaves(obj, out=None):
    if out is None:
        out = []
    if isinstance(obj, (list, tuple)):
        for o in obj:
            _tree_leaves(o, out)
    elif isinstance(obj, dict):
        for v in obj.values():
            _tree_leaves(v, out)
    else:
        out.append(obj)
    return out


class _Epoch:
    """One epoch's producer thread + bounded device-side ring."""

    def __init__(self, prefetcher):
        self._pf = prefetcher
        self._q = queue.Queue(maxsize=prefetcher.depth)
        self._stop = threading.Event()
        self._err = None
        self._thread = threading.Thread(
            target=self._produce, name="DevicePrefetcher", daemon=True)
        self._thread.start()

    def _produce(self):
        pf = self._pf
        try:
            for batch in pf._host_batches():
                if self._stop.is_set():
                    return
                t0 = time.perf_counter()
                with RecordEvent("DevicePrefetcher.h2d"):
                    staged = _tree_map(pf._stage_leaf, batch)
                    # block here (on the PRODUCER thread, never the step
                    # loop) so h2d_ms is the true transfer time and the
                    # ring holds at most `depth` fully-resident batches
                    for leaf in _tree_leaves(staged):
                        if isinstance(leaf, jax.Array):
                            leaf.block_until_ready()
                pf._note_h2d((time.perf_counter() - t0) * 1e3)
                while not self._stop.is_set():
                    try:
                        self._q.put(staged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    return
        except Exception as e:  # surfaced on the consumer at next()
            self._err = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def close(self):
        self._stop.set()
        while True:  # unblock a producer waiting on a full ring
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=10)


class DevicePrefetcher:
    """Stage host batches onto device(s) ahead of the consumer.

    Args:
      loader: a `DataLoader` or any (re-)iterable of batches. Batches may
        be (nested) Tensors / numpy arrays / jax arrays; non-array leaves
        pass through untouched.
      depth: ring depth K — how many batches may be resident on device
        ahead of the consumer (2 = classic double buffering).
      sharding: target placement for every array leaf — a
        `jax.sharding.Sharding` (a `PartitionSpec` longer than a leaf's
        rank is trimmed; scalars replicate), a `jax.Device`, or a callable
        ``leaf -> sharding``. Default: the plain default-device
        `device_put` (same placement `paddle.to_tensor` produces, so a
        warmed-up jitted step sees identical input layouts).
      mesh/axis: convenience — equivalent to
        ``sharding=NamedSharding(mesh, P(axis))`` (dim 0 split over the
        dp axis, rest replicated). `axis` defaults to the first of
        sharding/dp/data with degree > 1.
      to_tensor: wrap staged jax arrays into Tensors on delivery.
      process_local: multi-process SPMD — the loader yields only this
        process's 1/N batch shard (a `DistributedBatchSampler` loader) and
        leaves are assembled into the global sharded array without any
        cross-host transfer.
    """

    def __init__(self, loader, depth=2, sharding=None, mesh=None,
                 axis=None, device=None, to_tensor=True,
                 process_local=False, stats_window=4096):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if sharding is None and mesh is not None:
            from ..distributed import env as denv

            sharding = denv.data_sharding(mesh=mesh, axis=axis)
        if sharding is None and device is not None:
            sharding = device
        self._loader = loader
        self.depth = int(depth)
        self._sharding = sharding
        self._to_tensor = to_tensor
        self._process_local = process_local
        self._stats_window = int(stats_window)
        self._epoch = None
        self._lock = threading.Lock()
        self.reset_stats()
        # live-buffer attribution (ISSUE 14): staged ring batches claim
        # their device bytes at mem.live scrape time (weakly tracked)
        from ..observability.memory import live_registry

        live_registry().track(self)

    def _mem_owners(self):
        """observability.memory provider: the device arrays currently
        staged in the ring (a snapshot of the queue — scrape-time only,
        never on the hot path)."""
        ep = self._epoch
        if ep is None:
            return {"prefetch_ring": []}
        try:
            with ep._q.mutex:
                staged = list(ep._q.queue)
        except Exception:
            staged = []
        return {"prefetch_ring": [b for b in staged
                                  if b is not _SENTINEL]}

    # -- staging ---------------------------------------------------------
    @staticmethod
    def _cpu_backend(target):
        if target is None:
            return jax.default_backend() == "cpu"
        if isinstance(target, jax.Device):
            return target.platform == "cpu"
        devs = getattr(target, "device_set", None)
        if devs:
            return next(iter(devs)).platform == "cpu"
        return jax.default_backend() == "cpu"

    def _placement_for(self, leaf):
        sh = self._sharding
        if callable(sh) and not isinstance(sh, (jax.sharding.Sharding,
                                                jax.Device)):
            return sh(leaf)
        if isinstance(sh, NamedSharding):
            nd = getattr(leaf, "ndim", 0)
            spec = list(sh.spec)
            while len(spec) > nd or (spec and spec[-1] is None):
                spec.pop()           # trim to rank; normalize trailing None
            if tuple(spec) != tuple(sh.spec):
                return NamedSharding(sh.mesh, PartitionSpec(*spec))
        return sh

    def _stage_leaf(self, leaf):
        if isinstance(leaf, Tensor):
            leaf = leaf._data
        if not isinstance(leaf, (np.ndarray, np.generic, jax.Array)):
            return leaf              # python scalars / strings / None
        target = self._placement_for(leaf)
        if isinstance(leaf, np.ndarray) and self._cpu_backend(target):
            # CPU-backend device_put ZERO-COPIES an aligned numpy buffer —
            # a loader that reuses its host buffer would then rewrite a
            # staged (possibly in-flight) batch. Donation safety demands
            # every stage own fresh memory; on accelerators the H2D
            # transfer itself is that copy.
            leaf = np.array(leaf, copy=True)
        if target is None:
            return jax.device_put(leaf)
        if self._process_local and jax.process_count() > 1:
            make = getattr(jax, "make_array_from_process_local_data", None)
            if make is None:
                raise RuntimeError(
                    "process_local staging needs "
                    "jax.make_array_from_process_local_data; this jax "
                    "predates it — shard with device_put on a "
                    "single-controller mesh instead")
            return make(target, np.asarray(leaf))
        return jax.device_put(leaf, target)

    def _host_batches(self):
        loader = self._loader
        from . import DataLoader, numpy_collate_fn

        if isinstance(loader, DataLoader) \
                and not getattr(loader, "_user_collate", True):
            # default collate builds device Tensors INSIDE the loader —
            # that is the synchronous transfer this layer exists to hide.
            # Iterate a shallow clone collating to numpy so the only H2D
            # is the staged, overlapped one (the clone shares dataset +
            # sampler; only the collate differs).
            import copy

            clone = copy.copy(loader)
            clone.collate_fn = numpy_collate_fn
            clone._user_collate = True
            return iter(clone)
        return iter(loader)

    # -- stats -----------------------------------------------------------
    def _note_h2d(self, ms):
        with self._lock:
            self._h2d_ms.append(ms)
            if len(self._h2d_ms) > self._stats_window:
                del self._h2d_ms[: -self._stats_window]
            self._h2d_total += ms
            self._h2d_count += 1
        # unified telemetry (ISSUE 12): the same sample lands in the
        # process-global registry so scrapes/timelines see input health
        _obs_registry().histogram("input.h2d_ms").observe(ms)

    def _note_stall(self, ms):
        with self._lock:
            self._stall_ms.append(ms)
            if len(self._stall_ms) > self._stats_window:
                del self._stall_ms[: -self._stats_window]
            self._stall_total += ms
            self._stall_count += 1
        _obs_registry().histogram("input.stall_ms").observe(ms)

    def reset_stats(self):
        with self._lock:
            self._stall_ms = []
            self._h2d_ms = []
            self._stall_total = 0.0
            self._h2d_total = 0.0
            self._stall_count = 0
            self._h2d_count = 0

    def get_stats(self):
        """Per-step input_stall_ms / h2d_ms (last `stats_window` steps)
        plus aggregates. input_stall_ms is the time `next()` blocked on
        data — ≈0 means the device never waited on the host."""
        with self._lock:
            def agg(samples, total, count):
                return {
                    "total": round(total, 3),
                    "mean": round(total / count, 4) if count else None,
                    "max": round(max(samples), 3) if samples else None,
                    "count": count,
                }

            return {
                "depth": self.depth,
                "batches": self._stall_count,
                "input_stall_ms": agg(self._stall_ms, self._stall_total,
                                      self._stall_count),
                "h2d_ms": agg(self._h2d_ms, self._h2d_total,
                              self._h2d_count),
                "per_step_input_stall_ms": [round(v, 4)
                                            for v in self._stall_ms],
                "per_step_h2d_ms": [round(v, 4) for v in self._h2d_ms],
            }

    # -- iteration -------------------------------------------------------
    def __len__(self):
        return len(self._loader)

    def __iter__(self):
        # a fresh epoch when none is live; mid-epoch iter() continues the
        # current stream (so `next(pf)` + `for b in pf` compose). close()
        # abandons a live epoch explicitly.
        if self._epoch is None:
            self._epoch = _Epoch(self)
        return self

    def __next__(self):
        ep = self._epoch
        if ep is None:
            raise StopIteration
        t0 = time.perf_counter()
        with RecordEvent("DevicePrefetcher.wait"):
            item = ep._q.get()
        if item is _SENTINEL:
            self._epoch = None
            ep._thread.join(timeout=10)
            if ep._err is not None:
                raise ep._err
            raise StopIteration
        self._note_stall((time.perf_counter() - t0) * 1e3)
        if self._to_tensor:
            return _tree_map(
                lambda l: Tensor._wrap(l)
                if isinstance(l, jax.Array) else l, item)
        return item

    def close(self):
        """Stop the producer and release the ring (idempotent; also runs
        at GC). Safe mid-epoch — a producer blocked on the full ring
        unblocks and joins. A producer blocked inside the wrapped
        loader's own `next()` cannot be interrupted from outside: the
        join times out (10s) and the daemon thread exits on its own when
        the pull returns and sees the stop flag."""
        ep, self._epoch = self._epoch, None
        if ep is not None:
            ep.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
