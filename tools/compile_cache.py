"""Persistent compile-cache inspector (ISSUE 17 satellite).

Operator surface over `paddle_tpu.jit.compile_cache.CompileCache` — the
on-disk AOT executable store the step paths hit on warm start. Answers
the questions an operator actually asks: what is cached, WHY is an
entry keyed the way it is (full key provenance: signature, HLO hash,
toolchain versions, flags, donation, mesh), how big is the store, and
how do I trim it.

Usage::

    python tools/compile_cache.py list   [--dir DIR] [--json] [-v]
    python tools/compile_cache.py stats  [--dir DIR] [--json]
    python tools/compile_cache.py evict  KEYPREFIX [--dir DIR]
    python tools/compile_cache.py clear  [--dir DIR]
    python tools/compile_cache.py prune  [--dir DIR] [--max-mb MB]

``--dir`` defaults to ``$PADDLE_TPU_COMPILE_CACHE``. ``evict`` accepts
an unambiguous key prefix (keys are 32-hex). ``prune`` runs the same
LRU cap enforcement the store applies online (``--max-mb`` overrides
``$PADDLE_TPU_COMPILE_CACHE_MB``, default 512). `bench.py` calls
`render_list`/`render_stats` for its cold-start lane report.

Exit codes: 0 ok / 1 usage or no cache dir / 3 evict target missing or
ambiguous.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.jit.compile_cache import (  # noqa: E402
    CACHE_CAP_ENV, CACHE_ENV, CompileCache,
)


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


def _fmt_age(ts):
    if not ts:
        return "-"
    d = max(0.0, time.time() - float(ts))
    for lim, unit in ((60, "s"), (3600, "m"), (86400, "h")):
        if d < lim:
            return f"{d:.0f}{unit}" if unit == "s" else \
                f"{d / (lim / 60):.0f}{unit}"
    return f"{d / 86400:.1f}d"


def _provenance(comp):
    """One compact provenance string from the sidecar key components."""
    if not comp:
        return "(no sidecar)"
    backend = comp.get("backend", {})
    flags_on = sorted(k.replace("FLAGS_", "")
                      for k, v in (comp.get("flags") or {}).items() if v)
    bits = [
        f"sig={str(comp.get('signature', '?'))[:10]}",
        f"{comp.get('hlo', '?')}",
        f"jaxlib={comp.get('jaxlib_version', '?')}",
        f"{backend.get('platform', '?')}x{backend.get('n_devices', '?')}",
        f"donate={comp.get('donate_argnums', [])}",
    ]
    if comp.get("mesh"):
        bits.append("mesh=" + "x".join(
            f"{k}{v}" for k, v in comp["mesh"].items()))
    if flags_on:
        bits.append("flags=" + ",".join(flags_on))
    return " ".join(bits)


def render_list(cache, verbose=False):
    lines = []
    entries = cache.entries()
    if not entries:
        return [f"compile cache {cache.root}: empty"]
    lines.append(f"{'KEY':<14} {'LABEL':<24} {'SIZE':>9} {'HITS':>5} "
                 f"{'AGE':>6} {'USED':>6}  PROVENANCE")
    for e in entries:
        comp = e.meta.get("components") or {}
        lines.append(
            f"{e.key[:12]:<14} "
            f"{str(comp.get('label', '?'))[:24]:<24} "
            f"{_fmt_bytes(e.meta['bytes']):>9} "
            f"{int(e.meta.get('hits', 0)):>5} "
            f"{_fmt_age(e.meta.get('created')):>6} "
            f"{_fmt_age(e.meta.get('last_used')):>6}  "
            f"{_provenance(comp)}")
        if verbose:
            lines.append("    " + json.dumps(comp, sort_keys=True))
    return lines


def render_stats(cache):
    st = cache.stats()
    used = st["bytes"] / max(st["max_bytes"], 1) * 100.0
    return [
        f"compile cache {st['root']}",
        f"  entries      {st['entries']}",
        f"  size         {_fmt_bytes(st['bytes'])} / "
        f"{_fmt_bytes(st['max_bytes'])} cap ({used:.0f}%)",
        f"  proc hit/miss {st['hits']}/{st['misses']}",
        f"  lifetime hits {st['disk_hits']} (sidecar accounting)",
    ]


def _open_cache(args):
    root = args.dir or os.environ.get(CACHE_ENV, "").strip()
    if not root:
        print(f"no cache dir: pass --dir or set ${CACHE_ENV}",
              file=sys.stderr)
        return None
    max_bytes = None
    if getattr(args, "max_mb", None):
        max_bytes = int(args.max_mb * (1 << 20))
    return CompileCache(root, max_bytes=max_bytes)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="compile_cache", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("list", "stats", "clear"):
        p = sub.add_parser(name)
        p.add_argument("--dir", default=None)
        p.add_argument("--json", action="store_true")
        if name == "list":
            p.add_argument("-v", "--verbose", action="store_true")
    p = sub.add_parser("evict")
    p.add_argument("key")
    p.add_argument("--dir", default=None)
    p = sub.add_parser("prune")
    p.add_argument("--dir", default=None)
    p.add_argument("--max-mb", type=float, default=None,
                   help=f"cap override (default ${CACHE_CAP_ENV} or 512)")
    args = ap.parse_args(argv)

    cache = _open_cache(args)
    if cache is None:
        return 1

    if args.cmd == "list":
        if args.json:
            print(json.dumps([e.meta for e in cache.entries()],
                             indent=2, sort_keys=True))
        else:
            print("\n".join(render_list(cache, verbose=args.verbose)))
        return 0
    if args.cmd == "stats":
        if args.json:
            print(json.dumps(cache.stats(), indent=2, sort_keys=True))
        else:
            print("\n".join(render_stats(cache)))
        return 0
    if args.cmd == "evict":
        matches = [e for e in cache.entries()
                   if e.key.startswith(args.key)]
        if len(matches) != 1:
            print(f"evict {args.key!r}: "
                  f"{'no match' if not matches else 'ambiguous prefix'} "
                  f"({len(matches)} entries)", file=sys.stderr)
            return 3
        cache.evict(matches[0].key)
        print(f"evicted {matches[0].key}")
        return 0
    if args.cmd == "clear":
        n = cache.clear()
        print(f"cleared {n} entries from {cache.root}")
        return 0
    if args.cmd == "prune":
        before = {e.key for e in cache.entries()}
        cache._enforce_cap()
        gone = before - {e.key for e in cache.entries()}
        print(f"pruned {len(gone)} entries "
              f"(cap {_fmt_bytes(cache.max_bytes)}, now "
              f"{_fmt_bytes(cache.total_bytes())})")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
