"""Collective communication API.

Reference parity: python/paddle/distributed/communication/ (15 files) +
Group management (python/paddle/distributed/collective.py:151-180) over
ProcessGroupNCCL (paddle/phi/core/distributed/collective/process_group.h:48).

TPU-first: a Group is a set of named mesh axes on the global Mesh. Each
collective has two modes:

- **traced** (inside `shard_map`/pjit): lowers directly to the XLA
  collective (`lax.psum` / `all_gather` / `psum_scatter` / `all_to_all` /
  `ppermute`) over ICI with replica groups from the axis — the
  ProcessGroupXLA north star of SURVEY.md §5.8.
- **eager** (single-controller): wraps the same lax op in a `shard_map` over
  the group's axes. A replicated input behaves like "every rank holds this
  value" (reference per-rank semantics); an input sharded over the group
  axis uses its true per-device shards.

All collectives record on the autograd tape (they are jax-differentiable),
matching the reference's PyLayer comm ops (fleet/layers/mpu/mp_ops.py:91-341).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

shard_map = jax.shard_map

from ..framework.tensor import Tensor
from ..framework.autograd import apply_op
from . import env


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = named axes of the global mesh (reference
    Group, python/paddle/distributed/communication/group.py)."""

    _next_id = 0

    def __init__(self, mesh: Mesh, axes, name=None):
        self.mesh = mesh
        self.axes = tuple(axes) if not isinstance(axes, str) else (axes,)
        for a in self.axes:
            if a not in mesh.axis_names:
                raise ValueError(f"axis {a!r} not in mesh {mesh.axis_names}")
        Group._next_id += 1
        self.id = Group._next_id
        self.name = name or f"group_{self.id}"

    @property
    def nranks(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.axes]))

    world_size = nranks

    @property
    def rank(self) -> int:
        """Single-controller semantics: one Python process drives ALL group
        ranks, so "my rank" is only meaningful per host process. Returns 0
        single-host (parity with reference rank-0 driver code); multi-host
        SPMD returns the group index of the first device this process owns,
        so per-rank branches (logging, checkpoint writes) stay correct."""
        import jax

        if jax.process_count() == 1:
            return 0
        me = jax.process_index()
        mesh_axes = list(self.mesh.axis_names)
        group_dims = [self.mesh.shape[a] for a in self.axes]
        it = np.nditer(self.mesh.devices, flags=["multi_index", "refs_ok"])
        for _ in it:
            d = self.mesh.devices[it.multi_index]
            if d.process_index == me:
                # project the mesh coordinate onto the GROUP's axes and
                # linearize — a flat mesh index would exceed nranks-1 for
                # sub-axis groups
                coord = [it.multi_index[mesh_axes.index(a)]
                         for a in self.axes]
                rank = 0
                for c, dim in zip(coord, group_dims):
                    rank = rank * dim + int(c)
                return rank
        return -1  # this process owns no device of the group

    @property
    def process_ids(self):
        return list(range(self.nranks))

    ranks = process_ids

    def get_group_rank(self, rank):
        return rank if 0 <= rank < self.nranks else -1

    def __repr__(self):
        return f"Group(axes={self.axes}, nranks={self.nranks})"


_default_group = None


def _world_group() -> Group:
    global _default_group
    mesh = env.get_mesh()
    if _default_group is None or _default_group.mesh is not mesh:
        _default_group = Group(mesh, mesh.axis_names, name="world")
    return _default_group


def get_group(gid=None) -> Group:
    return _world_group()


def new_group(ranks=None, backend=None, timeout=None, axes=None, mesh=None) -> Group:
    """Reference collective.py:151 new_group. TPU-native extension: pass
    `axes=` to bind the group to mesh axes (the common case via topology);
    explicit `ranks` builds a 1-axis sub-mesh over those devices."""
    mesh = mesh or env.get_mesh()
    if axes is not None:
        return Group(mesh, axes)
    flat = list(mesh.devices.flat)
    if ranks is None or len(ranks) == len(flat):
        return _world_group()
    sub = np.asarray([flat[r] for r in ranks])
    return Group(Mesh(sub, ("sub",)), ("sub",))


def _axis_bound(axis: str) -> bool:
    """True when called inside a shard_map/pmap context binding `axis`."""
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:
        return False


def _group_axes(group) -> tuple:
    group = group or _world_group()
    return group.axes if isinstance(group, Group) else tuple(group)


def _input_spec(data, mesh) -> P:
    sh = getattr(data, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh.axis_names == mesh.axis_names:
        return sh.spec
    return P()


# Eager-mode composed-callable cache (VERDICT r3 weak #6): rebuilding the
# shard_map wrapper per call made every eager collective a fresh callable,
# so jax's executable cache missed and RETRACED each call — fine in tests,
# a trap in a hot eager loop. Keyed by the collective's semantic identity
# (name + baked-in args), mesh, axes and specs; jax's own cache then keys
# shapes/dtypes under the stable callable.
_eager_fn_cache: dict = {}


def _run(group, data, traced_fn, out_spec=None, cache_key=None):
    """Execute traced_fn (using lax collectives over group.axes) on `data`:
    directly if the axes are bound (already inside shard_map), else wrapped
    in an eager shard_map over the group's mesh (cached per `cache_key`)."""
    group = group or _world_group()
    axes = group.axes
    if isinstance(data, jax.core.Tracer) and _axis_bound(axes[0]):
        return traced_fn(data)
    mesh = group.mesh
    in_spec = _input_spec(data, mesh)
    o_spec = out_spec if out_spec is not None else in_spec
    if cache_key is not None:
        full_key = (cache_key, mesh, axes, in_spec, o_spec)
        fn = _eager_fn_cache.get(full_key)
        if fn is None:
            # bounded LRU instead of evict-all-other-meshes: sub-group
            # collectives (new_group sub-mesh) alternating with world-
            # group ones must not evict each other per call — that
            # silently reintroduced the per-call retrace this cache fixed
            # (ADVICE r4). Replaced meshes (elastic re-rendezvous, tests)
            # age out of the LRU instead of being evicted eagerly.
            while len(_eager_fn_cache) >= 128:
                _eager_fn_cache.pop(next(iter(_eager_fn_cache)))
            fn = jax.jit(shard_map(traced_fn, mesh=mesh,
                                   in_specs=(in_spec,),
                                   out_specs=o_spec, check_vma=False))
            _eager_fn_cache[full_key] = fn
        else:
            _eager_fn_cache[full_key] = _eager_fn_cache.pop(full_key)
        return fn(data)
    fn = shard_map(traced_fn, mesh=mesh, in_specs=(in_spec,),
                   out_specs=o_spec, check_vma=False)
    return fn(data)


def _axis_arg(axes):
    return axes if len(axes) > 1 else axes[0]


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------

def _reduce_traced(axes, op):
    ax = _axis_arg(axes)
    if op in (ReduceOp.SUM, "sum"):
        return lambda s: jax.lax.psum(s, ax)
    if op in (ReduceOp.MAX, "max"):
        return lambda s: jax.lax.pmax(s, ax)
    if op in (ReduceOp.MIN, "min"):
        return lambda s: jax.lax.pmin(s, ax)
    if op in (ReduceOp.AVG, "avg"):
        return lambda s: jax.lax.pmean(s, ax)
    if op in (ReduceOp.PROD, "prod"):
        return lambda s: jnp.exp(jax.lax.psum(jnp.log(s), ax))
    raise ValueError(f"unsupported reduce op {op}")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reference communication/all_reduce.py; in-place on `tensor`."""
    group = group or _world_group()
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    fn = _reduce_traced(group.axes, op)
    out = apply_op(lambda x: _run(group, x, fn,
                              cache_key=("all_reduce", str(op))),
               [t], name="all_reduce")
    t._inplace_from(out)
    return t


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # with a single controller, reduce == all_reduce (dst holds the value;
    # every device materializes it — XLA replicates for free)
    return all_reduce(tensor, op=op, group=group)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    """Reference communication/all_gather.py: gathers per-rank tensors into
    tensor_list (stack on a new leading dim per rank). For a tiled gather
    along an existing dim use `all_gather_concat(tensor, axis=...)`."""
    if axis != 0:
        raise NotImplementedError(
            "all_gather stacks on a new leading dim (reference "
            "semantics); for a concat along an existing axis use "
            "all_gather_concat(tensor, axis=...)")
    group = group or _world_group()
    ax = _axis_arg(group.axes)
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    def traced(s):
        return jax.lax.all_gather(s, ax, axis=0, tiled=False)

    out = apply_op(lambda x: _run(group, x, traced, out_spec=P(),
                                  cache_key=("all_gather",)), [t],
                   name="all_gather")
    if tensor_list is not None:
        del tensor_list[:]
        for i in range(group.nranks):
            tensor_list.append(out[i])
        return tensor_list
    return out


def all_gather_concat(tensor, group=None, axis=0):
    """TPU-native helper: gather and concat along `axis` (tiled all-gather —
    what SP/mp layers actually want)."""
    group = group or _world_group()
    ax = _axis_arg(group.axes)
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    def traced(s):
        return jax.lax.all_gather(s, ax, axis=axis, tiled=True)

    return apply_op(lambda x: _run(group, x, traced, out_spec=P(),
                                   cache_key=("all_gather_concat",
                                              axis)), [t],
                    name="all_gather_concat")


def reduce_scatter(tensor, tensor_or_tensor_list=None, op=ReduceOp.SUM,
                   group=None, sync_op=True, axis=0):
    """Reference communication/reduce_scatter.py: sum across ranks, then
    scatter slices along dim `axis`.

    Global-view semantics (single controller): the result keeps the GLOBAL
    shape, laid out sharded over the group axis along `axis` — device i
    holds slice i. Code that wants the per-rank slice shape of the
    reference API should index the result. The in-place form therefore
    requires `tensor` to already have the global shape (ADVICE r1)."""
    group = group or _world_group()
    ax = _axis_arg(group.axes)
    src = tensor_or_tensor_list if tensor_or_tensor_list is not None else tensor
    t = src if isinstance(src, Tensor) else Tensor(src)

    def traced(s):
        return jax.lax.psum_scatter(s, ax, scatter_dimension=axis, tiled=True)

    spec_axes = [None] * t.ndim
    spec_axes[axis] = ax
    out = apply_op(
        lambda x: _run(group, x, traced, out_spec=P(*spec_axes),
                       cache_key=("reduce_scatter", str(op), axis)),
        [t],
        name="reduce_scatter",
    )
    if tensor_or_tensor_list is not None and isinstance(tensor, Tensor):
        if tuple(tensor.shape) != tuple(out.shape):
            raise ValueError(
                f"reduce_scatter out tensor has shape {tuple(tensor.shape)} "
                f"but the global-view result has shape {tuple(out.shape)}; "
                "pass a global-shaped out tensor or use the return value")
        tensor._inplace_from(out)
        return tensor
    return out


def _quantized_sum_traced(axes, nranks, qformat):
    """EQuARX-style compressed all-reduce (PAPERS.md): decompose the ring
    all-reduce into its scatter leg (all_to_all of per-destination chunks)
    and gather leg (all_gather of the locally reduced chunk) and carry BOTH
    legs' payloads compressed — int8 with symmetric per-block scales on
    each side (the "two-sided" scales: the scatter leg ships each source
    rank's block scales, the gather leg ships the reduced chunk's), or
    bf16. Accumulation is fp32 on every path, so only the wire format is
    lossy; the fp32-parity contract is asserted by comm_quant_selftest."""
    ax = _axis_arg(axes)
    n = int(nranks)
    if qformat not in ("int8", "bf16"):
        raise ValueError(
            f"unsupported comm quant format {qformat!r} (int8|bf16)")

    # scaling-block granularity, both legs (EQuARX block scaling): one
    # fp32 scale per 32 int8 payload bytes (+12.5% wire) holds the L2
    # relative error near 6e-3 at n=8 — a whole-chunk max-based scale
    # floors at ~1e-2 because one outlier sets every element's step
    QBLOCK = 32

    def _q_blocks(x, b):
        """Symmetric int8 per-block: x [..., c] -> (q int8 [..., c/b, b],
        scales fp32 [..., c/b])."""
        blocks = x.reshape(x.shape[:-1] + (x.shape[-1] // b, b))
        return quantize_symmetric_q8(blocks)

    def traced(s):
        orig_shape, orig_dtype = s.shape, s.dtype
        flat = s.astype(jnp.float32).reshape(-1)
        # pad to a multiple of n*QBLOCK so every per-rank chunk splits
        # into whole scaling blocks — padding only to n would silently
        # collapse a non-32-aligned chunk to ONE whole-chunk scale,
        # reintroducing the ~1e-2 outlier floor
        pad = (-flat.shape[0]) % (n * QBLOCK)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        chunks = flat.reshape(n, -1)
        c = chunks.shape[1]
        assert c % QBLOCK == 0, (c, QBLOCK)   # guaranteed by the padding
        b = QBLOCK
        if qformat == "int8":
            # scatter leg: per-block scales, shipped on the same
            # all_to_all route as their chunks so they stay paired
            q, s1 = _q_blocks(chunks, b)           # [n, c/b, b], [n, c/b]
            recv = jax.lax.all_to_all(q, ax, split_axis=0, concat_axis=0)
            src_scales = jax.lax.all_to_all(s1, ax, split_axis=0,
                                            concat_axis=0)     # [n, c/b]
            red = jnp.sum(recv.astype(jnp.float32)
                          * src_scales[..., None], axis=0)     # [c/b, b]
            # gather leg: requantize the reduced chunk per block
            q2, s2 = _q_blocks(red.reshape(-1), b)
            gathered = jax.lax.all_gather(q2, ax)         # [n, c/b, b]
            out_scales = jax.lax.all_gather(s2, ax)       # [n, c/b]
            out = (gathered.astype(jnp.float32)
                   * out_scales[..., None]).reshape(-1)
        else:  # bf16
            recv = jax.lax.all_to_all(chunks.astype(jnp.bfloat16), ax,
                                      split_axis=0, concat_axis=0)
            red = jnp.sum(recv.astype(jnp.float32), axis=0)
            out = jax.lax.all_gather(red.astype(jnp.bfloat16), ax) \
                .astype(jnp.float32).reshape(-1)
        if pad:
            out = out[:-pad]
        return out.reshape(orig_shape).astype(orig_dtype)

    return traced


QUANT_SCATTER_BLOCK = 32      # int8 scaling-block, same as _quantized_sum


def quantize_symmetric_q8(x, axis=-1):
    """Symmetric int8 quantization along `axis` — THE wire/storage
    format of the comm stack (EQuARX per-block scales, PAPERS.md) and,
    since ISSUE 16, of the int8 paged KV pools (inference/kv_cache.py):
    one fp32 scale per `axis`-row, payload = round(x / scale) clipped to
    [-127, 127]. Returns (q int8, scales fp32 with `axis` removed); the
    1e-30 floor keeps all-zero rows from dividing by zero."""
    sc = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis),
                     1e-30) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32)
                           / jnp.expand_dims(sc, axis)),
                 -127, 127).astype(jnp.int8)
    return q, sc


def dequantize_q8(q, scales, axis=-1, dtype=jnp.float32):
    """Inverse of `quantize_symmetric_q8`: q * scale broadcast along
    `axis` (scales has `axis` removed)."""
    return (q.astype(jnp.float32)
            * jnp.expand_dims(scales, axis)).astype(dtype)


def quantized_psum_scatter_traced(axis, nranks, qformat):
    """The SCATTER LEG of the compressed all-reduce above, as a traced
    psum_scatter replacement for use INSIDE shard_map (the sharded
    fused-scan step's per-layer grad reduce-scatter): per-destination
    chunks ship int8 with per-block symmetric scales (or bf16), the sum
    accumulates in fp32. Input [..., n*c] on the LAST dim (c must split
    into whole QUANT_SCATTER_BLOCKs for int8 — callers pad the flat
    layout to nranks*QUANT_SCATTER_BLOCK); returns the local reduced
    chunk [..., c], numerically ≈ lax.psum_scatter to the comm_quant
    tolerance (rel err ~7e-3 int8, bf16 rounding for bf16).

    ``axis`` may be a TUPLE of mesh axis names (ISSUE 11): the
    all_to_all then exchanges chunks over the flattened first-axis-major
    product — the same split order as tuple-axis ``lax.psum_scatter`` —
    so the dp×mp/pp/ep hybrid steps' flattened grad scatter gets the
    same wire format as the single-axis path (``nranks`` is the
    flattened product; verified against the exact tuple psum_scatter by
    ``comm_quant_multiaxis_selftest``)."""
    n = int(nranks)
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis) if len(axis) > 1 else axis[0]
    if qformat not in ("int8", "bf16"):
        raise ValueError(
            f"unsupported comm quant format {qformat!r} (int8|bf16)")
    b = QUANT_SCATTER_BLOCK

    def traced(x):
        lead = x.shape[:-1]
        c = x.shape[-1] // n
        chunks = x.astype(jnp.float32).reshape(lead + (n, c))
        split_ax = len(lead)
        if qformat == "int8":
            if c % b:
                raise ValueError(
                    f"chunk {c} not a multiple of the {b}-wide int8 "
                    "scaling block; pad the flat layout to "
                    "nranks*QUANT_SCATTER_BLOCK")
            blocks = chunks.reshape(lead + (n, c // b, b))
            q, sc = quantize_symmetric_q8(blocks)
            recv = jax.lax.all_to_all(q, axis, split_axis=split_ax,
                                      concat_axis=split_ax)
            src_sc = jax.lax.all_to_all(sc, axis, split_axis=split_ax,
                                        concat_axis=split_ax)
            red = jnp.sum(recv.astype(jnp.float32) * src_sc[..., None],
                          axis=split_ax)
            return red.reshape(lead + (c,)).astype(x.dtype)
        recv = jax.lax.all_to_all(chunks.astype(jnp.bfloat16), axis,
                                  split_axis=split_ax,
                                  concat_axis=split_ax)
        return jnp.sum(recv.astype(jnp.float32),
                       axis=split_ax).astype(x.dtype)

    return traced


def quantized_all_gather_traced(axis, qformat, gather_axis=-1):
    """The GATHER LEG as a standalone traced collective: a tiled
    all_gather whose wire payload is int8 with symmetric per-block
    scales (or bf16) — the EQuARX gather-leg wire format applied to the
    sharded-parameter-storage gather-on-use path (ISSUE 11). Each rank
    quantizes its own shard ONCE, ships payload + scales on the same
    gather route so they stay paired, and dequantizes the concatenated
    result; there is no accumulation, so the elementwise error is
    bounded by one block's quantization step (rel err ~5e-3 int8 on
    standard-normal data, bf16 rounding for bf16).

    ``axis`` may be a tuple of mesh axes: the chunks concatenate in
    flattened first-axis-major order, identical to tuple-axis
    ``lax.all_gather(tiled=True)`` (the split order `gather_flat`
    depends on). The gathered dim (``gather_axis``, default last) must
    split into whole QUANT_SCATTER_BLOCKs for int8 — the flat-bucket
    layouts pad to nranks*QUANT_SCATTER_BLOCK already."""
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis) if len(axis) > 1 else axis[0]
    if qformat not in ("int8", "bf16"):
        raise ValueError(
            f"unsupported comm quant format {qformat!r} (int8|bf16)")
    b = QUANT_SCATTER_BLOCK

    def traced(x):
        ga = gather_axis % x.ndim
        if ga != x.ndim - 1:                    # quantize blocks on last
            x = jnp.moveaxis(x, ga, -1)
        lead, c = x.shape[:-1], x.shape[-1]
        if qformat == "int8":
            if c % b:
                raise ValueError(
                    f"gather dim {c} not a multiple of the {b}-wide "
                    "int8 scaling block; pad the flat layout to "
                    "nranks*QUANT_SCATTER_BLOCK")
            blocks = x.astype(jnp.float32).reshape(lead + (c // b, b))
            q, sc = quantize_symmetric_q8(blocks)
            gq = jax.lax.all_gather(q, axis, axis=len(lead), tiled=True)
            gsc = jax.lax.all_gather(sc, axis, axis=len(lead),
                                     tiled=True)
            out = (gq.astype(jnp.float32) * gsc[..., None]).reshape(
                lead + (-1,)).astype(x.dtype)
        else:  # bf16
            g = jax.lax.all_gather(x.astype(jnp.bfloat16), axis,
                                   axis=len(lead), tiled=True)
            out = g.astype(x.dtype)
        if ga != out.ndim - 1:
            out = jnp.moveaxis(out, -1, ga)
        return out

    return traced


def comm_quant_multiaxis_selftest(qformat="int8", numel_per_rank=2048,
                                  seed=0, mesh=None, axes=None):
    """Rel-err selftest for the FLATTENED-axis-tuple compressed legs
    (ISSUE 11 satellite): on a dp×mp-shaped host mesh, the tuple-axis
    quantized scatter must match exact tuple-axis psum_scatter, and the
    tuple-axis quantized all_gather must match exact tiled all_gather,
    both within the comm_quant bound (int8 rel err < 1e-2 — same gate
    as `comm_quant_selftest`; the gather leg has no accumulation so it
    lands tighter). Every rank holds distinct data with a distinct
    magnitude so chunk/scale mispairing or a wrong flat-rank split
    order would blow the gate, not hide under symmetry."""
    if mesh is None:
        mesh = env.get_mesh()
    if axes is None:
        axes = tuple(mesh.axis_names[:2])
    axes = tuple(axes)
    degrees = [int(mesh.shape[a]) for a in axes]
    n = int(np.prod(degrees))
    b = QUANT_SCATTER_BLOCK
    c = -(-int(numel_per_rank) // b) * b
    rng = np.random.default_rng(seed)
    data = (rng.standard_normal((n, n * c))
            * (1.0 + 0.1 * np.arange(n))[:, None]).astype(np.float32)
    flat = jax.device_put(jnp.asarray(data.reshape(-1)),
                          NamedSharding(mesh, P(axes)))

    def legs(x):
        exact_s = jax.lax.psum_scatter(x, axes, scatter_dimension=0,
                                       tiled=True)
        quant_s = quantized_psum_scatter_traced(axes, n, qformat)(x)
        shard = exact_s
        exact_g = jax.lax.all_gather(shard, axes, axis=0, tiled=True)
        quant_g = quantized_all_gather_traced(axes, qformat)(shard)
        return exact_s, quant_s, exact_g, quant_g

    es, qs, eg, qg = jax.jit(shard_map(
        legs, mesh=mesh, in_specs=(P(axes),),
        out_specs=(P(axes), P(axes), P(), P()), check_vma=False))(flat)

    def rel(got, ref):
        return float(jnp.linalg.norm(got.astype(jnp.float32)
                                     - ref.astype(jnp.float32))) / max(
            float(jnp.linalg.norm(ref.astype(jnp.float32))), 1e-30)

    r_s, r_g = rel(qs, es), rel(qg, eg)
    return {"qformat": qformat, "axes": list(axes),
            "degrees": degrees, "nranks": n,
            "scatter_rel_err": r_s, "gather_rel_err": r_g,
            "pass": bool(r_s < 1e-2 and r_g < 1e-2)}


def all_reduce_quantized(tensor, op=ReduceOp.SUM, group=None, qformat=None,
                         sync_op=True):
    """Compressed all_reduce (SUM only); in-place on `tensor` like
    all_reduce. `qformat` defaults to FLAGS_comm_quant; with the flag unset
    ('') this is exactly all_reduce — the compressed path is opt-in."""
    if qformat is None:
        from ..utils import flags as _flags

        qformat = _flags.get_flag("FLAGS_comm_quant") or ""
    if not qformat:
        return all_reduce(tensor, op=op, group=group)
    if op not in (ReduceOp.SUM, "sum"):
        raise ValueError(
            f"quantized collectives support ReduceOp.SUM only, got {op}")
    group = group or _world_group()
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    fn = _quantized_sum_traced(group.axes, group.nranks, qformat)
    out = apply_op(
        lambda x: _run(group, x, fn,
                       cache_key=("all_reduce_quantized", qformat)),
        [t], name="all_reduce_quantized")
    t._inplace_from(out)
    return t


def comm_quant_selftest(group=None, qformat="int8", numel=4096, seed=0):
    """fp32-parity self-test for the compressed collective path: sums
    random grads through the quantized all-reduce and reports the relative
    error against the exact fp32 psum. Contract (ISSUE/EQuARX): int8
    relative error < 1e-2 on standard-normal grads.

    The grads are SHARDED over the group axis with a different magnitude
    per rank, so every rank holds distinct data and a distinct bucket
    scale — a bug that mispairs recv chunks with source scales (or the
    scatter/gather-leg scales) changes the result here; a replicated
    input would mask it (identical rows, identical scales)."""
    group = group or _world_group()
    rng = np.random.default_rng(seed)
    n = group.nranks
    # distinct data AND distinct scales per rank, but only a 10% spread:
    # a mispaired scale still shifts the result by ~10% of a chunk
    # (far above the 1e-2 gate), while an order-of-magnitude spread
    # would unfairly inflate the honest quantization error itself
    per_rank = (rng.standard_normal((n, numel))
                * (1.0 + 0.1 * np.arange(n))[:, None]).astype(np.float32)
    data = jnp.asarray(per_rank.reshape(-1))
    if len(group.axes) == 1:
        data = jax.device_put(data, NamedSharding(
            group.mesh, P(group.axes[0])))
    ref = all_reduce(Tensor(data), group=group)
    got = all_reduce_quantized(Tensor(data), group=group, qformat=qformat)
    err = got._data - ref._data
    # rel_err: L2-norm ratio (the standard vector relative error; the
    # gate). max_rel: worst element over the result's max — reported for
    # visibility, intrinsically ~2/254 for two-leg int8
    rel = float(jnp.linalg.norm(err)) / max(
        float(jnp.linalg.norm(ref._data)), 1e-30)
    max_rel = float(jnp.max(jnp.abs(err))) / max(
        float(jnp.max(jnp.abs(ref._data))), 1e-30)
    return {"qformat": qformat, "nranks": n, "numel": numel,
            "rel_err": rel, "max_rel": max_rel,
            "pass": bool(rel < 1e-2)}


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Reference communication/broadcast.py: every rank gets src's value."""
    group = group or _world_group()
    ax = _axis_arg(group.axes)
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    def traced(s):
        idx = jax.lax.axis_index(ax)
        contrib = jnp.where(idx == src, s, jnp.zeros_like(s))
        return jax.lax.psum(contrib, ax)

    out = apply_op(lambda x: _run(group, x, traced,
                              cache_key=("broadcast", src)),
               [t], name="broadcast")
    t._inplace_from(out)
    return t


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """src's list entry i goes to rank i. Global view: returns the stacked
    [nranks, ...] tensor laid out so device i holds row i (the DTensor form
    of "each rank has its row"). Traced context: takes this rank's row."""
    group = group or _world_group()
    ax = _axis_arg(group.axes)
    if tensor_list is not None:
        stacked = Tensor(jnp.stack([x._data if isinstance(x, Tensor) else x
                                    for x in tensor_list]))
    else:
        stacked = tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    if isinstance(stacked._data, jax.core.Tracer) and _axis_bound(ax):
        def pick(s):
            return jnp.take(s, jax.lax.axis_index(ax), axis=0)

        return apply_op(pick, [stacked], name="scatter")

    spec = P(ax, *([None] * (stacked.ndim - 1)))
    sharding = NamedSharding(group.mesh, spec)
    out = apply_op(lambda x: jax.device_put(x, sharding), [stacked],
                   name="scatter")
    if isinstance(tensor, Tensor):
        tensor._inplace_from(out)
        return tensor
    return out


def alltoall(out_tensor_list, in_tensor_list=None, group=None, sync_op=True):
    """Reference communication/all_to_all.py."""
    group = group or _world_group()
    ax = _axis_arg(group.axes)
    if in_tensor_list is None:
        in_tensor_list = out_tensor_list
    stacked = Tensor(jnp.stack([x._data if isinstance(x, Tensor) else x
                                for x in in_tensor_list]))

    def traced(s):
        # s: [nranks, ...] rows destined per rank
        return jax.lax.all_to_all(s, ax, split_axis=0, concat_axis=0,
                                  tiled=False)

    out = apply_op(lambda x: _run(group, x, traced, out_spec=P(),
                                  cache_key=("alltoall",)), [stacked],
                   name="alltoall")
    if out_tensor_list is not None:
        del out_tensor_list[:]
        for i in range(group.nranks):
            out_tensor_list.append(out[i])
        return out_tensor_list
    return out


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    group = group or _world_group()
    ax = _axis_arg(group.axes)
    for splits in (in_split_sizes, out_split_sizes):
        if splits and len(set(splits)) > 1:
            raise NotImplementedError(
                "alltoall_single with unequal split sizes is not supported "
                "on the XLA all_to_all path (equal splits only)")
    t = in_tensor if isinstance(in_tensor, Tensor) else Tensor(in_tensor)

    def traced(s):
        return jax.lax.all_to_all(s, ax, split_axis=0, concat_axis=0,
                                  tiled=True)

    out = apply_op(lambda x: _run(group, x, traced,
                                  cache_key=("alltoall_single",)), [t],
                   name="alltoall_single")
    if isinstance(out_tensor, Tensor):
        out_tensor._inplace_from(out)
        return out_tensor
    return out


class _P2PTask:
    """Completed-task handle (reference core.task): eager single-controller
    p2p completes synchronously, so wait() is a no-op."""

    def wait(self):
        return True

    def is_completed(self):
        return True


# One FIFO mailbox per group (keyed by mesh identity + axes, holding a
# strong mesh ref so id() can't be reused for a different mesh while
# messages are pending). Single-controller semantics: EVERY group rank is
# this process (the all_gather_object convention), so peer arguments are
# range-validated routing metadata, not matching keys — a recv returns
# the oldest unconsumed send in the group. For a symmetric SPMD program
# (each rank sends to next / receives from prev) this is exactly the
# value the real exchange would deliver, since all ranks run this same
# code on the same process-local data. destroy_process_group drains it.
_p2p_mailbox: dict[tuple, tuple] = {}
_p2p_multidst_warned: list = []  # once-per-process latch


def _p2p_box(group):
    from collections import deque

    key = (id(group.mesh), group.axes)
    entry = _p2p_mailbox.get(key)
    if entry is None or entry[0] is not group.mesh:
        entry = (group.mesh, deque())
        _p2p_mailbox[key] = entry
    return entry[1]


def send(tensor, dst=0, group=None, sync_op=True):
    """Point-to-point send (reference communication/send.py:27).

    Eager single-controller: the tensor is enqueued to the group's
    in-process mailbox; `recv` dequeues it (see _p2p_mailbox). Inside
    traced code use `p2p_permute` (lax.ppermute) — XLA has no
    rank-conditional send."""
    group = group or _world_group()
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    if isinstance(t._data, jax.core.Tracer):
        raise RuntimeError(
            "send() inside traced code is not expressible (per-rank "
            "branches don't trace); use p2p_permute() / the pipeline ring")
    if not 0 <= dst < group.nranks:
        raise ValueError(f"dst {dst} out of range for {group!r}")
    _p2p_box(group).append((int(dst), t._data))
    return _P2PTask()


def recv(tensor, src=0, group=None, sync_op=True):
    """Point-to-point receive (reference communication/recv.py:27): fills
    `tensor` in place with the group's oldest unconsumed `send`. Shape
    and dtype must match the sent tensor (reference send/recv metadata
    contract)."""
    group = group or _world_group()
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)
    if isinstance(t._data, jax.core.Tracer):
        raise RuntimeError(
            "recv() inside traced code is not expressible; use "
            "p2p_permute() / the pipeline ring")
    if not 0 <= src < group.nranks:
        raise ValueError(f"src {src} out of range for {group!r}")
    box = _p2p_box(group)
    if not box:
        raise RuntimeError(
            f"recv(src={src}): no matching send in flight (single-"
            "controller p2p completes in-process; send must happen first)")
    # The single-controller mailbox delivers in send order. That is correct
    # for translation-symmetric SPMD patterns — including bidirectional
    # halo exchanges (two dsts in flight), where every rank issues the
    # same sends/recvs in the same program order — but it cannot verify a
    # genuinely non-symmetric pattern (e.g. rank 0 sending different
    # tensors to ranks 1 and 2), which would silently deliver the oldest
    # send to the wrong logical receiver. Warn once per process when
    # multiple distinct dsts are in flight so that case is auditable.
    dsts = {d for d, _ in box}
    if len(dsts) > 1 and not _p2p_multidst_warned:
        import warnings

        _p2p_multidst_warned.append(True)
        warnings.warn(
            f"recv(src={src}): sends to multiple distinct dst ranks "
            f"{sorted(dsts)} are in flight; the in-process mailbox "
            "delivers in send order, which is only correct for "
            "symmetric SPMD p2p programs (every rank issuing the same "
            "sends/recvs in the same order). For non-symmetric patterns "
            "use p2p_permute() inside traced code.", RuntimeWarning,
            stacklevel=2)
    _, data = box.popleft()
    if tuple(data.shape) != tuple(t._data.shape):
        raise ValueError(
            f"recv buffer shape {tuple(t._data.shape)} != sent shape "
            f"{tuple(data.shape)}")
    if data.dtype != t._data.dtype:
        raise ValueError(
            f"recv buffer dtype {t._data.dtype} != sent dtype "
            f"{data.dtype} (send/recv metadata must match)")
    t._inplace_from(Tensor._wrap(data))
    return _P2PTask()


isend = send
irecv = recv


class P2POp:
    """Batched p2p descriptor (reference communication/batch_isend_irecv.py:34)."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv, send, recv):
            raise ValueError(
                "op must be paddle.distributed.isend or irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Execute a batch of P2POps (reference batch_isend_irecv.py:132).
    Sends run before receives so a rank's paired ops can't deadlock —
    the single-controller analog of the reference's grouped NCCL calls."""
    if not p2p_op_list:
        raise ValueError("p2p_op_list must not be empty")
    for p in p2p_op_list:
        if not isinstance(p, P2POp):
            raise TypeError("batch_isend_irecv takes a list of P2POp")
    tasks = []
    sends = [p for p in p2p_op_list if p.op in (send, isend)]
    recvs = [p for p in p2p_op_list if p.op in (recv, irecv)]
    for p in sends:
        tasks.append(p.op(p.tensor, p.peer, group=p.group))
    for p in recvs:
        tasks.append(p.op(p.tensor, p.peer, group=p.group))
    return tasks


def p2p_permute(tensor, perm, group=None):
    """Traced-context point-to-point: permute values across the group axis.
    perm: list of (src, dst) pairs (reference P2pHelper's send/recv pattern,
    fleet/meta_parallel/pp_utils/p2p_communication.py:570)."""
    group = group or _world_group()
    ax = _axis_arg(group.axes)
    t = tensor if isinstance(tensor, Tensor) else Tensor(tensor)

    def traced(s):
        return jax.lax.ppermute(s, ax, perm)

    return apply_op(
        lambda x: _run(group, x, traced,
                       cache_key=("p2p_permute", tuple(map(tuple, perm)))),
        [t], name="p2p_permute")


def barrier(group=None):
    """Synchronize: a tiny psum forced to completion. The blocking wait is
    guarded by the comm watchdog (reference: comm_task_manager.h:37 watches
    every outstanding collective) so a dead peer interrupts instead of
    hanging forever."""
    group = group or _world_group()
    fn = _reduce_traced(group.axes, ReduceOp.SUM)
    out = _run(group, jnp.zeros((), jnp.int32), fn,
               cache_key=("barrier",))
    from . import comm_watchdog

    with comm_watchdog.watch(f"barrier(axes={group.axes})"):
        jax.block_until_ready(out)


def all_gather_object(object_list, obj, group=None):
    """Host-side object gather; single-controller: every rank is this
    process, so the list is nranks copies (parity with references tests)."""
    group = group or _world_group()
    del object_list[:]
    object_list.extend([obj] * group.nranks)
    return object_list


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    return env.get_world_size()


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    return env.get_rank()


def is_initialized() -> bool:
    return env.is_initialized()


def destroy_process_group(group=None):
    global _default_group
    _default_group = None
    _p2p_mailbox.clear()   # drop pending p2p messages (and mesh refs)
    _eager_fn_cache.clear()  # drop mesh refs + compiled executables
