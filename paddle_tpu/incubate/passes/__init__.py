"""paddle.incubate.passes (reference incubate/passes/ir.py): python
IR-pass authoring over ProgramDesc. Program transformation happens in
XLA's pass pipeline on this backend; there is no python pass hook."""
from __future__ import annotations


def ir_pass(*a, **k):
    raise NotImplementedError(
        "python IR passes rewrite ProgramDesc graphs; the TPU backend "
        "compiles jaxpr through XLA's pass pipeline (no python hook)")
