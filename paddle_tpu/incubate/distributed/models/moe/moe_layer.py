"""MoE layer with expert parallelism.

Reference parity: python/paddle/incubate/distributed/models/moe/moe_layer.py
(MoELayer :263, global_scatter :119, global_gather :140) with gshard/switch
gates (gate/).

TPU-first: the reference routes tokens with index-list global_scatter/
global_gather collectives (NCCL alltoall of ragged buffers). Here routing is
the GShard einsum formulation — dense [T,E,C] dispatch/combine masks, expert
params STACKED on a leading E dim sharded over the ``ep`` mesh axis, and a
vmap over experts; XLA GSPMD lowers the dispatch/combine einsums to the
all-to-alls on ICI. Static shapes (capacity) keep it jit-compilable; drops
are mask zeros, not ragged buffers.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..... import nn
from .....framework.tensor import Tensor
from .....framework.autograd import apply_op, no_grad
from .....nn.layer.layers import Parameter
from .gate import NaiveGate

__all__ = ["MoELayer", "ExpertFFN", "global_scatter", "global_gather"]


class ExpertFFN(nn.Layer):
    """Default expert: fc1 -> gelu -> fc2 (the reference examples' expert)."""

    def __init__(self, d_model, d_hidden):
        super().__init__()
        self.fc1 = nn.Linear(d_model, d_hidden)
        self.fc2 = nn.Linear(d_hidden, d_model)

    def forward(self, x):
        return self.fc2(nn.functional.gelu(self.fc1(x)))


class MoELayer(nn.Layer):
    """Mixture-of-experts over an expert-parallel mesh axis.

    Args:
      d_model: token feature size.
      experts: list of identically-structured expert Layers (their initial
        params are stacked onto a leading num_experts dim).
      gate: "gshard" (top-2) | "switch" (top-1) | a NaiveGate instance.
      capacity_factor: per-expert slots = ceil(cf * T / E). float("inf")
        disables dropping (capacity = T).
      axis: expert-parallel mesh axis name; stacked params are sharded over
        it when the ambient mesh has the axis.

    After forward, ``self.l_aux`` holds the load-balancing loss Tensor
    (add it to the training loss, reference MoELayer semantics).
    """

    def __init__(self, d_model, experts, gate="gshard",
                 capacity_factor=1.25, axis="ep", mesh=None, group=None):
        super().__init__()
        self.d_model = int(d_model)
        self.num_experts = len(experts)
        self.capacity_factor = capacity_factor
        self.gate = gate if isinstance(gate, NaiveGate) else NaiveGate(gate)
        self._axis = axis
        self._mesh = group.mesh if group is not None else mesh
        self.gate_weight = self.create_parameter(
            [self.d_model, self.num_experts])

        template = experts[0]
        object.__setattr__(self, "_template", template)
        names = [n for n, _ in template.named_parameters()]
        self._stacked_names = []
        for pname in names:
            stacked = jnp.stack([
                dict(e.named_parameters())[pname]._data for e in experts])
            flat = "experts__" + pname.replace(".", "__")
            self.add_parameter(flat, Parameter(stacked))
            self._stacked_names.append((flat, pname))
        self.l_aux = None
        self._shard_params()

    def _resolve_mesh(self):
        mesh = self._mesh
        if mesh is None:
            from .....distributed import env as denv

            if denv.is_initialized():
                mesh = denv.get_mesh()
        if mesh is not None and self._axis in mesh.axis_names \
                and mesh.shape[self._axis] > 1:
            return mesh
        return None

    def _shard_params(self):
        mesh = self._resolve_mesh()
        if mesh is None:
            return
        for flat, _ in self._stacked_names:
            p = self._parameters[flat]
            if p._data.shape[0] % mesh.shape[self._axis] == 0:
                spec = P(self._axis, *([None] * (p._data.ndim - 1)))
                p._data = jax.device_put(p._data,
                                         NamedSharding(mesh, spec))

    def _capacity(self, num_tokens):
        if math.isinf(self.capacity_factor):
            return int(num_tokens)
        return max(1, int(math.ceil(
            self.capacity_factor * num_tokens / self.num_experts)))

    def forward(self, x):
        orig_shape = x.shape
        hidden = orig_shape[-1]
        if hidden != self.d_model:
            raise ValueError(f"expected feature dim {self.d_model}, "
                             f"got {hidden}")
        num_tokens = 1
        for s in orig_shape[:-1]:
            num_tokens *= s
        capacity = self._capacity(num_tokens)
        gate_fn = self.gate
        mesh = self._resolve_mesh()
        axis = self._axis
        template = self._template
        leaves = [p for _, p in template.named_parameters()]
        stacked = [self._parameters[flat] for flat, _ in self._stacked_names]

        def expert_apply(layer_leaves, xe):
            with no_grad():
                saved = [p._data for p in leaves]
                for p, d in zip(leaves, layer_leaves):
                    p._data = d
                try:
                    out = template(Tensor._wrap(xe))._data
                finally:
                    for p, d in zip(leaves, saved):
                        p._data = d
            return out

        def moe_fn(xa, wg, *stacked_leaves):
            xt = xa.reshape(num_tokens, hidden)
            logits = (xt.astype(jnp.float32)
                      @ wg.astype(jnp.float32))
            combine, dispatch, aux = gate_fn(logits, capacity)
            combine = combine.astype(xt.dtype)
            expert_in = jnp.einsum(
                "tec,th->ech", dispatch.astype(xt.dtype), xt)
            if mesh is not None:
                from .....distributed.env import pin_sharding

                spec = P(axis, *([None] * (expert_in.ndim - 1)))
                expert_in = pin_sharding(expert_in,
                                         NamedSharding(mesh, spec))
            expert_out = jax.vmap(expert_apply)(list(stacked_leaves),
                                                expert_in)
            y = jnp.einsum("tec,ech->th", combine, expert_out)
            return y.reshape(orig_shape), aux.astype(jnp.float32)

        y, aux = apply_op(moe_fn, [x, self.gate_weight] + stacked,
                          name="moe")
        self.l_aux = aux
        return y


def _default_group():
    """World group when the distributed env is up, else None (count checks
    that need a group are skipped outside a mesh)."""
    from .....distributed import env as denv

    if not denv.is_initialized():
        return None
    from .....distributed.collective import get_group

    return get_group()


def _validated_counts(local_count, global_count, name, x=None, group=None):
    """The reference kernels move count-shaped ragged buffers
    (distributed/utils/moe_utils.py global_scatter/global_gather). The XLA
    all_to_all path is equal-split, so the counts are VERIFIED rather than
    silently ignored: uniform counts run (they describe exactly the
    equal-split exchange), ragged counts raise with guidance to the
    TPU-native dense-capacity einsum dispatch (MoELayer), which is this
    framework's ragged-routing mechanism (static shapes, GSPMD all-to-all).
    """
    import numpy as np

    counts = []
    for c in (local_count, global_count):
        if c is None:
            counts.append(None)
            continue
        data = c._data if isinstance(c, Tensor) else c
        if isinstance(data, jax.core.Tracer):
            raise NotImplementedError(
                f"{name} with traced counts cannot be validated; use "
                "MoELayer's dense capacity dispatch inside jit")
        counts.append(np.asarray(data))
    lc, gc = counts
    if lc is not None and gc is not None and lc.sum() != gc.sum():
        raise ValueError(
            f"{name}: local_count total ({int(lc.sum())}) != global_count "
            f"total ({int(gc.sum())}) — the exchange would lose tokens")
    for label, c in (("local_count", lc), ("global_count", gc)):
        if c is not None and len(set(c.tolist())) > 1:
            raise NotImplementedError(
                f"{name} with ragged {label} ({c.tolist()}) is not "
                "supported on the XLA equal-split all_to_all path; route "
                "tokens with MoELayer's capacity-slot einsum dispatch "
                "(the TPU-native ragged mechanism) or pad buckets to "
                "uniform counts")
    # counts must actually describe the exchange (not just be uniform):
    # length a multiple of nranks (n_expert * world entries) and totals
    # covering x's rows (global leading dim = nranks * per-rank rows)
    if group is not None and lc is not None:
        nranks = group.nranks
        if lc.size % nranks:
            raise ValueError(
                f"{name}: counts length {lc.size} is not a multiple of "
                f"the group's nranks ({nranks})")
        if x is not None:
            rows = (x._data if isinstance(x, Tensor)
                    else jnp.asarray(x)).shape[0]
            if int(lc.sum()) * nranks != rows:
                raise ValueError(
                    f"{name}: counts route {int(lc.sum())} rows/rank x "
                    f"{nranks} ranks but x has {rows} rows")


def global_scatter(x, local_count, global_count, group=None):
    """Reference moe_layer.py:119 — alltoall token push. Counts are
    validated (uniform -> equal-split all_to_all; ragged -> error), never
    silently ignored."""
    from .....distributed.collective import alltoall_single

    _validated_counts(local_count, global_count, "global_scatter", x=x,
                      group=group or _default_group())
    out = Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor)
                                else jnp.asarray(x)))
    alltoall_single(out, x, group=group)
    return out


def global_gather(x, local_count, global_count, group=None):
    """Reference moe_layer.py:140 — inverse alltoall pull (counts
    validated, equal splits only; see global_scatter)."""
    from .....distributed.collective import alltoall_single

    _validated_counts(local_count, global_count, "global_gather", x=x,
                      group=group or _default_group())
    out = Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor)
                                else jnp.asarray(x)))
    alltoall_single(out, x, group=group)
    return out
