"""Auto-generated op-sweep fleet (VERDICT r4 next #4): one numpy-referenced
sweep per implemented op with a mappable signature, driven by a spec table
— the bulk counterpart of the reference's per-op OpTest fleet
(/root/reference/test/legacy_test/op_test.py:418, 1,217 files).

Each spec checks: forward vs numpy in fp32 (tight) AND bf16 (loose), and
tape-AD grads vs central finite differences in fp32 for differentiable
ops. Ops whose signatures don't map to the (arrays in → arrays out) shape
are listed in SKIPPED with the reason, so the sweep's coverage boundary
is explicit. Specs reuse the OpTest harness (tests/op_test.py).
"""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

from op_test import OpTest


class Spec:
    def __init__(self, name, op, ref, inputs, grad=(0,), tols=None,
                 dtypes=("float32", "bfloat16"), grad_kw=None,
                 grad_skip=None):
        self.name = name
        self.op = op
        self.ref = ref
        self.inputs = inputs
        self.grad = grad               # wrt indices, or None = no grad check
        self.tols = tols or {}
        self.dtypes = dtypes
        self.grad_kw = grad_kw or {}
        # forward-only specs must say WHY in one word (boolean / integer /
        # indices / zerograd / discontinuous / constant / counting /
        # nogradrule / nangrad / complex / unstable / aliasing / dynshape) — the
        # explicit coverage boundary of the grad sweep
        self.grad_skip = grad_skip


def _pos(shape=(3, 4), lo=0.2, hi=2.0):
    def gen(rng):
        return [rng.uniform(lo, hi, shape).astype("float32")]
    return gen


def _std(shape=(3, 4), scale=1.0, n=1):
    def gen(rng):
        return [(rng.standard_normal(shape) * scale).astype("float32")
                for _ in range(n)]
    return gen


def _unit(shape=(3, 4), lo=-0.9, hi=0.9):
    def gen(rng):
        return [rng.uniform(lo, hi, shape).astype("float32")]
    return gen


def _ints(shape=(3, 4), lo=0, hi=8, dtype="int64", n=1):
    def gen(rng):
        return [rng.integers(lo, hi, shape).astype(dtype)
                for _ in range(n)]
    return gen


def _bools(shape=(3, 4), n=1):
    def gen(rng):
        return [(rng.uniform(size=shape) > 0.5) for _ in range(n)]
    return gen


SPECS = []


def S(*a, **kw):
    SPECS.append(Spec(*a, **kw))


# --------------------------------------------------------------------------
# unary elementwise math
# --------------------------------------------------------------------------
import scipy.special as sps  # in the image via scipy (jax dependency)

S("abs", lambda x: paddle.abs(x), np.abs, _std())
S("acos", lambda x: paddle.acos(x), np.arccos, _unit())
S("acosh", lambda x: paddle.acosh(x), np.arccosh, _pos(lo=1.2, hi=3.0))
S("asin", lambda x: paddle.asin(x), np.arcsin, _unit())
S("asinh", lambda x: paddle.asinh(x), np.arcsinh, _std())
S("atan", lambda x: paddle.atan(x), np.arctan, _std())
S("atanh", lambda x: paddle.atanh(x), np.arctanh, _unit(lo=-0.8, hi=0.8))
S("ceil", lambda x: paddle.ceil(x), np.ceil, _std(scale=3), grad=None, grad_skip="zerograd")
S("cos", lambda x: paddle.cos(x), np.cos, _std())
S("cosh", lambda x: paddle.cosh(x), np.cosh, _std())
S("deg2rad", lambda x: paddle.deg2rad(x), np.deg2rad, _std(scale=90))
S("digamma", lambda x: paddle.digamma(x), sps.digamma, _pos(lo=0.5, hi=4))
S("erf", lambda x: paddle.erf(x), sps.erf, _std())
S("erfinv", lambda x: paddle.erfinv(x), sps.erfinv, _unit(lo=-0.7, hi=0.7))
S("exp", lambda x: paddle.exp(x), np.exp, _std())
S("expm1", lambda x: paddle.expm1(x), np.expm1, _std())
S("floor", lambda x: paddle.floor(x), np.floor, _std(scale=3), grad=None, grad_skip="zerograd")
S("frac", lambda x: paddle.frac(x), lambda x: x - np.trunc(x),
  _std(scale=3))
S("i0", lambda x: paddle.i0(x), sps.i0, _std())
S("i0e", lambda x: paddle.i0e(x), sps.i0e, _std())
S("i1", lambda x: paddle.i1(x), sps.i1, _std())
S("i1e", lambda x: paddle.i1e(x), sps.i1e, _std())
S("lgamma", lambda x: paddle.lgamma(x), sps.gammaln, _pos(lo=0.5, hi=4))
S("log", lambda x: paddle.log(x), np.log, _pos())
S("log10", lambda x: paddle.log10(x), np.log10, _pos())
S("log1p", lambda x: paddle.log1p(x), np.log1p, _pos(lo=-0.5, hi=2))
S("log2", lambda x: paddle.log2(x), np.log2, _pos())
S("logit", lambda x: paddle.logit(x), sps.logit, _unit(lo=0.1, hi=0.9))
S("neg", lambda x: paddle.neg(x), np.negative, _std())
S("rad2deg", lambda x: paddle.rad2deg(x), np.rad2deg, _std())
S("reciprocal", lambda x: paddle.reciprocal(x), np.reciprocal, _pos())
S("round", lambda x: paddle.round(x), np.round, _std(scale=3), grad=None, grad_skip="zerograd")
S("rsqrt", lambda x: paddle.rsqrt(x), lambda x: 1 / np.sqrt(x), _pos())
S("sigmoid", lambda x: F.sigmoid(x), sps.expit, _std())
S("sign", lambda x: paddle.sign(x), np.sign, _std(), grad=None, grad_skip="zerograd")
S("sgn", lambda x: paddle.sgn(x), np.sign, _std(), grad=None, grad_skip="zerograd")
S("sin", lambda x: paddle.sin(x), np.sin, _std())
S("sinh", lambda x: paddle.sinh(x), np.sinh, _std())
S("sqrt", lambda x: paddle.sqrt(x), np.sqrt, _pos())
S("square", lambda x: paddle.square(x), np.square, _std())
S("tan", lambda x: paddle.tan(x), np.tan, _unit())
S("tanh", lambda x: paddle.tanh(x), np.tanh, _std())
S("trunc", lambda x: paddle.trunc(x), np.trunc, _std(scale=3), grad=None, grad_skip="zerograd")
S("isnan", lambda x: paddle.isnan(x),
  np.isnan, lambda rng: [np.asarray([[1.0, np.nan, 2.0]], np.float32)],
  grad=None, grad_skip="boolean")
S("isinf", lambda x: paddle.isinf(x),
  np.isinf, lambda rng: [np.asarray([[1.0, np.inf, 2.0]], np.float32)],
  grad=None, grad_skip="boolean")
S("isfinite", lambda x: paddle.isfinite(x),
  np.isfinite,
  lambda rng: [np.asarray([[1.0, np.inf, np.nan]], np.float32)],
  grad=None, grad_skip="boolean")
S("angle", lambda x: paddle.angle(x), np.angle, _std(), grad=None, grad_skip="complex")
S("conj", lambda x: paddle.conj(x), np.conj, _std())
S("real", lambda x: paddle.real(x), np.real, _std(), grad=None, grad_skip="complex")
S("imag", lambda x: paddle.imag(x), np.imag, _std(), grad=None, grad_skip="complex")
S("nan_to_num", lambda x: paddle.nan_to_num(x), np.nan_to_num,
  lambda rng: [np.asarray([[1.0, np.nan, -np.inf, np.inf]], np.float32)],
  grad=None, grad_skip="nangrad")
S("clip", lambda x: paddle.clip(x, -0.5, 0.5),
  lambda x: np.clip(x, -0.5, 0.5), _std())
S("polygamma", lambda x: paddle.polygamma(x, 1),
  lambda x: sps.polygamma(1, x), _pos(lo=0.5, hi=3))
S("gammaln", lambda x: paddle.gammaln(x), sps.gammaln, _pos(lo=0.5, hi=4))
S("sinc", lambda x: paddle.sinc(x), np.sinc, _std())
S("softsign_f", lambda x: F.softsign(x), lambda x: x / (1 + np.abs(x)),
  _std())

# --------------------------------------------------------------------------
# binary elementwise
# --------------------------------------------------------------------------
S("add", lambda x, y: paddle.add(x, y), np.add, _std(n=2), grad=(0, 1))
S("subtract", lambda x, y: paddle.subtract(x, y), np.subtract, _std(n=2),
  grad=(0, 1))
S("multiply", lambda x, y: paddle.multiply(x, y), np.multiply, _std(n=2),
  grad=(0, 1))
S("divide", lambda x, y: paddle.divide(x, y),
  np.divide, lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
                          rng.uniform(0.5, 2, (3, 4)).astype("float32")],
  grad=(0, 1))
S("pow", lambda x, y: paddle.pow(x, y), np.power,
  lambda rng: [rng.uniform(0.3, 2, (3, 4)).astype("float32"),
               rng.uniform(0.5, 2, (3, 4)).astype("float32")],
  grad=(0, 1))
S("mod", lambda x, y: paddle.mod(x, y), np.mod,
  lambda rng: [rng.uniform(-3, 3, (3, 4)).astype("float32"),
               rng.uniform(0.5, 2, (3, 4)).astype("float32")], grad=None, grad_skip="discontinuous")
S("floor_divide", lambda x, y: paddle.floor_divide(x, y),
  np.floor_divide,
  lambda rng: [rng.uniform(-3, 3, (3, 4)).astype("float32"),
               rng.uniform(0.5, 2, (3, 4)).astype("float32")], grad=None, grad_skip="zerograd")
S("maximum", lambda x, y: paddle.maximum(x, y), np.maximum, _std(n=2),
  grad=(0, 1))
S("minimum", lambda x, y: paddle.minimum(x, y), np.minimum, _std(n=2),
  grad=(0, 1))
S("fmax", lambda x, y: paddle.fmax(x, y), np.fmax, _std(n=2))
S("fmin", lambda x, y: paddle.fmin(x, y), np.fmin, _std(n=2))
S("atan2", lambda x, y: paddle.atan2(x, y), np.arctan2,
  lambda rng: [rng.uniform(0.3, 2, (3, 4)).astype("float32"),
               rng.uniform(0.3, 2, (3, 4)).astype("float32")],
  grad=(0, 1))
S("hypot", lambda x, y: paddle.hypot(x, y), np.hypot, _std(n=2),
  grad=(0, 1))
S("logaddexp", lambda x, y: paddle.logaddexp(x, y), np.logaddexp,
  _std(n=2), grad=(0, 1))
S("heaviside", lambda x, y: paddle.heaviside(x, y), np.heaviside,
  _std(n=2), grad=None, grad_skip="zerograd")
S("copysign", lambda x, y: paddle.copysign(x, y), np.copysign, _std(n=2),
  grad=(0,))
S("nextafter", lambda x, y: paddle.nextafter(x, y), np.nextafter,
  _std(n=2), grad=None, grad_skip="nogradrule", dtypes=("float32",))
S("ldexp", lambda x, y: paddle.ldexp(x, y),
  lambda x, y: np.ldexp(x, y),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.integers(-2, 3, (3, 4)).astype("int32")], grad=None, grad_skip="nogradrule")
S("remainder", lambda x, y: paddle.remainder(x, y), np.remainder,
  lambda rng: [rng.uniform(-3, 3, (3, 4)).astype("float32"),
               rng.uniform(0.5, 2, (3, 4)).astype("float32")], grad=None, grad_skip="discontinuous")
S("gcd", lambda x, y: paddle.gcd(x, y), np.gcd, _ints(lo=1, hi=30, n=2),
  grad=None, grad_skip="integer")
S("lcm", lambda x, y: paddle.lcm(x, y), np.lcm, _ints(lo=1, hi=12, n=2),
  grad=None, grad_skip="integer")
S("inner_product", lambda x, y: paddle.inner(x, y), np.inner, _std(n=2),
  grad=(0, 1))
S("outer", lambda x, y: paddle.outer(x, y), np.outer,
  lambda rng: [rng.standard_normal(4).astype("float32"),
               rng.standard_normal(5).astype("float32")], grad=(0, 1))
S("cross", lambda x, y: paddle.cross(x, y, axis=-1),
  lambda x, y: np.cross(x, y),
  _std(shape=(4, 3), n=2), grad=(0, 1))
S("dot", lambda x, y: paddle.dot(x, y),
  lambda x, y: np.asarray(np.dot(x, y)),
  lambda rng: [rng.standard_normal(6).astype("float32"),
               rng.standard_normal(6).astype("float32")], grad=(0, 1))

# comparisons / logical / bitwise
S("equal", lambda x, y: paddle.equal(x, y), np.equal,
  _ints(lo=0, hi=3, n=2), grad=None, grad_skip="boolean")
S("not_equal", lambda x, y: paddle.not_equal(x, y), np.not_equal,
  _ints(lo=0, hi=3, n=2), grad=None, grad_skip="boolean")
S("less_than", lambda x, y: paddle.less_than(x, y), np.less, _std(n=2),
  grad=None, grad_skip="boolean")
S("less_equal", lambda x, y: paddle.less_equal(x, y), np.less_equal,
  _std(n=2), grad=None, grad_skip="boolean")
S("greater_than", lambda x, y: paddle.greater_than(x, y), np.greater,
  _std(n=2), grad=None, grad_skip="boolean")
S("greater_equal", lambda x, y: paddle.greater_equal(x, y),
  np.greater_equal, _std(n=2), grad=None, grad_skip="boolean")
S("logical_and", lambda x, y: paddle.logical_and(x, y), np.logical_and,
  _bools(n=2), grad=None, grad_skip="boolean")
S("logical_or", lambda x, y: paddle.logical_or(x, y), np.logical_or,
  _bools(n=2), grad=None, grad_skip="boolean")
S("logical_xor", lambda x, y: paddle.logical_xor(x, y), np.logical_xor,
  _bools(n=2), grad=None, grad_skip="boolean")
S("logical_not", lambda x: paddle.logical_not(x), np.logical_not,
  _bools(), grad=None, grad_skip="boolean")
S("bitwise_and", lambda x, y: paddle.bitwise_and(x, y), np.bitwise_and,
  _ints(n=2, dtype="int32"), grad=None, grad_skip="integer")
S("bitwise_or", lambda x, y: paddle.bitwise_or(x, y), np.bitwise_or,
  _ints(n=2, dtype="int32"), grad=None, grad_skip="integer")
S("bitwise_xor", lambda x, y: paddle.bitwise_xor(x, y), np.bitwise_xor,
  _ints(n=2, dtype="int32"), grad=None, grad_skip="integer")
S("bitwise_not", lambda x: paddle.bitwise_not(x), np.invert,
  _ints(dtype="int32"), grad=None, grad_skip="integer")
S("isclose", lambda x, y: paddle.isclose(x, y), np.isclose, _std(n=2),
  grad=None, grad_skip="boolean")
S("allclose", lambda x, y: paddle.allclose(x, y),
  lambda x, y: np.asarray(np.allclose(x, y)), _std(n=2), grad=None, grad_skip="boolean")
S("equal_all", lambda x, y: paddle.equal_all(x, y),
  lambda x, y: np.asarray(np.array_equal(x, y)),
  _ints(lo=0, hi=2, n=2), grad=None, grad_skip="boolean")

# --------------------------------------------------------------------------
# reductions
# --------------------------------------------------------------------------
S("sum", lambda x: paddle.sum(x, axis=1), lambda x: x.sum(1), _std())
S("mean", lambda x: paddle.mean(x, axis=0), lambda x: x.mean(0), _std())
S("max", lambda x: paddle.max(x, axis=1), lambda x: x.max(1), _std())
S("min", lambda x: paddle.min(x, axis=1), lambda x: x.min(1), _std())
S("prod", lambda x: paddle.prod(x, axis=1), lambda x: x.prod(1),
  _pos())
S("amax", lambda x: paddle.amax(x, axis=1), lambda x: x.max(1), _std(),
  grad=(0,))
S("amin", lambda x: paddle.amin(x, axis=1), lambda x: x.min(1), _std(),
  grad=(0,))
S("all", lambda x: paddle.all(x, axis=1), lambda x: x.all(1), _bools(),
  grad=None, grad_skip="boolean")
S("any", lambda x: paddle.any(x, axis=1), lambda x: x.any(1), _bools(),
  grad=None, grad_skip="boolean")
S("logsumexp", lambda x: paddle.logsumexp(x, axis=1),
  lambda x: np.log(np.exp(x).sum(1)), _std())
S("std", lambda x: paddle.std(x, axis=1),
  lambda x: x.std(1, ddof=1), _std())
S("var", lambda x: paddle.var(x, axis=1),
  lambda x: x.var(1, ddof=1), _std())
S("median", lambda x: paddle.median(x, axis=1),
  lambda x: np.median(x, 1), _std(shape=(3, 5)), grad=(0,))
S("nanmean", lambda x: paddle.nanmean(x, axis=0),
  lambda x: np.nanmean(x, 0),
  lambda rng: [np.asarray([[1.0, np.nan], [2.0, 3.0]], np.float32)],
  grad=(0,))
S("nansum", lambda x: paddle.nansum(x, axis=0),
  lambda x: np.nansum(x, 0),
  lambda rng: [np.asarray([[1.0, np.nan], [2.0, 3.0]], np.float32)],
  grad=(0,))
S("count_nonzero", lambda x: paddle.count_nonzero(x, axis=1),
  lambda x: np.count_nonzero(x, 1),
  lambda rng: [np.asarray([[0.0, 1.0, 2.0], [0.0, 0.0, 3.0]],
                          np.float32)], grad=None, grad_skip="integer")
S("cumsum", lambda x: paddle.cumsum(x, axis=1),
  lambda x: np.cumsum(x, 1), _std())
S("cumprod", lambda x: paddle.cumprod(x, dim=1),
  lambda x: np.cumprod(x, 1), _pos())
S("cummax", lambda x: paddle.cummax(x, axis=1)[0],
  lambda x: np.maximum.accumulate(x, 1), _std(), grad=(0,))
S("cummax_idx", lambda x: paddle.cummax(x, axis=1)[1],
  lambda x: np.asarray([[int(np.argmax(r[:j + 1])) for j in range(len(r))]
                        for r in x]), _std(), grad=None, grad_skip="indices")
S("cummin_idx", lambda x: paddle.cummin(x, axis=1)[1],
  lambda x: np.asarray([[int(np.argmin(r[:j + 1])) for j in range(len(r))]
                        for r in x]), _std(), grad=None, grad_skip="indices")
S("cummin", lambda x: paddle.cummin(x, axis=1)[0],
  lambda x: np.minimum.accumulate(x, 1), _std(), grad=(0,))
S("logcumsumexp", lambda x: paddle.logcumsumexp(x, axis=1),
  lambda x: np.log(np.cumsum(np.exp(x), 1)), _std())
S("quantile", lambda x: paddle.quantile(x, 0.5, axis=1),
  lambda x: np.quantile(x, 0.5, axis=1), _std(shape=(3, 5)), grad=(0,))
S("kthvalue", lambda x: paddle.kthvalue(x, 2, axis=1)[0],
  lambda x: np.sort(x, 1)[:, 1], _std(shape=(3, 5)), grad=(0,))
S("mode", lambda x: paddle.mode(x, axis=1)[0],
  lambda x: np.asarray([np.bincount(r).argmax() for r in x]),
  _ints(shape=(3, 6), lo=0, hi=3), grad=None, grad_skip="integer")
S("trace_op", lambda x: paddle.trace(x), lambda x: np.asarray(np.trace(x)),
  _std(shape=(4, 4)))
S("diagonal", lambda x: paddle.diagonal(x),
  lambda x: np.diagonal(x), _std(shape=(4, 4)))
S("norm_fro", lambda x: paddle.linalg.norm(x),
  lambda x: np.asarray(np.linalg.norm(x)), _std())
S("norm_l1", lambda x: paddle.linalg.norm(x, p=1, axis=1),
  lambda x: np.abs(x).sum(1), _std())

# --------------------------------------------------------------------------
# manipulation
# --------------------------------------------------------------------------
S("reshape", lambda x: paddle.reshape(x, [4, 3]),
  lambda x: x.reshape(4, 3), _std())
S("transpose", lambda x: paddle.transpose(x, [1, 0]),
  lambda x: x.T, _std())
S("concat", lambda x, y: paddle.concat([x, y], axis=1),
  lambda x, y: np.concatenate([x, y], 1), _std(n=2), grad=(0, 1))
S("stack", lambda x, y: paddle.stack([x, y], axis=0),
  lambda x, y: np.stack([x, y], 0), _std(n=2), grad=(0, 1))
S("split", lambda x: paddle.split(x, 2, axis=1),
  lambda x: np.split(x, 2, 1), _std(shape=(3, 6)))
S("chunk", lambda x: paddle.chunk(x, 2, axis=1),
  lambda x: np.split(x, 2, 1), _std(shape=(3, 6)))
S("unstack", lambda x: paddle.unstack(x, axis=0),
  lambda x: [x[i] for i in range(x.shape[0])], _std())
S("squeeze", lambda x: paddle.squeeze(x, axis=1),
  lambda x: x.squeeze(1), _std(shape=(3, 1, 4)))
S("unsqueeze", lambda x: paddle.unsqueeze(x, axis=1),
  lambda x: x[:, None], _std())
S("flip", lambda x: paddle.flip(x, axis=[1]),
  lambda x: np.flip(x, 1), _std())
S("roll", lambda x: paddle.roll(x, 2, axis=1),
  lambda x: np.roll(x, 2, 1), _std())
S("tile", lambda x: paddle.tile(x, [2, 3]),
  lambda x: np.tile(x, (2, 3)), _std())
S("expand", lambda x: paddle.expand(x, [3, 4]),
  lambda x: np.broadcast_to(x, (3, 4)), _std(shape=(1, 4)))
S("broadcast_to", lambda x: paddle.broadcast_to(x, [3, 4]),
  lambda x: np.broadcast_to(x, (3, 4)), _std(shape=(1, 4)))
S("flatten", lambda x: paddle.flatten(x),
  lambda x: x.reshape(-1), _std())
S("rot90", lambda x: paddle.rot90(x),
  lambda x: np.rot90(x), _std())
S("tril", lambda x: paddle.tril(x), np.tril, _std(shape=(4, 4)))
S("triu", lambda x: paddle.triu(x), np.triu, _std(shape=(4, 4)))
S("kron", lambda x, y: paddle.kron(x, y), np.kron,
  _std(shape=(2, 2), n=2), grad=(0, 1))
S("diag", lambda x: paddle.diag(x), np.diag,
  lambda rng: [rng.standard_normal(4).astype("float32")])
S("diagflat", lambda x: paddle.diagflat(x), np.diagflat, _std())
S("unbind", lambda x: paddle.unbind(x, axis=0),
  lambda x: [x[i] for i in range(x.shape[0])], _std())
S("pad_constant",
  lambda x: F.pad(x, [1, 1], mode="constant", value=0.0),
  lambda x: np.pad(x, ((0, 0), (1, 1))), _std())
S("gather", lambda x, i: paddle.gather(x, i, axis=0),
  lambda x, i: x[i],
  lambda rng: [rng.standard_normal((5, 3)).astype("float32"),
               rng.integers(0, 5, (4,)).astype("int64")])
S("index_select", lambda x, i: paddle.index_select(x, i, axis=0),
  lambda x, i: x[i],
  lambda rng: [rng.standard_normal((5, 3)).astype("float32"),
               rng.integers(0, 5, (4,)).astype("int64")])
S("take_along_axis", lambda x, i: paddle.take_along_axis(x, i, axis=1),
  lambda x, i: np.take_along_axis(x, i, 1),
  lambda rng: [rng.standard_normal((3, 5)).astype("float32"),
               rng.integers(0, 5, (3, 2)).astype("int64")])
S("gather_nd", lambda x, i: paddle.gather_nd(x, i),
  lambda x, i: x[tuple(i.T)],
  lambda rng: [rng.standard_normal((4, 3)).astype("float32"),
               rng.integers(0, 3, (5, 2)).astype("int64")])
S("masked_select", lambda x, m: paddle.masked_select(x, m),
  lambda x, m: x[m],
  lambda rng: [np.arange(12, dtype=np.float32).reshape(3, 4),
               (np.arange(12).reshape(3, 4) % 2 == 0)], grad=None,
  grad_skip="dynshape")
S("where", lambda c, x, y: paddle.where(c, x, y), np.where,
  lambda rng: [(rng.uniform(size=(3, 4)) > 0.5),
               rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((3, 4)).astype("float32")],
  grad=(1, 2))
S("repeat_interleave",
  lambda x: paddle.repeat_interleave(x, 2, axis=1),
  lambda x: np.repeat(x, 2, 1), _std())
S("meshgrid", lambda x, y: paddle.meshgrid(x, y),
  lambda x, y: np.meshgrid(x, y, indexing="ij"),
  lambda rng: [rng.standard_normal(3).astype("float32"),
               rng.standard_normal(4).astype("float32")], grad=(0, 1))
S("one_hot", lambda x: F.one_hot(x, 5),
  lambda x: np.eye(5, dtype=np.float32)[x],
  _ints(shape=(4,), lo=0, hi=5), grad=None, grad_skip="integer")
S("as_strided_t", lambda x: paddle.t(x), lambda x: x.T, _std())
S("moveaxis", lambda x: paddle.moveaxis(x, 0, 1),
  lambda x: np.moveaxis(x, 0, 1), _std())
S("swapaxes", lambda x: paddle.transpose(x, [1, 0]),
  lambda x: np.swapaxes(x, 0, 1), _std())
S("dstack", lambda x, y: paddle.dstack([x, y]),
  lambda x, y: np.dstack([x, y]), _std(n=2), grad=(0, 1))
S("hstack", lambda x, y: paddle.hstack([x, y]),
  lambda x, y: np.hstack([x, y]), _std(n=2), grad=(0, 1))
S("vstack", lambda x, y: paddle.vstack([x, y]),
  lambda x, y: np.vstack([x, y]), _std(n=2), grad=(0, 1))
S("atleast_2d", lambda x: paddle.atleast_2d(x),
  lambda x: np.atleast_2d(x),
  lambda rng: [rng.standard_normal(4).astype("float32")], grad=(0,))
S("crop", lambda x: paddle.crop(x, shape=[2, 2], offsets=[1, 1]),
  lambda x: x[1:3, 1:3], _std(shape=(4, 4)))

# --------------------------------------------------------------------------
# creation (output-only: compare values; no grads)
# --------------------------------------------------------------------------
S("zeros_like", lambda x: paddle.zeros_like(x), np.zeros_like, _std(),
  grad=None, grad_skip="zerograd")
S("ones_like", lambda x: paddle.ones_like(x), np.ones_like, _std(),
  grad=None, grad_skip="zerograd")
S("full_like", lambda x: paddle.full_like(x, 2.5),
  lambda x: np.full_like(x, 2.5), _std(), grad=None, grad_skip="zerograd")
S("arange", lambda x: paddle.arange(0, 10, 2, dtype="float32") + 0 * x,
  lambda x: np.arange(0, 10, 2, dtype=np.float32) + 0 * x,
  lambda rng: [np.zeros(5, np.float32)], grad=None, grad_skip="constant")
S("linspace", lambda x: paddle.linspace(0, 1, 5) + 0 * x,
  lambda x: np.linspace(0, 1, 5, dtype=np.float32) + 0 * x,
  lambda rng: [np.zeros(5, np.float32)], grad=None, grad_skip="constant")
S("logspace", lambda x: paddle.logspace(0, 2, 5) + 0 * x,
  lambda x: np.logspace(0, 2, 5, dtype=np.float32) + 0 * x,
  lambda rng: [np.zeros(5, np.float32)], grad=None, grad_skip="constant",
  tols={"float32": dict(rtol=1e-4, atol=1e-4)})
S("eye", lambda x: paddle.eye(4) + 0 * x,
  lambda x: np.eye(4, dtype=np.float32) + 0 * x,
  lambda rng: [np.zeros((4, 4), np.float32)], grad=None, grad_skip="constant")
S("diag_embed", lambda x: paddle.diag_embed(x),
  lambda x: np.stack([np.diag(r) for r in x]), _std(shape=(3, 4)),
  grad=(0,))

# --------------------------------------------------------------------------
# search / sort
# --------------------------------------------------------------------------
S("argmax", lambda x: paddle.argmax(x, axis=1),
  lambda x: x.argmax(1), _std(), grad=None, grad_skip="indices")
S("argmin", lambda x: paddle.argmin(x, axis=1),
  lambda x: x.argmin(1), _std(), grad=None, grad_skip="indices")
S("argsort", lambda x: paddle.argsort(x, axis=1),
  lambda x: np.argsort(x, 1, kind="stable"), _std(), grad=None, grad_skip="indices")
S("sort", lambda x: paddle.sort(x, axis=1),
  lambda x: np.sort(x, 1), _std())
S("topk", lambda x: paddle.topk(x, 3, axis=1)[0],
  lambda x: -np.sort(-x, 1)[:, :3], _std(shape=(3, 6)))
S("searchsorted", lambda s, v: paddle.searchsorted(s, v),
  lambda s, v: np.stack([np.searchsorted(s[i], v[i])
                         for i in range(s.shape[0])]),
  lambda rng: [np.sort(rng.standard_normal((2, 6)).astype("float32"), 1),
               rng.standard_normal((2, 3)).astype("float32")], grad=None, grad_skip="indices")
S("bucketize", lambda x, e: paddle.bucketize(x, e),
  lambda x, e: np.searchsorted(e, x),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               np.asarray([-1.0, 0.0, 1.0], np.float32)], grad=None, grad_skip="indices")
S("nonzero", lambda x: paddle.nonzero(x),
  lambda x: np.stack(np.nonzero(x), 1),
  lambda rng: [np.asarray([[0.0, 1.0], [2.0, 0.0]], np.float32)],
  grad=None, grad_skip="indices")
S("unique", lambda x: paddle.unique(x),
  lambda x: np.unique(x), _ints(shape=(8,), lo=0, hi=4), grad=None, grad_skip="indices")
S("unique_consecutive", lambda x: paddle.unique_consecutive(x),
  lambda x: np.asarray([k for k, g in __import__("itertools")
                        .groupby(x.tolist())]),
  lambda rng: [np.asarray([1, 1, 2, 2, 3, 1, 1], np.int64)], grad=None, grad_skip="indices")
S("index_sample", lambda x, i: paddle.index_sample(x, i),
  lambda x, i: np.take_along_axis(x, i, 1),
  lambda rng: [rng.standard_normal((3, 5)).astype("float32"),
               rng.integers(0, 5, (3, 2)).astype("int64")], grad=(0,))

# --------------------------------------------------------------------------
# linalg
# --------------------------------------------------------------------------
S("matmul", lambda x, y: paddle.matmul(x, y), lambda x, y: x @ y,
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((4, 5)).astype("float32")],
  grad=(0, 1))
S("bmm", lambda x, y: paddle.bmm(x, y), lambda x, y: x @ y,
  lambda rng: [rng.standard_normal((2, 3, 4)).astype("float32"),
               rng.standard_normal((2, 4, 5)).astype("float32")],
  grad=(0, 1))
S("mv", lambda x, y: paddle.mv(x, y), lambda x, y: x @ y,
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal(4).astype("float32")], grad=(0, 1))
S("addmm", lambda a, x, y: paddle.addmm(a, x, y),
  lambda a, x, y: a + x @ y,
  lambda rng: [rng.standard_normal((3, 5)).astype("float32"),
               rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((4, 5)).astype("float32")],
  grad=(0, 1, 2))
S("cholesky", lambda x: paddle.linalg.cholesky(x),
  lambda x: np.linalg.cholesky(x),
  lambda rng: [(lambda a: (a @ a.T + 3 * np.eye(3)).astype("float32"))(
      rng.standard_normal((3, 3)))], dtypes=("float32",))
S("inv", lambda x: paddle.linalg.inv(x),
  lambda x: np.linalg.inv(x),
  lambda rng: [(rng.standard_normal((3, 3))
                + 3 * np.eye(3)).astype("float32")], dtypes=("float32",))
S("pinv", lambda x: paddle.linalg.pinv(x),
  lambda x: np.linalg.pinv(x),
  lambda rng: [rng.standard_normal((4, 3)).astype("float32")],
  dtypes=("float32",), grad=(0,),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("det", lambda x: paddle.linalg.det(x),
  lambda x: np.asarray(np.linalg.det(x)),
  lambda rng: [(rng.standard_normal((3, 3))
                + 2 * np.eye(3)).astype("float32")], dtypes=("float32",))
S("slogdet", lambda x: paddle.linalg.slogdet(x),
  lambda x: [np.asarray(v) for v in np.linalg.slogdet(x)],
  lambda rng: [(rng.standard_normal((3, 3))
                + 3 * np.eye(3)).astype("float32")], dtypes=("float32",),
  grad=(0,))
S("solve", lambda a, b: paddle.linalg.solve(a, b),
  lambda a, b: np.linalg.solve(a, b),
  lambda rng: [(rng.standard_normal((3, 3))
                + 3 * np.eye(3)).astype("float32"),
               rng.standard_normal((3, 2)).astype("float32")],
  dtypes=("float32",), grad=(0, 1),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("triangular_solve",
  lambda a, b: paddle.linalg.triangular_solve(a, b, upper=False),
  lambda a, b: np.linalg.solve(np.tril(a), b),
  lambda rng: [(np.tril(rng.standard_normal((3, 3)))
                + 2 * np.eye(3)).astype("float32"),
               rng.standard_normal((3, 2)).astype("float32")],
  dtypes=("float32",), grad=(0, 1))
S("matrix_power", lambda x: paddle.linalg.matrix_power(x, 3),
  lambda x: np.linalg.matrix_power(x, 3),
  _std(shape=(3, 3), scale=0.5), dtypes=("float32",),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("matrix_rank", lambda x: paddle.linalg.matrix_rank(x),
  lambda x: np.asarray(np.linalg.matrix_rank(x)),
  lambda rng: [rng.standard_normal((4, 3)).astype("float32")],
  dtypes=("float32",), grad=None, grad_skip="integer")
S("qr_r", lambda x: paddle.abs(paddle.linalg.qr(x)[1]),
  lambda x: np.abs(np.linalg.qr(x)[1]),
  lambda rng: [rng.standard_normal((4, 3)).astype("float32")],
  dtypes=("float32",), grad=None, grad_skip="unstable",
  tols={"float32": dict(rtol=1e-4, atol=1e-4)})
S("svdvals", lambda x: paddle.linalg.svd(x)[1],
  lambda x: np.linalg.svd(x)[1],
  lambda rng: [rng.standard_normal((4, 3)).astype("float32")],
  dtypes=("float32",), grad=(0,),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("eigvalsh", lambda x: paddle.linalg.eigvalsh(x),
  lambda x: np.linalg.eigvalsh(x),
  lambda rng: [(lambda a: ((a + a.T) / 2).astype("float32"))(
      rng.standard_normal((3, 3)))], dtypes=("float32",), grad=None, grad_skip="unstable",
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("lstsq", lambda a, b: paddle.linalg.lstsq(a, b)[0],
  lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0],
  lambda rng: [rng.standard_normal((5, 3)).astype("float32"),
               rng.standard_normal((5, 2)).astype("float32")],
  dtypes=("float32",), grad=None, grad_skip="unstable",
  tols={"float32": dict(rtol=1e-3, atol=1e-4)})
S("multi_dot", lambda x, y, z: paddle.linalg.multi_dot([x, y, z]),
  lambda x, y, z: x @ y @ z,
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((4, 2)).astype("float32"),
               rng.standard_normal((2, 5)).astype("float32")],
  grad=(0, 1, 2))
S("histogram", lambda x: paddle.histogram(x, bins=4, min=-2.0, max=2.0),
  lambda x: np.histogram(x, bins=4, range=(-2, 2))[0],
  _std(), grad=None, grad_skip="counting")
S("bincount", lambda x: paddle.bincount(x, minlength=5),
  lambda x: np.bincount(x, minlength=5),
  _ints(shape=(10,), lo=0, hi=5), grad=None, grad_skip="counting")

# --------------------------------------------------------------------------
# activations & nn.functional
# --------------------------------------------------------------------------
S("relu", lambda x: F.relu(x), lambda x: np.maximum(x, 0), _std())
S("relu6", lambda x: F.relu6(x), lambda x: np.clip(x, 0, 6),
  _std(scale=4))
S("leaky_relu", lambda x: F.leaky_relu(x, 0.1),
  lambda x: np.where(x > 0, x, 0.1 * x), _std())
S("elu", lambda x: F.elu(x, 1.0),
  lambda x: np.where(x > 0, x, np.expm1(x)), _std())
S("celu", lambda x: F.celu(x, 1.5),
  lambda x: np.maximum(x, 0) + np.minimum(0, 1.5 * np.expm1(x / 1.5)),
  _std())
S("selu", lambda x: F.selu(x),
  lambda x: 1.0507009873554805 * np.where(
      x > 0, x, 1.6732632423543772 * np.expm1(x)), _std())
S("gelu_tanh", lambda x: F.gelu(x, approximate=True),
  lambda x: 0.5 * x * (1 + np.tanh(
      np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3))), _std())
S("gelu_erf", lambda x: F.gelu(x),
  lambda x: x * 0.5 * (1 + sps.erf(x / np.sqrt(2))), _std())
S("silu", lambda x: F.silu(x), lambda x: x * sps.expit(x), _std())
S("mish", lambda x: F.mish(x),
  lambda x: x * np.tanh(np.log1p(np.exp(x))), _std())
S("softplus", lambda x: F.softplus(x),
  lambda x: np.log1p(np.exp(x)), _std())
S("softshrink", lambda x: F.softshrink(x, 0.5),
  lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0)),
  _std())
S("hardshrink", lambda x: F.hardshrink(x, 0.5),
  lambda x: np.where(np.abs(x) > 0.5, x, 0), _std())
S("tanhshrink", lambda x: F.tanhshrink(x),
  lambda x: x - np.tanh(x), _std())
S("hardsigmoid", lambda x: F.hardsigmoid(x),
  lambda x: np.clip(x / 6 + 0.5, 0, 1), _std(scale=4))
S("hardswish", lambda x: F.hardswish(x),
  lambda x: x * np.clip(x + 3, 0, 6) / 6, _std(scale=3))
S("hardtanh", lambda x: F.hardtanh(x),
  lambda x: np.clip(x, -1, 1), _std(scale=2))
S("swish", lambda x: F.swish(x), lambda x: x * sps.expit(x), _std())
S("glu", lambda x: F.glu(x, axis=-1),
  lambda x: x[..., :2] * sps.expit(x[..., 2:]), _std(shape=(3, 4)))
S("softmax", lambda x: F.softmax(x, axis=-1),
  lambda x: sps.softmax(x, -1), _std())
S("log_softmax", lambda x: F.log_softmax(x, axis=-1),
  lambda x: sps.log_softmax(x, -1), _std(),
  # fp32 fd probe: the summed-output quantization floor is ~1e-3 in
  # grad units here; default atol sat just below it (flaky per jax
  # version's rounding)
  grad_kw=dict(atol=2e-3))
S("prelu", lambda x: F.prelu(x, paddle.to_tensor(
    np.asarray([0.25], np.float32))),
  lambda x: np.where(x > 0, x, 0.25 * x), _std())
S("rrelu_eval",
  lambda x: F.rrelu(x, lower=0.2, upper=0.2, training=False),
  lambda x: np.where(x > 0, x, 0.2 * x), _std())
S("thresholded_relu", lambda x: F.thresholded_relu(x, 1.0),
  lambda x: np.where(x > 1.0, x, 0), _std(scale=2))
S("log_sigmoid", lambda x: F.log_sigmoid(x),
  lambda x: np.log(sps.expit(x)), _std())
S("maxout", lambda x: F.maxout(x, groups=2, axis=1),
  lambda x: x.reshape(2, 2, 2, 3, 4).max(2).reshape(2, 2, 3, 4),
  _std(shape=(2, 4, 3, 4)))
S("stanh", lambda x: paddle.stanh(x),
  lambda x: 1.7159 * np.tanh(0.67 * x), _std())

# losses / distance
S("mse_loss", lambda x, y: F.mse_loss(x, y),
  lambda x, y: np.asarray(((x - y) ** 2).mean()), _std(n=2),
  grad=(0, 1))
S("l1_loss", lambda x, y: F.l1_loss(x, y),
  lambda x, y: np.asarray(np.abs(x - y).mean()), _std(n=2))
S("smooth_l1", lambda x, y: F.smooth_l1_loss(x, y),
  lambda x, y: np.asarray(np.where(
      np.abs(x - y) < 1, 0.5 * (x - y) ** 2,
      np.abs(x - y) - 0.5).mean()), _std(n=2))
S("kl_div", lambda x, y: F.kl_div(x, y, reduction="sum"),
  lambda x, y: np.asarray((y * (np.log(y) - x)).sum()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               sps.softmax(rng.standard_normal((3, 4)), -1)
               .astype("float32")], grad=(0,))
S("bce_with_logits",
  lambda x, y: F.binary_cross_entropy_with_logits(x, y),
  lambda x, y: np.asarray(
      (np.maximum(x, 0) - x * y + np.log1p(np.exp(-np.abs(x)))).mean()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               (rng.uniform(size=(3, 4)) > 0.5).astype("float32")],
  grad=(0,))
S("bce", lambda x, y: F.binary_cross_entropy(x, y),
  lambda x, y: np.asarray(
      -(y * np.log(x) + (1 - y) * np.log(1 - x)).mean()),
  lambda rng: [rng.uniform(0.1, 0.9, (3, 4)).astype("float32"),
               (rng.uniform(size=(3, 4)) > 0.5).astype("float32")],
  grad=(0,))
S("nll_loss", lambda x, y: F.nll_loss(x, y),
  lambda x, y: np.asarray(-x[np.arange(len(y)), y].mean()),
  lambda rng: [sps.log_softmax(
      rng.standard_normal((4, 5)), -1).astype("float32"),
      rng.integers(0, 5, (4,)).astype("int64")], grad=(0,))
S("cross_entropy_idx", lambda x, y: F.cross_entropy(x, y),
  lambda x, y: np.asarray(
      -sps.log_softmax(x, -1)[np.arange(len(y)), y].mean()),
  lambda rng: [rng.standard_normal((4, 5)).astype("float32"),
               rng.integers(0, 5, (4,)).astype("int64")], grad=(0,))
S("cosine_similarity", lambda x, y: F.cosine_similarity(x, y),
  lambda x, y: (x * y).sum(-1)
  / (np.linalg.norm(x, axis=-1) * np.linalg.norm(y, axis=-1)),
  _std(n=2), grad=(0, 1))
S("pairwise_distance",
  lambda x, y: paddle.nn.PairwiseDistance()(x, y),
  lambda x, y: np.linalg.norm(x - y + 1e-6, axis=-1), _std(n=2),
  grad=(0, 1))
S("hinge_embedding",
  lambda x, y: F.hinge_embedding_loss(x, y),
  lambda x, y: np.asarray(np.where(
      y == 1, x, np.maximum(0, 1.0 - x)).mean()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               np.where(rng.uniform(size=(3, 4)) > 0.5, 1.0, -1.0)
               .astype("float32")], grad=(0,))
S("triplet_margin",
  lambda a, p, n: F.triplet_margin_loss(a, p, n),
  lambda a, p, n: np.asarray(np.maximum(
      np.linalg.norm(a - p, axis=-1)
      - np.linalg.norm(a - n, axis=-1) + 1.0, 0).mean()),
  _std(n=3), grad=(0, 1, 2))
S("pdist", lambda x: paddle.pdist(x),
  lambda x: np.asarray([np.linalg.norm(x[i] - x[j])
                        for i in range(len(x))
                        for j in range(i + 1, len(x))]),
  _std(shape=(4, 3)), dtypes=("float32",), grad=(0,))
S("cdist", lambda x, y: paddle.cdist(x, y),
  lambda x, y: np.linalg.norm(x[:, None] - y[None], axis=-1),
  _std(shape=(3, 4), n=2), grad=(0, 1),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})

# norm / pooling / conv
S("layer_norm",
  lambda x: F.layer_norm(x, x.shape[-1:]),
  lambda x: (x - x.mean(-1, keepdims=True))
  / np.sqrt(x.var(-1, keepdims=True) + 1e-5), _std())
S("rms_norm_f", lambda x: F.rms_norm(x),
  lambda x: x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6),
  _std(), grad=(0,))
S("normalize_l2", lambda x: F.normalize(x, axis=-1),
  lambda x: x / np.maximum(np.linalg.norm(x, axis=-1, keepdims=True),
                           1e-12), _std())
S("max_pool2d", lambda x: F.max_pool2d(x, 2),
  lambda x: x.reshape(1, 2, 2, 2, 2, 2).max((3, 5)),
  _std(shape=(1, 2, 4, 4)))
S("avg_pool2d", lambda x: F.avg_pool2d(x, 2),
  lambda x: x.reshape(1, 2, 2, 2, 2, 2).mean((3, 5)),
  _std(shape=(1, 2, 4, 4)))
S("adaptive_avg_pool2d", lambda x: F.adaptive_avg_pool2d(x, 1),
  lambda x: x.mean((2, 3), keepdims=True), _std(shape=(1, 2, 4, 4)))
S("adaptive_max_pool2d", lambda x: F.adaptive_max_pool2d(x, 1),
  lambda x: x.max(3, keepdims=True).max(2, keepdims=True),
  _std(shape=(1, 2, 4, 4)))
S("embedding", lambda w, i: F.embedding(i, w),
  lambda w, i: w[i],
  lambda rng: [rng.standard_normal((6, 3)).astype("float32"),
               rng.integers(0, 6, (2, 4)).astype("int64")], grad=(0,))
S("pixel_shuffle", lambda x: F.pixel_shuffle(x, 2),
  lambda x: x.reshape(1, 1, 2, 2, 2, 2).transpose(0, 1, 4, 2, 5, 3)
  .reshape(1, 1, 4, 4), _std(shape=(1, 4, 2, 2)))
S("pixel_unshuffle", lambda x: F.pixel_unshuffle(x, 2),
  lambda x: x.reshape(1, 1, 2, 2, 2, 2).transpose(0, 1, 3, 5, 2, 4)
  .reshape(1, 4, 2, 2), _std(shape=(1, 1, 4, 4)))
S("channel_shuffle", lambda x: F.channel_shuffle(x, 2),
  lambda x: x.reshape(1, 2, 2, 2, 2).transpose(0, 2, 1, 3, 4)
  .reshape(1, 4, 2, 2), _std(shape=(1, 4, 2, 2)))
S("interp_nearest",
  lambda x: F.interpolate(x, scale_factor=2, mode="nearest"),
  lambda x: x.repeat(2, 2).repeat(2, 3), _std(shape=(1, 2, 3, 3)))
S("unfold", lambda x: F.unfold(x, 2),
  lambda x: np.stack([x[0, :, i:i + 2, j:j + 2].reshape(-1)
                      for i in range(3) for j in range(3)], -1)[None],
  _std(shape=(1, 2, 4, 4)))
S("dropout_eval", lambda x: F.dropout(x, 0.5, training=False),
  lambda x: x, _std())
S("temporal_shift", lambda x: F.temporal_shift(x, 2, 0.25),
  lambda x: np.concatenate([
      np.concatenate([np.zeros((1, 1, 1, 2, 2), np.float32),
                      x.reshape(1, 2, 4, 2, 2)[:, :-1, :1]], 1),
      np.concatenate([x.reshape(1, 2, 4, 2, 2)[:, 1:, 1:2],
                      np.zeros((1, 1, 1, 2, 2), np.float32)], 1),
      x.reshape(1, 2, 4, 2, 2)[:, :, 2:]], 2).reshape(2, 4, 2, 2),
  _std(shape=(2, 4, 2, 2)), grad=(0,))



# --------------------------------------------------------------------------
# batch 2 (r5): scatter/index family, windows, second-tier losses, linalg
# tails — pushes the sweep past 300 named ops
# --------------------------------------------------------------------------
S("put_along_axis",
  lambda x, i, v: paddle.put_along_axis(x, i, v, axis=1),
  lambda x, i, v: (lambda y: (np.put_along_axis(y, i, v, 1), y)[1])(
      x.copy()),
  lambda rng: [rng.standard_normal((3, 5)).astype("float32"),
               rng.integers(0, 5, (3, 2)).astype("int64"),
               rng.standard_normal((3, 2)).astype("float32")],
  grad=(0,))
S("scatter_overwrite",
  lambda x, i, u: paddle.scatter(x, i, u),
  lambda x, i, u: (lambda y: (y.__setitem__(i, u), y)[1])(x.copy()),
  lambda rng: [rng.standard_normal((5, 3)).astype("float32"),
               np.asarray([0, 2, 4], np.int64),
               rng.standard_normal((3, 3)).astype("float32")],
  grad=(0, 2))
S("scatter_nd_add",
  lambda x, i, u: paddle.scatter_nd_add(x, i, u),
  lambda x, i, u: (lambda y: (np.add.at(y, tuple(i.T), u), y)[1])(
      x.copy()),
  lambda rng: [rng.standard_normal((5, 3)).astype("float32"),
               rng.integers(0, 5, (4, 1)).astype("int64"),
               rng.standard_normal((4, 3)).astype("float32")],
  grad=(0, 2))
S("index_add",
  lambda x, i, v: paddle.index_add(x, i, 0, v),
  lambda x, i, v: (lambda y: (np.add.at(y, i, v), y)[1])(x.copy()),
  lambda rng: [rng.standard_normal((5, 3)).astype("float32"),
               np.asarray([0, 2, 2], np.int64),
               rng.standard_normal((3, 3)).astype("float32")],
  grad=(0, 2))
S("masked_fill",
  lambda x, m: paddle.masked_fill(x, m, 7.5),
  lambda x, m: np.where(m, 7.5, x), 
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.uniform(size=(3, 4)) > 0.5], grad=(0,))
S("masked_scatter",
  lambda x, m, v: paddle.masked_scatter(x, m, v),
  lambda x, m, v: (lambda y: (y.__setitem__(m, v[:m.sum()]), y)[1])(
      x.copy()),
  lambda rng: [np.zeros((3, 4), np.float32),
               np.tile(np.asarray([True, False, True, False]), (3, 1)),
               np.arange(12, dtype=np.float32)], grad=(0, 2))
S("index_fill",
  lambda x, i: paddle.index_fill(x, i, 0, -1.0),
  lambda x, i: (lambda y: (y.__setitem__(i, -1.0), y)[1])(x.copy()),
  lambda rng: [rng.standard_normal((5, 3)).astype("float32"),
               np.asarray([1, 3], np.int64)], grad=(0,))
S("take", lambda x, i: paddle.take(x, i),
  lambda x, i: x.reshape(-1)[i],
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.integers(0, 12, (5,)).astype("int64")], grad=(0,))
S("renorm", lambda x: paddle.renorm(x, 2.0, 0, 1.0),
  lambda x: x * np.minimum(
      1.0, 1.0 / np.maximum(
          np.sqrt((x ** 2).sum(axis=(1,), keepdims=True)), 1e-7)),
  _std(shape=(3, 4)), grad=(0,),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("diff", lambda x: paddle.diff(x, axis=1),
  lambda x: np.diff(x, axis=1), _std())
S("trapezoid", lambda y: paddle.trapezoid(y, dx=0.5, axis=1),
  lambda y: np.trapezoid(y, dx=0.5, axis=1)
  if hasattr(np, "trapezoid") else np.trapz(y, dx=0.5, axis=1), _std())
S("cumulative_trapezoid",
  lambda y: paddle.cumulative_trapezoid(y, dx=1.0, axis=1),
  lambda y: (lambda c: c)(np.cumsum(
      (y[:, 1:] + y[:, :-1]) / 2.0, axis=1)), _std())
S("vander", lambda x: paddle.vander(x, 4),
  lambda x: np.vander(x, 4, increasing=False),
  lambda rng: [rng.standard_normal(5).astype("float32")], grad=(0,))
S("unflatten", lambda x: paddle.unflatten(x, 1, [2, 2]),
  lambda x: x.reshape(3, 2, 2), _std(shape=(3, 4)))
S("as_complex_real_roundtrip",
  lambda x: paddle.as_real(paddle.as_complex(x)),
  lambda x: x, _std(shape=(3, 4, 2)), grad=None, grad_skip="complex")
S("cholesky_solve",
  lambda b, l: paddle.cholesky_solve(b, l, upper=False),
  lambda b, l: np.linalg.solve(l @ l.T, b),
  lambda rng: [rng.standard_normal((3, 2)).astype("float32"),
               (lambda a: np.linalg.cholesky(
                   a @ a.T + 3 * np.eye(3)).astype("float32"))(
                   rng.standard_normal((3, 3)))],
  dtypes=("float32",), grad=(0, 1),
  tols={"float32": dict(rtol=1e-4, atol=1e-4)})
S("cov", lambda x: paddle.cov(x),
  lambda x: np.cov(x), _std(shape=(3, 6)), dtypes=("float32",),
  grad=(0,), tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("corrcoef", lambda x: paddle.corrcoef(x),
  lambda x: np.corrcoef(x), _std(shape=(3, 6)), dtypes=("float32",),
  grad=(0,), tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("nanmedian", lambda x: paddle.nanmedian(x, axis=1),
  lambda x: np.nanmedian(x, 1),
  lambda rng: [np.asarray([[1.0, np.nan, 3.0, 2.0],
                           [5.0, 4.0, np.nan, np.nan]], np.float32)],
  grad=None, grad_skip="nangrad")
S("frexp", lambda x: paddle.frexp(x),
  lambda x: list(np.frexp(x)), _pos(), grad=None, grad_skip="nogradrule")
S("signbit", lambda x: paddle.signbit(x), np.signbit, _std(),
  grad=None, grad_skip="boolean")
S("isneginf", lambda x: paddle.isneginf(x), np.isneginf,
  lambda rng: [np.asarray([[1.0, -np.inf, np.inf]], np.float32)],
  grad=None, grad_skip="boolean")
S("isposinf", lambda x: paddle.isposinf(x), np.isposinf,
  lambda rng: [np.asarray([[1.0, -np.inf, np.inf]], np.float32)],
  grad=None, grad_skip="boolean")
S("lerp", lambda x, y: paddle.lerp(x, y, 0.3),
  lambda x, y: x + 0.3 * (y - x), _std(n=2), grad=(0, 1))
S("bitwise_left_shift",
  lambda x, y: paddle.bitwise_left_shift(x, y), np.left_shift,
  lambda rng: [rng.integers(0, 8, (3, 4)).astype("int32"),
               rng.integers(0, 4, (3, 4)).astype("int32")], grad=None, grad_skip="integer")
S("bitwise_right_shift",
  lambda x, y: paddle.bitwise_right_shift(x, y), np.right_shift,
  lambda rng: [rng.integers(0, 64, (3, 4)).astype("int32"),
               rng.integers(0, 4, (3, 4)).astype("int32")], grad=None, grad_skip="integer")
S("tensordot", lambda x, y: paddle.tensordot(x, y, axes=1),
  lambda x, y: np.tensordot(x, y, axes=1),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((4, 5)).astype("float32")],
  grad=(0, 1))
S("block_diag", lambda x, y: paddle.block_diag([x, y]),
  lambda x, y: __import__("scipy.linalg", fromlist=["block_diag"])
  .block_diag(x, y), _std(shape=(2, 3), n=2), grad=(0, 1))
S("column_stack", lambda x, y: paddle.column_stack([x, y]),
  lambda x, y: np.column_stack([x, y]), _std(n=2), grad=(0, 1))
S("row_stack", lambda x, y: paddle.row_stack([x, y]),
  lambda x, y: np.vstack([x, y]), _std(n=2), grad=(0, 1))
S("tensor_split", lambda x: paddle.tensor_split(x, 3, axis=1),
  lambda x: np.array_split(x, 3, axis=1), _std(shape=(2, 7)),
  grad=(0,))
S("hsplit", lambda x: paddle.hsplit(x, 2),
  lambda x: np.hsplit(x, 2), _std(shape=(2, 6)), grad=(0,))
S("vsplit", lambda x: paddle.vsplit(x, 2),
  lambda x: np.vsplit(x, 2), _std(shape=(4, 3)), grad=(0,))
S("gammainc", lambda x, y: paddle.gammainc(x, y),
  lambda x, y: sps.gammainc(x, y),
  lambda rng: [rng.uniform(0.5, 3, (3, 4)).astype("float32"),
               rng.uniform(0.5, 3, (3, 4)).astype("float32")],
  grad=None, grad_skip="nogradrule")
S("gammaincc", lambda x, y: paddle.gammaincc(x, y),
  lambda x, y: sps.gammaincc(x, y),
  lambda rng: [rng.uniform(0.5, 3, (3, 4)).astype("float32"),
               rng.uniform(0.5, 3, (3, 4)).astype("float32")],
  grad=None, grad_skip="nogradrule")
S("cartesian_prod", lambda x, y: paddle.cartesian_prod([x, y]),
  lambda x, y: np.stack(np.meshgrid(x, y, indexing="ij"),
                        -1).reshape(-1, 2),
  lambda rng: [rng.standard_normal(3).astype("float32"),
               rng.standard_normal(2).astype("float32")], grad=(0, 1))
S("margin_ranking_loss",
  lambda a, b, y: F.margin_ranking_loss(a, b, y),
  lambda a, b, y: np.asarray(np.maximum(0, -y * (a - b)).mean()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((3, 4)).astype("float32"),
               np.where(rng.uniform(size=(3, 4)) > 0.5, 1.0, -1.0)
               .astype("float32")], grad=(0, 1))
S("soft_margin_loss",
  lambda x, y: F.soft_margin_loss(x, y),
  lambda x, y: np.asarray(np.log1p(np.exp(-y * x)).mean()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               np.where(rng.uniform(size=(3, 4)) > 0.5, 1.0, -1.0)
               .astype("float32")], grad=(0,))
S("square_error_cost",
  lambda x, y: F.square_error_cost(x, y),
  lambda x, y: (x - y) ** 2, _std(n=2), grad=(0, 1))
S("log_loss", lambda x, y: F.log_loss(x, y),
  lambda x, y: -(y * np.log(x + 1e-4)
                 + (1 - y) * np.log(1 - x + 1e-4)),
  lambda rng: [rng.uniform(0.1, 0.9, (3, 1)).astype("float32"),
               (rng.uniform(size=(3, 1)) > 0.5).astype("float32")],
  grad=(0,))
S("label_smooth", lambda x: F.label_smooth(x, epsilon=0.1),
  lambda x: x * 0.9 + 0.1 / x.shape[-1],
  lambda rng: [np.eye(4, dtype=np.float32)[
      rng.integers(0, 4, (3,))]], grad=(0,))
S("poisson_nll_loss",
  lambda x, y: F.poisson_nll_loss(x, y, log_input=True, full=False),
  lambda x, y: np.asarray((np.exp(x) - y * x).mean()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.poisson(2.0, (3, 4)).astype("float32")], grad=(0,))
S("gaussian_nll_loss",
  lambda x, y, v: F.gaussian_nll_loss(x, y, v, full=False,
                                      epsilon=1e-6),
  lambda x, y, v: np.asarray(
      0.5 * (np.log(np.maximum(v, 1e-6))
             + (x - y) ** 2 / np.maximum(v, 1e-6)).mean()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((3, 4)).astype("float32"),
               rng.uniform(0.5, 2.0, (3, 4)).astype("float32")],
  grad=(0, 1, 2))
S("multi_label_soft_margin",
  lambda x, y: F.multi_label_soft_margin_loss(x, y),
  lambda x, y: np.asarray(
      -(y * np.log(sps.expit(x)) + (1 - y)
        * np.log(sps.expit(-x))).mean(-1).mean()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               (rng.uniform(size=(3, 4)) > 0.5).astype("float32")],
  grad=(0,))
S("npair_loss",
  lambda a, p, l: F.npair_loss(a, p, l, l2_reg=0.0),
  lambda a, p, l: np.asarray(
      np.mean([sps.logsumexp(
          np.concatenate([[0.0],
                          (a[i] @ p.T)[np.arange(len(l)) != i]
                          - a[i] @ p[i]]))
          for i in range(len(l))])),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32") * 0.3,
               rng.standard_normal((3, 4)).astype("float32") * 0.3,
               np.arange(3).astype("int64")], grad=(0, 1),
  tols={"float32": dict(rtol=1e-3, atol=1e-4)})
S("local_response_norm",
  lambda x: F.local_response_norm(x, size=3, alpha=1e-4, beta=0.75,
                                  k=1.0),
  lambda x: x / (1.0 + (1e-4 / 3) * np.stack([
      (x ** 2)[:, max(0, c - 1):c + 2].sum(1)
      for c in range(x.shape[1])], 1)) ** 0.75,
  _std(shape=(2, 4, 3, 3)), grad=(0,),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("zeropad2d", lambda x: F.zeropad2d(x, [1, 2, 0, 1]),
  lambda x: np.pad(x, ((0, 0), (0, 0), (0, 1), (1, 2))),
  _std(shape=(1, 2, 3, 3)), grad=(0,))
S("alpha_dropout_eval",
  lambda x: F.alpha_dropout(x, 0.5, training=False),
  lambda x: x, _std())



# batch 3 (r5): the last reference linalg.__all__ entries
S("cholesky_inverse",
  lambda l: paddle.linalg.cholesky_inverse(l),
  lambda l: np.linalg.inv(l @ l.T),
  lambda rng: [(lambda a: np.linalg.cholesky(
      a @ a.T + 3 * np.eye(3)).astype("float32"))(
      rng.standard_normal((3, 3)))],
  dtypes=("float32",), grad=(0,),
  tols={"float32": dict(rtol=1e-4, atol=1e-4)})
S("matrix_norm_fro",
  lambda x: paddle.linalg.matrix_norm(x),
  lambda x: np.asarray(np.linalg.norm(x)), _std(), grad=(0,))
S("vector_norm_l3",
  lambda x: paddle.linalg.vector_norm(x, p=3.0),
  lambda x: np.asarray((np.abs(x) ** 3).sum() ** (1 / 3)), _std(),
  grad=(0,), tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("svd_lowrank_reconstruct",
  lambda x: (lambda u, s, v: paddle.matmul(
      u * s.unsqueeze(-2), v, transpose_y=True))(
      *paddle.linalg.svd_lowrank(x, q=2)),
  lambda x: x,
  lambda rng: [(rng.standard_normal((6, 2))
                @ rng.standard_normal((2, 4))).astype("float32")],
  dtypes=("float32",), grad=None, grad_skip="unstable",
  tols={"float32": dict(rtol=1e-3, atol=1e-4)})
S("pca_lowrank_linalg",
  lambda x: (lambda u, s, v: paddle.matmul(
      u * s.unsqueeze(-2), v, transpose_y=True))(
      *paddle.linalg.pca_lowrank(x, q=3, center=False)),
  lambda x: x,
  lambda rng: [(rng.standard_normal((6, 3))
                @ rng.standard_normal((3, 4))).astype("float32")],
  dtypes=("float32",), grad=None, grad_skip="unstable",
  tols={"float32": dict(rtol=1e-3, atol=1e-4)})



# --------------------------------------------------------------------------
# batch 3 (r5 final): remaining mappable surface — structural ops, linalg
# decompositions (checked via canonical recompositions), scatter family
# --------------------------------------------------------------------------
import scipy.linalg as spl

S("add_n", lambda a, b, c: paddle.add_n([a, b, c]),
  lambda a, b, c: a + b + c, _std(n=3), grad=(0, 1, 2))
S("inner", lambda x, y: paddle.inner(x, y),
  np.inner, _std(n=2), grad=(0, 1))
S("mm", lambda x, y: paddle.mm(x, y),
  lambda x, y: x @ y,
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((4, 5)).astype("float32")],
  grad=(0, 1))
S("dist", lambda x, y: paddle.dist(x, y, p=2),
  lambda x, y: np.linalg.norm((x - y).ravel(), 2), _std(n=2),
  grad=(0, 1))
S("trace", lambda x: paddle.trace(x), np.trace, _std((4, 4)))
S("t", lambda x: paddle.t(x), np.transpose, _std((3, 5)))
S("scale", lambda x: paddle.scale(x, scale=2.5, bias=1.0),
  lambda x: 2.5 * x + 1.0, _std())
S("floor_mod", lambda x, y: paddle.floor_mod(x, y),
  lambda x, y: np.mod(x, y),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.uniform(0.5, 2.0, (3, 4)).astype("float32")],
  grad=None, grad_skip="discontinuous")
S("reverse", lambda x: paddle.reverse(x, axis=[0]),
  lambda x: x[::-1].copy(), _std())
S("expand_as", lambda x, y: paddle.expand_as(x, y),
  lambda x, y: np.broadcast_to(x, y.shape).copy(),
  lambda rng: [rng.standard_normal((1, 4)).astype("float32"),
               rng.standard_normal((3, 4)).astype("float32")],
  grad=(0,))
S("atleast_1d", lambda x: paddle.atleast_1d(x), np.atleast_1d,
  _std((4,)))
S("atleast_3d", lambda x: paddle.atleast_3d(x), np.atleast_3d,
  _std((3, 4)))
S("dsplit_0", lambda x: paddle.dsplit(x, 2)[0],
  lambda x: np.dsplit(x, 2)[0], _std((2, 3, 4)))
S("as_complex", lambda x: paddle.as_real(paddle.as_complex(x)),
  lambda x: x, _std((3, 4, 2)), grad=None, grad_skip="complex", dtypes=("float32",))
S("complex", lambda re, im: paddle.as_real(paddle.complex(re, im)),
  lambda re, im: np.stack([re, im], -1), _std(n=2), grad=None, grad_skip="complex",
  dtypes=("float32",))
S("polar", lambda r, t: paddle.as_real(paddle.polar(r, t)),
  lambda r, t: np.stack([r * np.cos(t), r * np.sin(t)], -1),
  lambda rng: [rng.uniform(0.2, 2.0, (3, 4)).astype("float32"),
               rng.uniform(-3.0, 3.0, (3, 4)).astype("float32")],
  grad=None, grad_skip="complex", dtypes=("float32",))
S("isreal", lambda x: paddle.isreal(x),
  lambda x: np.isreal(x), _std(), grad=None, grad_skip="boolean")
S("isin", lambda x, t: paddle.isin(x, t),
  np.isin, _ints(n=2), grad=None, grad_skip="boolean", dtypes=("int64",))
S("pad_constant", lambda x: paddle.nn.functional.pad(
      x, [1, 2], mode="constant", value=0.5),
  lambda x: np.pad(x, [(0, 0), (1, 2)], constant_values=0.5),
  _std(), grad=(0,))
S("norm_fro", lambda x: paddle.linalg.norm(x),
  lambda x: np.linalg.norm(x), _std(), grad=(0,),
  tols={"float32": dict(rtol=2e-5, atol=2e-6)})
S("vector_norm_1", lambda x: paddle.linalg.vector_norm(x, p=1),
  lambda x: np.abs(x).sum(), _std(), grad=(0,))
S("matrix_norm_nuc",
  lambda x: paddle.linalg.matrix_norm(x, p="nuc"),
  lambda x: np.linalg.norm(x, "nuc"), _std((4, 4)), grad=(0,),
  dtypes=("float32",), tols={"float32": dict(rtol=1e-4, atol=1e-4)})
S("matrix_exp", lambda x: paddle.linalg.matrix_exp(0.3 * x),
  lambda x: spl.expm(0.3 * np.asarray(x, np.float64)).astype(
      np.float32),
  _std((4, 4)), grad=(0,), dtypes=("float32",),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("qr_recompose",
  lambda x: paddle.matmul(*paddle.linalg.qr(x)),
  lambda x: x,
  lambda rng: [rng.standard_normal((5, 3)).astype("float32")],
  grad=None, grad_skip="unstable", dtypes=("float32",),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
S("svd_recompose",
  # svd returns (U, S, VH) — reference tensor/linalg.py:2785
  lambda x: (lambda u, s, vh: paddle.matmul(
      u * s.unsqueeze(-2), vh))(
          *paddle.linalg.svd(x, full_matrices=False)),
  lambda x: x,
  lambda rng: [rng.standard_normal((4, 3)).astype("float32")],
  grad=None, grad_skip="unstable", dtypes=("float32",),
  tols={"float32": dict(rtol=1e-4, atol=1e-4)})
S("eigh_vals",
  lambda x: paddle.linalg.eigh(
      paddle.add(x, paddle.t(x)))[0],
  lambda x: np.linalg.eigvalsh(x + x.T),
  _std((4, 4)), grad=None, grad_skip="unstable", dtypes=("float32",),
  tols={"float32": dict(rtol=1e-4, atol=1e-4)})
S("eigvals_sorted",
  lambda x: paddle.sort(paddle.abs(paddle.linalg.eigvals(
      paddle.add(x, paddle.t(x))))),
  lambda x: np.sort(np.abs(np.linalg.eigvals(
      (x + x.T).astype(np.complex64)))),
  _std((4, 4)), grad=None, grad_skip="unstable", dtypes=("float32",),
  tols={"float32": dict(rtol=1e-3, atol=1e-3)})
S("lu_recompose",
  lambda x: (lambda lu_, piv: (lambda p, l, u: paddle.matmul(
      paddle.matmul(p, l), u))(*paddle.linalg.lu_unpack(lu_, piv)))(
          *paddle.linalg.lu(x)[:2]),
  lambda x: x, _std((4, 4)), grad=None, grad_skip="unstable", dtypes=("float32",),
  tols={"float32": dict(rtol=1e-4, atol=1e-5)})
def _np_householder_product(a, tau):
    # H_i = I - tau_i v_i v_i^T with v_i = [0...0, 1, a[i+1:, i]]
    m, n = a.shape
    q = np.eye(m, dtype=np.float64)
    for i in range(n - 1, -1, -1):
        v = np.zeros(m)
        v[i] = 1.0
        v[i + 1:] = a[i + 1:, i]
        h = np.eye(m) - tau[i] * np.outer(v, v)
        q = h @ q
    return q[:, :n].astype(np.float32)


S("householder_product",
  lambda x, tau: paddle.linalg.householder_product(x, tau),
  _np_householder_product,
  lambda rng: [np.tril(rng.standard_normal((5, 3)), -1).astype(
      "float32") + np.eye(5, 3, dtype=np.float32),
      rng.uniform(0.1, 0.5, (3,)).astype("float32")],
  grad=None, grad_skip="unstable", dtypes=("float32",),
  tols={"float32": dict(rtol=1e-3, atol=1e-3)})
S("scatter_overwrite",
  lambda x, idx, upd: paddle.scatter(x, idx, upd),
  lambda x, idx, upd: (lambda y: (y.__setitem__(idx, upd), y)[1])(
      x.copy()),
  lambda rng: [rng.standard_normal((5, 3)).astype("float32"),
               np.array([0, 2, 4], np.int64),
               rng.standard_normal((3, 3)).astype("float32")],
  grad=(0, 2))
S("scatter_nd_sum",
  lambda idx, upd: paddle.scatter_nd(idx, upd, [6]),
  lambda idx, upd: (lambda y: (np.add.at(y, idx[:, 0], upd), y)[1])(
      np.zeros(6, np.float32)),
  lambda rng: [np.array([[1], [3], [1]], np.int64),
               rng.standard_normal((3,)).astype("float32")],
  grad=(1,))
S("select_scatter",
  lambda x, v: paddle.select_scatter(x, v, axis=0, index=1),
  lambda x, v: (lambda y: (y.__setitem__(1, v), y)[1])(x.copy()),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((4,)).astype("float32")],
  grad=(0, 1))
S("slice_scatter",
  lambda x, v: paddle.slice_scatter(x, v, axes=[0], starts=[1],
                                    ends=[3], strides=[1]),
  lambda x, v: (lambda y: (y.__setitem__(slice(1, 3), v), y)[1])(
      x.copy()),
  lambda rng: [rng.standard_normal((4, 3)).astype("float32"),
               rng.standard_normal((2, 3)).astype("float32")],
  grad=(0, 1))
S("diagonal_scatter",
  lambda x, v: paddle.diagonal_scatter(x, v),
  lambda x, v: (lambda y: (np.fill_diagonal(y, v), y)[1])(x.copy()),
  lambda rng: [rng.standard_normal((4, 4)).astype("float32"),
               rng.standard_normal((4,)).astype("float32")],
  grad=(0, 1))
S("fill_diagonal_tensor",
  lambda x, v: paddle.fill_diagonal_tensor(x, v, offset=0, dim1=0,
                                           dim2=1),
  lambda x, v: (lambda y: (np.fill_diagonal(y, v), y)[1])(x.copy()),
  lambda rng: [rng.standard_normal((4, 4)).astype("float32"),
               rng.standard_normal((4,)).astype("float32")],
  grad=(0, 1))
S("index_put",
  lambda x, v: paddle.index_put(
      x, [paddle.to_tensor(np.array([0, 2], np.int64))], v),
  lambda x, v: (lambda y: (y.__setitem__(np.array([0, 2]), v), y)[1])(
      x.copy()),
  lambda rng: [rng.standard_normal((4, 3)).astype("float32"),
               rng.standard_normal((2, 3)).astype("float32")],
  grad=(0, 1))
S("strided_slice",
  lambda x: paddle.strided_slice(x, axes=[0, 1], starts=[0, 1],
                                 ends=[4, 4], strides=[2, 1]),
  lambda x: x[0:4:2, 1:4].copy(), _std((5, 5)), grad=(0,))
S("slice_op",
  lambda x: paddle.slice(x, axes=[0], starts=[1], ends=[3]),
  lambda x: x[1:3].copy(), _std((5, 4)), grad=(0,))
S("as_strided_view",
  lambda x: paddle.as_strided(x, [2, 3], [3, 1]),
  lambda x: np.lib.stride_tricks.as_strided(
      x, (2, 3), (3 * x.itemsize, x.itemsize)).copy(),
  _std((12,)), grad=None, grad_skip="aliasing")
S("multiplex",
  lambda a, b, idx: paddle.multiplex([a, b], idx),
  lambda a, b, idx: np.stack([a, b])[idx[:, 0],
                                     np.arange(a.shape[0])],
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((3, 4)).astype("float32"),
               np.array([[0], [1], [0]], np.int64)],
  grad=(0, 1))
S("shard_index",
  lambda x: paddle.shard_index(x, index_num=20, nshards=2,
                               shard_id=0),
  lambda x: np.where((x >= 0) & (x < 10), x, -1),
  lambda rng: [rng.integers(0, 20, (4, 1)).astype("int64")],
  grad=None, grad_skip="integer", dtypes=("int64",))
S("reduce_as",
  lambda x, y: paddle.reduce_as(x, y),
  lambda x, y: x.sum(0, keepdims=False),
  lambda rng: [rng.standard_normal((3, 4)).astype("float32"),
               rng.standard_normal((4,)).astype("float32")],
  grad=(0,))
S("tril_indices",
  lambda: paddle.tril_indices(4, 4, 0),
  lambda: np.stack(np.tril_indices(4, 0, 4)).astype(np.int64),
  lambda rng: [], grad=None, grad_skip="constant", dtypes=("int64",))
S("triu_indices",
  lambda: paddle.triu_indices(4, 4, 0),
  lambda: np.stack(np.triu_indices(4, 0, 4)).astype(np.int64),
  lambda rng: [], grad=None, grad_skip="constant", dtypes=("int64",))
S("histogramdd_counts",
  lambda x: paddle.histogramdd(x, bins=[3, 3],
                               ranges=[-2.0, 2.0, -2.0, 2.0])[0],
  lambda x: np.histogramdd(
      x, bins=[3, 3], range=[(-2, 2), (-2, 2)])[0].astype(np.float32),
  _unit((20, 2)), grad=None, grad_skip="counting", dtypes=("float32",))
S("multigammaln",
  lambda x: paddle.multigammaln(x, p=2),
  lambda x: sps.multigammaln(np.asarray(x, np.float64), 2).astype(
      np.float32),
  _pos(lo=1.2, hi=4.0), grad=None, grad_skip="nogradrule",
  tols={"float32": dict(rtol=1e-4, atol=1e-4),
        "bfloat16": dict(rtol=0.1, atol=0.1)})
S("combinations_pairs",
  lambda x: paddle.combinations(x, r=2),
  lambda x: np.array([[x[i], x[j]] for i in range(len(x))
                      for j in range(i + 1, len(x))], np.float32),
  _std((5,)), grad=(0,))
S("column_stack",
  lambda a, b: paddle.column_stack([a, b]),
  lambda a, b: np.column_stack([a, b]), _std((4,), n=2),
  grad=(0, 1))
S("cartesian_prod",
  lambda a, b: paddle.cartesian_prod([a, b]),
  lambda a, b: np.array([[i, j] for i in a for j in b], np.float32),
  _std((3,), n=2), grad=(0, 1))


S("nanquantile",
  lambda x: paddle.nanquantile(x, 0.5, axis=-1),
  lambda x: np.nanquantile(x, 0.5, axis=-1).astype(np.float32),
  lambda rng: [np.where(rng.uniform(size=(3, 8)) > 0.8, np.nan,
                        rng.standard_normal((3, 8))).astype("float32")],
  grad=None, grad_skip="nangrad", dtypes=("float32",))
S("histogram_bin_edges",
  # min==max==0 selects the data-dependent auto-range branch — the
  # only path that actually reads the tensor
  lambda x: x.histogram_bin_edges(bins=6),
  lambda x: np.histogram_bin_edges(x, bins=6).astype(np.float32),
  _std(), grad=None, grad_skip="counting", dtypes=("float32",))


SKIPPED = {
    "conv2d": "covered by dedicated shape/grad tests (test_ops.py)",
    "rnn/lstm/gru": "stateful multi-output recurrent API (test_nn.py)",
    "dropout-training": "stochastic output has no numpy point reference",
    "batch_norm-training": "running-stat mutation (test_nn extras)",
    "collectives": "need a device mesh (test_distributed.py)",
    "io/random/optimizer kernels": "not (arrays->arrays) signatures",
    "einsum": "dedicated tests in test_ops.py",
    "fft family": "dedicated tests in test_fft_signal.py",
    "sparse family": "dedicated tests in test_sparse.py",
}


@pytest.mark.parametrize("spec", SPECS, ids=[s.name for s in SPECS])
def test_op_sweep(spec):
    class T(OpTest):
        dtypes = spec.dtypes
        tols = spec.tols

        def op(self, *a):
            return spec.op(*a)

        def ref(self, *a):
            return spec.ref(*a)

        def inputs(self, rng):
            return spec.inputs(rng)

    t = T()
    t.check_output()
    if spec.grad is not None:
        t.check_grad(wrt=spec.grad, **spec.grad_kw)


def test_grad_coverage_boundary():
    """Every forward-only spec carries a one-word reason, and the
    grad-checked majority stays large (the r5 'forward-only tail'
    finding: >160 specs skipped grads with no stated cause)."""
    unexplained = [s.name for s in SPECS
                   if s.grad is None and not (
                       isinstance(s.grad_skip, str)
                       and s.grad_skip.isidentifier())]
    assert unexplained == [], unexplained
    spurious = [s.name for s in SPECS
                if s.grad is not None and s.grad_skip is not None]
    assert spurious == [], spurious
    assert sum(1 for s in SPECS if s.grad is not None) >= 200


def test_sweep_count():
    """The audit promises broad numeric coverage: keep the sweep large."""
    assert len(SPECS) >= 300, len(SPECS)
