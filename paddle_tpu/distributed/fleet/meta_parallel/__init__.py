"""Model wrappers per parallelism axis.

Reference parity: fleet/meta_parallel/ — TensorParallel
(tensor_parallel.py), SegmentParallel (segment_parallel.py:26),
ShardingParallel, PipelineParallel (pipeline_parallel.py:231).

TPU-first: wrappers are thin — parameter placement/sharding happens in the
layers (mpu) or the sharded optimizer; inputs get sharding constraints for
the relevant axis. The reference's param-broadcast/input-broadcast steps are
unnecessary (single controller: there is one copy of truth).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework.tensor import Tensor
from ....nn.layer.layers import Layer
from ...parallel import _shard_batch


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def train_step(self, optimizer, criterion=None, **kw):
        """Whole-step entry shared by the hybrid wrappers: dispatch on
        the mesh's active axes via jit.select_train_step — a >1 ``pp``
        axis gets the ring `PipelineScanTrainStep`, a >1 ``mp`` axis the
        dp×mp `ShardedFusedScanTrainStep`, a dp/sharding axis the
        dp-only sharded scan (micro-batch count for pp comes from the
        strategy's pipeline_configs accumulate_steps unless overridden).
        """
        from ....jit.sharded_scan import select_train_step

        hcg = self._hcg
        if "num_micro" not in kw and hcg is not None and \
                hcg.get_pipe_parallel_world_size() > 1:
            cfg = (getattr(self._strategy, "pipeline_configs", None)
                   or {})
            accum = int(cfg.get("accumulate_steps", 1) or 1)
            if accum > 1:
                kw["num_micro"] = accum
        return select_train_step(self._layers, optimizer,
                                 criterion=criterion,
                                 mesh=hcg.mesh if hcg is not None
                                 else None, **kw)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


class TensorParallel(MetaParallelBase):
    """Reference tensor_parallel.py — mpu layers already shard their own
    weights; batch additionally shards on dp if present."""

    def forward(self, *inputs, **kwargs):
        mesh = self._hcg.mesh
        inputs = tuple(
            _shard_batch(x, mesh, "dp") if isinstance(x, Tensor) else x
            for x in inputs
        )
        return self._layers(*inputs, **kwargs)


class SegmentParallel(MetaParallelBase):
    """Reference segment_parallel.py:26 — sequence dim sharded over sep."""

    def forward(self, *inputs, **kwargs):
        mesh = self._hcg.mesh

        def shard_seq(t):
            if not isinstance(t, Tensor) or t.ndim < 2:
                return t
            if t.shape[1] % mesh.shape["sep"] != 0:
                return t
            spec = P(None, "sep", *([None] * (t.ndim - 2)))
            from ....framework.autograd import apply_op

            return apply_op(
                lambda x: jax.device_put(x, NamedSharding(mesh, spec)), [t],
                name="shard_seq")

        inputs = tuple(shard_seq(x) for x in inputs)
        return self._layers(*inputs, **kwargs)


class ShardingParallel(MetaParallelBase):
    """Reference sharding_parallel.py — param sharding is done by the
    GroupSharded optimizer/stage wrappers; batch shards on sharding axis
    (which doubles as a data axis in ZeRO)."""

    def forward(self, *inputs, **kwargs):
        mesh = self._hcg.mesh
        inputs = tuple(
            _shard_batch(x, mesh, "sharding") if isinstance(x, Tensor) else x
            for x in inputs
        )
        return self._layers(*inputs, **kwargs)

    def train_step(self, optimizer, criterion=None, **kw):
        """fleet.distributed_model's whole-step entry: scan_layers GPT
        models get the weight-update-sharded fused scan step over the
        sharding axis (jit/sharded_scan.py), others the generic
        TrainStep."""
        from ....jit.sharded_scan import select_train_step

        return select_train_step(self._layers, optimizer,
                                 criterion=criterion,
                                 mesh=self._hcg.mesh, axis="sharding",
                                 **kw)


class HybridParallel(MetaParallelBase):
    """Generic hybrid wrapper for models that are not PipelineLayers
    (e.g. a scan_layers GPT) on a mesh with >1 mp and/or pp degrees:
    batch shards on the dp-like axis, `train_step()` builds the
    matching dp×mp / dp×pp compiled step via select_train_step."""

    def forward(self, *inputs, **kwargs):
        mesh = self._hcg.mesh
        axis = next((a for a in ("sharding", "dp")
                     if a in mesh.axis_names and mesh.shape[a] > 1),
                    None)
        if axis is not None:
            inputs = tuple(
                _shard_batch(x, mesh, axis) if isinstance(x, Tensor)
                else x for x in inputs)
        return self._layers(*inputs, **kwargs)


from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: E402,F401
from .pipeline_parallel import PipelineParallel  # noqa: E402,F401
from .ring_attention import (  # noqa: E402,F401
    ring_attention, ring_flash_attention, sep_sharding,
)
