"""Profiler tests (reference profiler.py:358 semantics, host side)."""
import json
import os
import time

import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent,
    export_chrome_tracing, make_scheduler, load_profiler_result,
)


class TestScheduler:
    def test_state_machine(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                               skip_first=1)
        states = [sched(i) for i in range(6)]
        assert states == [
            ProfilerState.CLOSED,   # skip_first
            ProfilerState.CLOSED,
            ProfilerState.READY,
            ProfilerState.RECORD,
            ProfilerState.RECORD_AND_RETURN,
            ProfilerState.CLOSED,   # repeat exhausted
        ]

    def test_tuple_scheduler(self):
        p = Profiler(scheduler=(1, 3), on_trace_ready=lambda prof: None)
        p.start()
        assert p.current_state == ProfilerState.CLOSED
        p.step()
        assert p.current_state == ProfilerState.RECORD
        p.step()
        assert p.current_state == ProfilerState.RECORD_AND_RETURN
        p.step()
        assert p.current_state == ProfilerState.CLOSED
        p.stop()


class TestRecordEvent:
    def test_events_captured_and_summary(self, tmp_path):
        traces = []
        p = Profiler(on_trace_ready=lambda prof: traces.append(
            prof._last_result))
        p.start()
        with RecordEvent("forward"):
            time.sleep(0.002)
        with RecordEvent("backward"):
            time.sleep(0.001)
        p.step()
        with RecordEvent("forward"):
            time.sleep(0.002)
        p.stop()
        res = traces[-1]
        names = [e.name for e in res.events]
        assert names.count("forward") == 2 and "backward" in names
        s = p.summary()
        assert "forward" in s and "Steps: 2" in s

    def test_not_recorded_when_closed(self):
        with RecordEvent("orphan"):
            pass
        p = Profiler(on_trace_ready=lambda prof: None)
        p.start()
        p.stop()
        assert all(e.name != "orphan" for e in p._last_result.events)


class TestChromeExport:
    def test_export_and_load(self, tmp_path):
        d = str(tmp_path / "trace")
        p = Profiler(on_trace_ready=export_chrome_tracing(d))
        p.start()
        with RecordEvent("matmul"):
            time.sleep(0.001)
        p.stop()
        assert p._last_export_path and os.path.exists(p._last_export_path)
        data = load_profiler_result(p._last_export_path)
        names = [e["name"] for e in data["traceEvents"]]
        assert "matmul" in names
        assert any(n.startswith("ProfileStep#") for n in names)

    def test_step_times(self):
        p = Profiler(on_trace_ready=lambda prof: None)
        p.start()
        time.sleep(0.001)
        p.step()
        time.sleep(0.001)
        p.stop()
        assert len(p.step_times_ms) == 2
        assert all(t > 0 for t in p.step_times_ms)
