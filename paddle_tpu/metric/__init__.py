"""Metrics (python/paddle/metric/metrics.py parity)."""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor


def _to_np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = [name] if name else [f"acc_top{k}" for k in self.topk]
        if len(self._name) == 1 and len(self.topk) == 1:
            self._name = [name or "acc"]
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _to_np(pred)
        label_np = _to_np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = top == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _to_np(correct)
        num = c.shape[0]
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
        self.count += num
        return self.total[0] / max(self.count, 1)

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_to_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _to_np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_to_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _to_np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _to_np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _to_np(labels).reshape(-1)
        bins = np.minimum(
            (p * self.num_thresholds).astype(np.int64), self.num_thresholds - 1
        )
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds (descending), anchored at (0,0) so
        # a populated top bucket keeps its leading triangle
        tp = np.concatenate([[0.0], np.cumsum(self._stat_pos[::-1])])
        fp = np.concatenate([[0.0], np.cumsum(self._stat_neg[::-1])])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr) if hasattr(np, "trapezoid")
                     else np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None):
    pred = _to_np(input)
    l = _to_np(label).reshape(-1)
    top = np.argsort(-pred, axis=-1)[:, :k]
    c = (top == l[:, None]).any(axis=1)
    return Tensor(np.asarray(c.mean(), np.float32))
