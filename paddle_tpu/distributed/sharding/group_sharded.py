"""Group sharded training — ZeRO stages 2 and 3.

Reference parity: group_sharded_parallel
(python/paddle/distributed/sharding/group_sharded.py:50) dispatching to
GroupShardedOptimizerStage2 + GroupShardedStage2 (grad slices
reduce-scattered) and GroupShardedStage3
(fleet/meta_parallel/sharding/group_sharded_stage3.py:85 — param
segmentation :422, forward allgather hooks :557, reduce-scatter grads :639).

TPU-first: every stage is a layout choice the XLA partitioner executes:

- stage 2 ("os_g"): optimizer states AND the gradient computation are
  sharded over the axis; grads materialize reduce-scattered because the
  update operands are sharded (GSPMD sharding propagation).
- stage 3 ("p_g_os"): parameters themselves carry the sharded layout;
  XLA all-gathers them where the forward needs them and reduce-scatters
  gradients — the hand-written pre-forward allgather hooks + post-backward
  release of the reference become compiler-scheduled, overlapped with
  compute.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..fleet.meta_optimizers.dygraph_sharding_optimizer import (
    DygraphShardingOptimizer, _shardable_dim,
)
from .. import env


class GroupShardedStage2:
    """Model wrapper for stage 2: forward passes through; grad sharding is
    induced by the sharded optimizer states."""

    def __init__(self, layer, sharding_optimizer=None, group=None,
                 sync_buffers=False, buffer_max_size=2 ** 23,
                 auto_refresh_trainable=True, device="tpu", dp_group=None):
        self._layers = layer
        self._opt = sharding_optimizer

    def __call__(self, *a, **k):
        return self._layers(*a, **k)

    def __getattr__(self, item):
        return getattr(self._layers, item)


class GroupShardedStage3:
    """Stage 3 wrapper: shards every large parameter over the axis."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pertrain_sync_models=True,
                 offload=False, sync_comm=False, dp_group=None,
                 exclude_layer=None):
        self._layers = layer
        self._opt = optimizer
        if group is not None:
            mesh, axis = group.mesh, group.axes[0]
        else:
            mesh = env.get_mesh()
            axis = ("sharding" if "sharding" in mesh.axis_names
                    else mesh.axis_names[0])
        self._mesh, self._axis = mesh, axis
        self._segment_size = segment_size
        self._shard_params()

    def _shard_params(self):
        degree = int(self._mesh.shape[self._axis])
        if degree <= 1:
            return
        for p in self._layers.parameters():
            if p.size * 4 < self._segment_size:
                continue  # small params stay replicated (reference keeps
                          # sub-segment params unsharded)
            dim = _shardable_dim(p.shape, degree)
            if dim is None:
                continue
            axes = [None] * p.ndim
            axes[dim] = self._axis
            p._data = jax.device_put(
                p._data, NamedSharding(self._mesh, P(*axes)))

    def __call__(self, *a, **k):
        return self._layers(*a, **k)

    def __getattr__(self, item):
        return getattr(self._layers, item)

    def get_all_parameters(self, convert2cpu=False):
        """Reference stage3: re-materialize full params (all-gather)."""
        for p in self._layers.parameters():
            p._data = jax.device_put(
                p._data, NamedSharding(self._mesh, P()))
        return list(self._layers.parameters())


class GroupShardedScaler:
    """Reference group_sharded_utils.GroupShardedScaler — delegates to the
    base scaler; found_inf is already global under one controller."""

    def __init__(self, scaler):
        self._scaler = scaler

    def __getattr__(self, item):
        return getattr(self._scaler, item)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Reference group_sharded.py:50. level: "os" (stage1) | "os_g" (stage2)
    | "p_g_os" (stage3). Returns (model, optimizer, scaler)."""
    assert level in ("os", "os_g", "p_g_os"), f"bad level {level}"
    sharded_opt = (optimizer if isinstance(optimizer, DygraphShardingOptimizer)
                   else DygraphShardingOptimizer(optimizer, group=group))
    if level == "os":
        out_model = model
    elif level == "os_g":
        out_model = GroupShardedStage2(model, sharded_opt, group=group,
                                       buffer_max_size=buffer_max_size)
    else:
        out_model = GroupShardedStage3(model, sharded_opt, group=group,
                                       segment_size=segment_size,
                                       offload=offload)
    if scaler is not None:
        scaler = GroupShardedScaler(scaler)
    return out_model, sharded_opt, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Reference group_sharded.py:199 — gather full params then save."""
    import os as _os

    from ...framework import io as fio

    layers = model._layers if hasattr(model, "_layers") else model
    if isinstance(model, GroupShardedStage3):
        model.get_all_parameters()
    _os.makedirs(output, exist_ok=True)
    fio.save(layers.state_dict(), _os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        fio.save(optimizer.state_dict(),
                 _os.path.join(output, "model.pdopt"))
