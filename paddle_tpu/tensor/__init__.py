"""paddle.tensor namespace parity (python/paddle/tensor/): the tensor op
library grouped by category. The ops live in paddle_tpu.ops (same
categories); this package re-exports them under the reference's module
names so `paddle.tensor.math.add`-style imports work."""
from ..ops import *  # noqa: F401,F403
from ..ops import creation, linalg, logic, manipulation, math  # noqa: F401
from ..ops import reduction as stat  # noqa: F401  (mean/std/var live here)
