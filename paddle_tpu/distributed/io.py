"""paddle.distributed.io (reference distributed/io.py): persistables
save/load for distributed inference programs. Under the single
controller these are the plain framework save/load — re-exported so
ported scripts resolve."""
from ..framework.io import save, load  # noqa: F401


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    raise NotImplementedError(
        "static-program persistable sweeps do not exist here; use "
        "paddle.save(model.state_dict(), path)")


def load_inference_model_distributed(*a, **k):
    raise NotImplementedError(
        "distributed inference programs are served via jit.save/"
        "paddle.inference (StableHLO artifacts)")
