"""Long-tail op pack parity vs numpy/scipy oracles + top-level __all__
coverage check against the reference's paddle/__init__.py."""
import ast

import numpy as np
import pytest
import scipy.special as sp

import paddle_tpu as paddle


def _t(a, dtype=None):
    return paddle.to_tensor(np.asarray(a), dtype=dtype)


def _np(t):
    return np.asarray(t._data)


def test_reference_top_level_all_covered():
    import os

    if not os.path.exists("/root/reference/python/paddle/__init__.py"):
        pytest.skip("reference checkout not present")
    src = open("/root/reference/python/paddle/__init__.py").read()
    tree = ast.parse(src)
    names = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tt in node.targets:
                if getattr(tt, "id", None) == "__all__":
                    names = [ast.literal_eval(e) for e in node.value.elts]
    missing = [n for n in names if not hasattr(paddle, n)]
    assert not missing, f"missing top-level names: {missing}"


class TestSpecial:
    def test_special_functions(self):
        x = np.array([0.5, 1.5, 3.0])
        np.testing.assert_allclose(_np(paddle.gammaln(_t(x))),
                                   sp.gammaln(x), rtol=1e-5)
        np.testing.assert_allclose(_np(paddle.i0(_t(x))), sp.i0(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(_np(paddle.i1e(_t(x))), sp.i1e(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(_np(paddle.sinc(_t(x))), np.sinc(x),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            _np(paddle.gammainc(_t(x), _t(x + 1))),
            sp.gammainc(x, x + 1), rtol=1e-5)
        np.testing.assert_allclose(
            _np(paddle.polygamma(_t(x), 1)), sp.polygamma(1, x), rtol=1e-4)

    def test_sgn_signbit_polar(self):
        np.testing.assert_allclose(
            _np(paddle.sgn(_t([-2.0, 0.0, 5.0]))), [-1, 0, 1])
        np.testing.assert_allclose(
            _np(paddle.signbit(_t([-1.0, 1.0]))), [True, False])
        out = _np(paddle.polar(_t([1.0, 2.0]), _t([0.0, np.pi / 2])))
        np.testing.assert_allclose(out.real, [1.0, 0.0], atol=1e-6)
        np.testing.assert_allclose(out.imag, [0.0, 2.0], atol=1e-6)


class TestManipulation:
    def test_splits(self):
        x = _t(np.arange(12.0).reshape(3, 4))
        parts = paddle.tensor_split(x, 2, axis=1)
        assert [list(p.shape) for p in parts] == [[3, 2], [3, 2]]
        np.testing.assert_allclose(
            np.concatenate([_np(p) for p in paddle.hsplit(x, 2)], 1),
            _np(x))
        vs = paddle.vsplit(x, [1])
        assert [list(p.shape) for p in vs] == [[1, 4], [2, 4]]

    def test_split_grads(self):
        x = _t(np.arange(6.0))
        x.stop_gradient = False
        a, b = paddle.tensor_split(x, 2)
        (a.sum() * 2 + b.sum()).backward()
        np.testing.assert_allclose(_np(x.grad), [2, 2, 2, 1, 1, 1])

    def test_stacks_atleast(self):
        a, b = _t([1.0, 2.0]), _t([3.0, 4.0])
        np.testing.assert_allclose(_np(paddle.column_stack([a, b])),
                                   np.column_stack([[1, 2], [3, 4]]))
        np.testing.assert_allclose(_np(paddle.row_stack([a, b])),
                                   [[1, 2], [3, 4]])
        assert list(paddle.atleast_2d(_t(5.0)).shape) == [1, 1]
        assert list(paddle.atleast_3d(_t([1.0, 2.0])).shape) == [1, 2, 1]

    def test_block_diag_diag_embed(self):
        a = _t([[1.0, 2.0]])
        b = _t([[3.0]])
        np.testing.assert_allclose(_np(paddle.block_diag([a, b])),
                                   [[1, 2, 0], [0, 0, 3]])
        d = paddle.diag_embed(_t([1.0, 2.0]))
        np.testing.assert_allclose(_np(d), np.diag([1.0, 2.0]))
        d2 = paddle.diag_embed(_t([1.0, 2.0]), offset=1)
        np.testing.assert_allclose(_np(d2), np.diag([1.0, 2.0], k=1))

    def test_scatter_family(self):
        x = _t(np.zeros((3, 4), np.float32))
        out = paddle.slice_scatter(x, _t(np.ones((3, 2), np.float32)),
                                   axes=[1], starts=[1], ends=[3],
                                   strides=[1])
        want = np.zeros((3, 4))
        want[:, 1:3] = 1
        np.testing.assert_allclose(_np(out), want)
        out2 = paddle.select_scatter(x, _t(np.full((4,), 7.0, np.float32)),
                                     axis=0, index=1)
        assert _np(out2)[1].sum() == 28
        out3 = paddle.diagonal_scatter(x, _t(np.ones(3, np.float32)))
        np.testing.assert_allclose(np.diagonal(_np(out3)), [1, 1, 1])

    def test_masked_scatter_index_fill(self):
        x = _t(np.zeros(5, np.float32))
        m = _t(np.array([True, False, True, False, True]))
        v = _t(np.array([1.0, 2.0, 3.0], np.float32))
        np.testing.assert_allclose(_np(paddle.masked_scatter(x, m, v)),
                                   [1, 0, 2, 0, 3])
        out = paddle.index_fill(_t(np.zeros((3, 2), np.float32)),
                                _t([0, 2], dtype="int32"), 0, 9.0)
        np.testing.assert_allclose(_np(out)[:, 0], [9, 0, 9])

    def test_combinations_cartesian(self):
        c = _np(paddle.combinations(_t([1.0, 2.0, 3.0]), 2))
        np.testing.assert_allclose(c, [[1, 2], [1, 3], [2, 3]])
        cp = _np(paddle.cartesian_prod([_t([1.0, 2.0]), _t([3.0, 4.0])]))
        np.testing.assert_allclose(cp, [[1, 3], [1, 4], [2, 3], [2, 4]])

    def test_unflatten_unfold_view_as(self):
        x = _t(np.arange(12.0))
        assert list(paddle.unflatten(x, 0, [3, 4]).shape) == [3, 4]
        u = paddle.unfold(x, 0, 4, 4)
        assert list(u.shape) == [3, 4]
        np.testing.assert_allclose(_np(u)[1], [4, 5, 6, 7])
        assert list(paddle.view_as(x, _t(np.zeros((2, 6)))).shape) == [2, 6]

    def test_search_family(self):
        x = _t(np.array([[3.0, 1.0, 2.0], [5.0, 5.0, 0.0]]))
        v, i = paddle.kthvalue(x, 2)
        np.testing.assert_allclose(_np(v), [2.0, 5.0])
        v, i = paddle.mode(x)
        # all-distinct row: ties resolve to smallest (reference _mode1D);
        # index = last occurrence
        np.testing.assert_allclose(_np(v), [1.0, 5.0])
        np.testing.assert_allclose(_np(i), [1, 1])
        cm, ci = paddle.cummin(_t(np.array([3.0, 1.0, 2.0])), axis=0)
        np.testing.assert_allclose(_np(cm), [3, 1, 1])
        np.testing.assert_allclose(_np(ci), [0, 1, 1])

    def test_reduce_as_add_n(self):
        x = _t(np.ones((2, 3, 4), np.float32))
        tgt = _t(np.zeros((3, 1), np.float32))
        assert list(paddle.reduce_as(x, tgt).shape) == [3, 1]
        np.testing.assert_allclose(_np(paddle.reduce_as(x, tgt)),
                                   np.full((3, 1), 8.0))
        s = paddle.add_n([_t([1.0]), _t([2.0]), _t([3.0])])
        np.testing.assert_allclose(_np(s), [6.0])

    def test_pdist_histogramdd(self):
        pts = np.array([[0.0, 0.0], [3.0, 4.0], [0.0, 1.0]], np.float32)
        np.testing.assert_allclose(_np(paddle.pdist(_t(pts))),
                                   [5.0, 1.0, np.sqrt(18)], rtol=1e-6)
        h, edges = paddle.histogramdd(_t(pts), bins=2)
        assert _np(h).sum() == 3


class TestInplaceAndQueries:
    def test_inplace_variants(self):
        x = _t([1.0, 4.0, 9.0])
        paddle.sqrt_(x)
        np.testing.assert_allclose(_np(x), [1, 2, 3])
        y = _t([[1.0, 2.0], [3.0, 4.0]])
        paddle.transpose_(y, [1, 0])
        assert list(y.shape) == [2, 2]
        np.testing.assert_allclose(_np(y), [[1, 3], [2, 4]])
        z = _t([1.0, -1.0])
        paddle.pow_(z, 2.0)
        np.testing.assert_allclose(_np(z), [1, 1])

    def test_inplace_random_fills(self):
        paddle.seed(7)
        x = _t(np.zeros(2000, np.float32))
        paddle.normal_(x, mean=1.0, std=0.5)
        assert abs(float(_np(x).mean()) - 1.0) < 0.05
        paddle.bernoulli_(x, p=0.25)
        assert abs(float(_np(x).mean()) - 0.25) < 0.05

    def test_queries(self):
        x = _t(np.zeros((2, 3), np.float32))
        np.testing.assert_allclose(_np(paddle.shape(x)), [2, 3])
        assert int(_np(paddle.rank(x))) == 2
        assert paddle.is_floating_point(x)
        assert not paddle.is_complex(x)
        assert paddle.tolist(_t([1, 2])) == [1, 2]

    def test_float8_dtypes_and_places(self):
        import jax.numpy as jnp

        assert paddle.float8_e4m3fn is jnp.float8_e4m3fn
        p = paddle.CUDAPlace(0)
        assert "tpu" in repr(p).lower() or p.device_type == "tpu"

    def test_create_parameter(self):
        p = paddle.create_parameter([4, 4], "float32")
        assert not p.stop_gradient and list(p.shape) == [4, 4]
