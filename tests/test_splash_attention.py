"""Splash training attention (ops/pallas/splash_attention.py): kernel
(interpret mode) vs XLA fallback vs dense reference — forward + custom
backward — across causal/non-causal, GQA, and packed-sequence segment
masks; plus the F.scaled_dot_product_attention routing surface."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import splash_attention as sa

HP = jax.lax.Precision.HIGHEST


def _ref(q, k, v, causal, scale, seg=None):
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    grp = h // kvh
    qg = q.reshape(b, sq, kvh, grp, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                   precision=HP).astype(jnp.float32) * scale
    mask = jnp.ones((b, sq, sq), bool)
    if causal:
        mask = mask & jnp.tril(jnp.ones((sq, sq), bool))[None]
    if seg is not None:
        mask = mask & (seg[:, :, None] == seg[:, None, :])
    s = jnp.where(mask[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, -1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(v.dtype), v,
                     precision=HP)
    return out.reshape(b, sq, h, d)


def _rand(b, s, h, kvh, d, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda hh: jnp.asarray(  # noqa: E731
        rng.standard_normal((b, s, hh, d)) * 0.5, dtype)
    return mk(h), mk(kvh), mk(kvh)


def _segments(b, s, docs, seed=0):
    rng = np.random.default_rng(seed)
    bounds = np.sort(rng.integers(1, s, docs - 1))
    return jnp.asarray(np.broadcast_to(
        np.searchsorted(bounds, np.arange(s), side="right"),
        (b, s)).copy(), jnp.int32)


class TestSplashKernel:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("h,kvh", [(2, 2), (4, 2)])
    def test_forward_and_grads_match_dense(self, causal, h, kvh):
        q, k, v = _rand(2, 256, h, kvh, 32)
        scale = 1.0 / 32 ** 0.5
        out = sa.splash_attention(q, k, v, causal=causal, scale=scale,
                                  interpret=True)
        want = _ref(q, k, v, causal, scale)
        assert float(jnp.max(jnp.abs(out - want))) < 3e-5

        def loss_k(q, k, v):
            return jnp.sum(jnp.sin(sa.splash_attention(
                q, k, v, causal=causal, scale=scale, interpret=True)))

        def loss_r(q, k, v):
            return jnp.sum(jnp.sin(_ref(q, k, v, causal, scale)))

        gk = jax.grad(loss_k, (0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
        for a, b in zip(gk, gr):
            assert float(jnp.max(jnp.abs(a - b))) < 5e-4

    @pytest.mark.parametrize("h,kvh", [(2, 2), (2, 1)])
    def test_segment_mask_matches_dense(self, h, kvh):
        q, k, v = _rand(2, 256, h, kvh, 32, seed=3)
        seg = _segments(2, 256, 3, seed=3)
        scale = 0.2
        out = sa.splash_attention(q, k, v, causal=True, scale=scale,
                                  segment_ids=seg, interpret=True)
        want = _ref(q, k, v, True, scale, seg=seg)
        assert float(jnp.max(jnp.abs(out - want))) < 3e-5

        def loss_k(q, k, v):
            return jnp.sum(jnp.sin(sa.splash_attention(
                q, k, v, causal=True, scale=scale, segment_ids=seg,
                interpret=True)))

        def loss_r(q, k, v):
            return jnp.sum(jnp.sin(_ref(q, k, v, True, scale, seg=seg)))

        gk = jax.grad(loss_k, (0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
        for a, b in zip(gk, gr):
            assert float(jnp.max(jnp.abs(a - b))) < 5e-4

    def test_segments_equal_per_document_attention(self):
        """The packed-sequence contract: one splash call over packed
        docs == each document attended separately (out AND grads)."""
        b, s, h, d = 1, 256, 2, 32
        lens = [96, 64, 96]
        q, k, v = _rand(b, s, h, h, d, seed=4)
        seg = jnp.asarray(np.repeat(np.arange(len(lens)), lens)[None],
                          jnp.int32)

        def packed(q, k, v):
            return sa.splash_attention(q, k, v, causal=True,
                                       segment_ids=seg, interpret=True)

        def perdoc(q, k, v):
            outs, off = [], 0
            for ln in lens:
                sl = slice(off, off + ln)
                outs.append(_ref(q[:, sl], k[:, sl], v[:, sl], True,
                                 1.0 / d ** 0.5))
                off += ln
            return jnp.concatenate(outs, axis=1)

        assert float(jnp.max(jnp.abs(
            packed(q, k, v) - perdoc(q, k, v)))) < 3e-5
        gk = jax.grad(lambda *a: jnp.sum(jnp.sin(packed(*a))),
                      (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(jnp.sin(perdoc(*a))),
                      (0, 1, 2))(q, k, v)
        for a, bb in zip(gk, gr):
            assert float(jnp.max(jnp.abs(a - bb))) < 5e-4

    def test_xla_fallback_matches_kernel(self):
        q, k, v = _rand(2, 256, 2, 2, 32, seed=5)
        seg = _segments(2, 256, 2, seed=5)
        out_k = sa.splash_attention(q, k, v, causal=True,
                                    segment_ids=seg, interpret=True)
        out_x = sa.splash_attention(q, k, v, causal=True,
                                    segment_ids=seg, use_kernel=False)
        assert float(jnp.max(jnp.abs(out_k - out_x))) < 3e-5

    def test_bf16(self):
        q, k, v = _rand(1, 256, 2, 2, 32, dtype=jnp.bfloat16, seed=6)
        out = sa.splash_attention(q, k, v, causal=True, interpret=True)
        want = _ref(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), True, 1.0 / 32 ** 0.5)
        assert out.dtype == jnp.bfloat16
        assert float(jnp.max(jnp.abs(
            out.astype(jnp.float32) - want))) < 3e-2

    def test_supports_gate(self):
        assert sa.supports((2, 1024, 8, 64), 8, jnp.bfloat16)
        assert sa.supports((2, 256, 8, 64), 4, jnp.float32)     # GQA
        assert not sa.supports((2, 1021, 8, 64), 8, jnp.float32)
        assert not sa.supports((2, 256, 8, 64), 3, jnp.float32)
        assert not sa.supports((2, 256, 8, 512), 8, jnp.float32)
        assert not sa.supports((2, 256, 8, 64), 8, jnp.int8)


class TestFunctionalRouting:
    def test_sdpa_segments_route_to_splash(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(7)
        qn = rng.standard_normal((1, 256, 2, 32)).astype(np.float32)
        seg = _segments(1, 256, 2, seed=7)
        q = paddle.to_tensor(qn)
        out = F.scaled_dot_product_attention(
            q, q, q, is_causal=True, segment_ids=paddle.to_tensor(
                np.asarray(seg)))
        want = _ref(jnp.asarray(qn), jnp.asarray(qn), jnp.asarray(qn),
                    True, 1.0 / 32 ** 0.5, seg=seg)
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(want), atol=3e-5)

    def test_sdpa_segments_with_dropout_use_dense_mask(self):
        """Dropout forces the dense segment-mask path (splash has no
        dropout plumbing) — output rows still never cross a segment."""
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(8)
        s = 64   # any length: the dense path has no tiling constraint
        qn = rng.standard_normal((1, s, 2, 16)).astype(np.float32)
        vn = np.zeros((1, s, 2, 16), np.float32)
        vn[0, :32] = 1.0    # doc 0's values are 1, doc 1's are 0
        seg = jnp.asarray(np.repeat([0, 1], s // 2)[None], jnp.int32)
        q = paddle.to_tensor(qn)
        v = paddle.to_tensor(vn)
        out = F.scaled_dot_product_attention(
            q, q, v, is_causal=True, dropout_p=0.5, training=True,
            segment_ids=paddle.to_tensor(np.asarray(seg)))
        o = np.asarray(out._data)
        # doc-1 queries can only see doc-1 keys, whose values are all 0
        assert np.abs(o[0, 32:]).max() == 0.0

    def test_segment_context_threads_through_model(self):
        """GPTModel.forward publishes segment_ids to every attention
        layer: packed forward == per-document forward."""
        import paddle_tpu as paddle
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_attention_heads=2,
                        max_position_embeddings=32)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.eval()
        rng = np.random.default_rng(9)
        ids = rng.integers(0, 97, (1, 32))
        seg = np.repeat([0, 1], 16)[None]
        packed = m(paddle.to_tensor(ids, dtype="int64"),
                   segment_ids=paddle.to_tensor(seg, dtype="int32"))
        parts = []
        for sl in (slice(0, 16), slice(16, 32)):
            # per-doc forward at positions matching the packed layout
            pos = paddle.to_tensor(np.arange(32)[None, sl],
                                   dtype="int64")
            parts.append(np.asarray(m(
                paddle.to_tensor(ids[:, sl], dtype="int64"),
                position_ids=pos)._data))
        want = np.concatenate(parts, axis=1)
        np.testing.assert_allclose(np.asarray(packed._data), want,
                                   atol=2e-4)

    def test_sdpa_rejects_mask_plus_segments(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        q = paddle.to_tensor(np.zeros((1, 16, 2, 8), np.float32))
        mask = paddle.to_tensor(np.zeros((1, 1, 16, 16), np.float32))
        seg = paddle.to_tensor(np.zeros((1, 16), np.int32))
        with pytest.raises(ValueError, match="segment_ids"):
            F.scaled_dot_product_attention(q, q, q, attn_mask=mask,
                                           segment_ids=seg)
