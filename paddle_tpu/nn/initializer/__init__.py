"""Weight initializers (python/paddle/nn/initializer/ parity).

Each initializer is a callable (shape, dtype) -> jax array, drawing keys from
the global Generator so `paddle_tpu.seed` makes init reproducible.
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dtype import to_jax_dtype
from ...framework.random import default_generator


from ...framework.random import host_rng as _host_rng  # noqa: E402


def _as_dtype(arr, dtype):
    # the host draw is float64; round ONCE to the target dtype, and do the
    # rounding ON HOST when numpy supports the dtype — transferring f64
    # and casting on device would double the host->device bytes (meaningful
    # for 100M+-param models over a remote-device link)
    jdt = to_jax_dtype(dtype)
    try:
        np_dt = np.dtype(jdt)
        return jnp.asarray(np.asarray(arr, np_dt))
    except TypeError:   # bf16 etc: host-cast to f32, device-cast to target
        return jnp.asarray(np.asarray(arr, np.float32)).astype(jdt)


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) < 2:
        fan_in = fan_out = shape[0] if shape else 1
    else:
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        rng = _host_rng()
        if rng is not None:
            return _as_dtype(
                self.mean + self.std * rng.standard_normal(tuple(shape)),
                dtype)
        key = default_generator().next_key()
        return self.mean + self.std * jax.random.normal(key, tuple(shape), to_jax_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype="float32"):
        rng = _host_rng()
        if rng is not None:
            arr = rng.standard_normal(tuple(shape))
            for _ in range(64):   # resample out-of-bounds draws
                bad = (arr < self.a) | (arr > self.b)
                if not bad.any():
                    break
                arr = np.where(bad, rng.standard_normal(tuple(shape)), arr)
            arr = np.clip(arr, self.a, self.b)
            return _as_dtype(self.mean + self.std * arr, dtype)
        key = default_generator().next_key()
        return self.mean + self.std * jax.random.truncated_normal(
            key, self.a, self.b, tuple(shape), to_jax_dtype(dtype)
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        rng = _host_rng()
        if rng is not None:
            return _as_dtype(rng.uniform(self.low, self.high, tuple(shape)),
                             dtype)
        key = default_generator().next_key()
        return jax.random.uniform(
            key, tuple(shape), to_jax_dtype(dtype), minval=self.low, maxval=self.high
        )


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        rng = _host_rng()
        if rng is not None:
            return _as_dtype(rng.uniform(-limit, limit, tuple(shape)), dtype)
        key = default_generator().next_key()
        return jax.random.uniform(
            key, tuple(shape), to_jax_dtype(dtype), minval=-limit, maxval=limit
        )


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        rng = _host_rng()
        if rng is not None:
            return _as_dtype(std * rng.standard_normal(tuple(shape)), dtype)
        key = default_generator().next_key()
        return std * jax.random.normal(key, tuple(shape), to_jax_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        rng = _host_rng()
        if rng is not None:
            return _as_dtype(rng.uniform(-limit, limit, tuple(shape)), dtype)
        key = default_generator().next_key()
        return jax.random.uniform(
            key, tuple(shape), to_jax_dtype(dtype), minval=-limit, maxval=limit
        )


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        std = gain / math.sqrt(fi)
        rng = _host_rng()
        if rng is not None:
            return _as_dtype(std * rng.standard_normal(tuple(shape)), dtype)
        key = default_generator().next_key()
        return std * jax.random.normal(key, tuple(shape), to_jax_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        from ...framework.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = np.asarray(v._data)
        arr = jnp.asarray(np.asarray(v), to_jax_dtype(dtype)).reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        key = default_generator().next_key()
        return self.gain * jax.nn.initializers.orthogonal()(key, tuple(shape), to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        arr = np.zeros(shape, dtype=np.float32)
        out_c, in_c = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(out_c, in_c * self.groups)):
            idx = (i, i % in_c) + tuple(centers)
            arr[idx] = 1.0
        return jnp.asarray(arr, to_jax_dtype(dtype))


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a**2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


class Bilinear(Initializer):
    """Bilinear-upsample kernel init (reference initializer/Bilinear):
    for ConvTranspose weights [C_out, C_in, k, k] — each spatial kernel
    is the separable triangle filter, the classic learned-upsample
    warm start."""

    def __call__(self, shape, dtype="float32"):
        import numpy as np

        if len(shape) != 4:
            raise ValueError("Bilinear init expects a 4-D conv weight")
        k = shape[-1]
        if shape[-2] != k:
            raise ValueError("Bilinear init expects square kernels")
        # reference semantics (initializer/bilinear.py:116): the SAME
        # (k, k) interpolation kernel for every (out, in) channel pair,
        # with normalized coordinates x/f against center c
        f = np.ceil(k / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        xs = np.arange(k, dtype=np.float32)
        t = 1 - np.abs(xs / f - c)
        filt = t[:, None] * t[None, :]   # symmetric: y/x order is moot
        w = np.broadcast_to(filt, shape).copy().astype(np.float32)
        return _as_dtype(w, dtype)


_GLOBAL_INIT = None


def set_global_initializer(weight_init, bias_init=None):
    """reference set_global_initializer: default initializers for
    subsequently created parameters (consumed by
    Layer.create_parameter via get_global_initializer); pass None to
    restore the framework defaults."""
    global _GLOBAL_INIT
    _GLOBAL_INIT = (None if weight_init is None
                    else (weight_init, bias_init))


def get_global_initializer():
    return _GLOBAL_INIT
