"""Hermetic decode-parity probe: paged == dense == full-sequence forward.

Run as ``python -m paddle_tpu.inference.decode_selftest`` in a clean
JAX_PLATFORMS=cpu subprocess (bench.py --selftest wires this through the
same env-strip recipe as the host-mesh probes) and prints ONE JSON line:

    {"check": "pass", "max_err_dense_vs_full": ..., ...}

so every BENCH_r*.json records that the decode engine's three paths —
dense cache (masked_multihead_attention fast path), paged cache (ragged
paged attention), and the plain full-sequence forward — agree within
fp32 tolerance, and that greedy generate is identical eager vs compiled.
"""
from __future__ import annotations

import json


def run_probe(tol=2e-4):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    rng = np.random.default_rng(0)
    b, s, new = 2, 8, 4
    ids = rng.integers(1, 64, (b, s))
    ids_t = paddle.to_tensor(ids, dtype="int64")

    out_d, log_d = m.generate(ids_t, max_new_tokens=new,
                              use_cache="dense", return_logits=True)
    out_p, log_p = m.generate(ids_t, max_new_tokens=new,
                              use_cache="paged", return_logits=True)
    out_e = m.generate(ids_t, max_new_tokens=new, use_cache="dense",
                       compiled=False)
    out_d = np.asarray(out_d._data)
    log_d = np.asarray(log_d._data, np.float32)
    log_p = np.asarray(log_p._data, np.float32)

    err_full = 0.0
    for i in range(b):
        full = np.concatenate([ids[i], out_d[i][:-1]])
        want = np.asarray(
            m(paddle.to_tensor(full[None], dtype="int64"))._data,
            np.float32)[0]
        for t in range(new):
            err_full = max(err_full, float(np.max(np.abs(
                log_d[i, t] - want[s - 1 + t]))))
    err_paged = float(np.max(np.abs(log_d - log_p)))
    eager_ok = bool((out_d == np.asarray(out_e._data)).all())
    paged_ok = bool((out_d == np.asarray(out_p._data)).all())

    rec = {
        "max_err_dense_vs_full_forward": err_full,
        "max_err_paged_vs_dense": err_paged,
        "greedy_eager_equals_compiled": eager_ok,
        "paged_tokens_equal_dense": paged_ok,
        "tol": tol,
    }
    ok = (err_full < tol and err_paged < tol and eager_ok and paged_ok)
    rec["check"] = "pass" if ok else "FAIL: decode parity out of tol"
    return rec


if __name__ == "__main__":
    print(json.dumps(run_probe()))
