"""paddle.incubate.layers (reference incubate/layers/nn.py): legacy
fused CTR/PS layers (fused_embedding_seq_pool, shuffle_batch,
pull_box_sparse, ...). The parameter-server data stack is descoped
(docs/DECISIONS.md §3); every name resolves to an informative raiser
so ported configs fail with guidance, not AttributeError."""
from __future__ import annotations

from . import nn  # noqa: F401
