"""MoE / expert-parallel tests.

Reference test strategy: parity vs the dense twin (SURVEY.md §4) — with
capacity ∞ and a single expert, MoE output must equal the plain FFN; with
identical experts, any routing gives the dense answer (switch gate weights
sum handled separately).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.incubate.distributed.models.moe import (
    ExpertFFN, MoELayer, top1_gating, top2_gating,
)


def _x(b=2, s=8, h=16, seed=0):
    rng = np.random.default_rng(seed)
    return paddle.to_tensor(rng.standard_normal((b, s, h)).astype("float32"),
                            stop_gradient=False)


class TestGating:
    def test_top1_masks(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((12, 4)), jnp.float32)
        combine, dispatch, aux = top1_gating(logits, capacity=12)
        # no drops at full capacity: every token dispatched exactly once
        assert float(jnp.sum(dispatch.astype(jnp.int32))) == 12
        # combine weight of each token == its max softmax prob
        probs = jax.nn.softmax(logits, axis=-1)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(combine, axis=(1, 2))),
            np.asarray(jnp.max(probs, axis=-1)), rtol=1e-6)
        assert float(aux) > 0

    def test_top2_weights_normalized(self):
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.standard_normal((10, 4)), jnp.float32)
        combine, dispatch, aux = top2_gating(logits, capacity=10)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(combine, axis=(1, 2))), 1.0, rtol=1e-5)

    def test_capacity_drops(self):
        # all tokens prefer expert 0; capacity 2 keeps exactly 2
        logits = jnp.tile(jnp.asarray([[10.0, 0.0]]), (6, 1))
        combine, dispatch, aux = top1_gating(logits, capacity=2)
        assert float(jnp.sum(dispatch[:, 0].astype(jnp.int32))) == 2


class TestGlobalScatterGather:
    @pytest.fixture(autouse=True)
    def _clean_mesh(self):
        from paddle_tpu.distributed import env as denv

        yield
        denv.reset()

    def _ep_group(self, n=2):
        from paddle_tpu.distributed import env as denv
        from paddle_tpu.distributed.collective import new_group

        mesh = denv.build_mesh({"ep": n}, devices=jax.devices("cpu")[:n])
        denv.set_mesh(mesh)
        return new_group(axes=["ep"], mesh=mesh)

    def test_ragged_counts_exchange(self):
        """ISSUE 9 satellite: ragged per-expert counts ride the
        capacity-padded equal-split exchange instead of raising —
        checked against a numpy model of the reference exchange."""
        from paddle_tpu.incubate.distributed.models.moe import moe_layer

        grp = self._ep_group(2)
        counts = paddle.to_tensor(np.array([3, 1], np.int64))
        S = 4
        x = paddle.to_tensor(
            np.arange(2 * S * 2, dtype=np.float32).reshape(2 * S, 2))
        out = moe_layer.global_scatter(x, counts, counts, group=grp)
        # numpy reference: rank r receives, source-major, the
        # counts[r] rows each source sent it (destination-major send)
        xa = np.asarray(x._data)
        lc, off = np.array([3, 1]), [0, 3]
        ref = np.concatenate([
            xa[s * S + off[r]: s * S + off[r] + lc[r]]
            for r in range(2) for s in range(2)])
        np.testing.assert_allclose(np.asarray(out._data), ref)

    def test_ragged_roundtrip(self):
        """gather(scatter(x)) == x for ragged counts (the inverse-map
        contract), incl. zero-count buckets and multi-expert groups."""
        from paddle_tpu.incubate.distributed.models.moe import moe_layer

        grp = self._ep_group(2)
        for raw in ([2, 0], [4, 1, 2, 3]):
            counts = paddle.to_tensor(np.array(raw, np.int64))
            S = int(np.sum(raw))
            x = paddle.to_tensor(np.random.default_rng(0)
                                 .standard_normal((2 * S, 3))
                                 .astype(np.float32))
            out = moe_layer.global_scatter(x, counts, counts, group=grp)
            assert tuple(out.shape) == tuple(x.shape)
            back = moe_layer.global_gather(out, counts, counts,
                                           group=grp)
            np.testing.assert_allclose(np.asarray(back._data),
                                       np.asarray(x._data),
                                       err_msg=str(raw))

    def test_disagreeing_counts_raise(self):
        """Genuinely unsupported group shape: per-rank-distinct count
        vectors are not representable in the single-controller global
        view — a clear ValueError, not silence."""
        from paddle_tpu.incubate.distributed.models.moe import moe_layer

        grp = self._ep_group(2)
        x = paddle.to_tensor(np.ones((8, 2), np.float32))
        lc = paddle.to_tensor(np.array([3, 1], np.int64))
        gc = paddle.to_tensor(np.array([1, 3], np.int64))
        with pytest.raises(ValueError, match="disagree"):
            moe_layer.global_scatter(x, lc, gc, group=grp)

    def test_traced_counts_raise(self):
        from paddle_tpu.framework.tensor import Tensor
        from paddle_tpu.incubate.distributed.models.moe import moe_layer

        grp = self._ep_group(2)
        x = paddle.to_tensor(np.ones((8, 2), np.float32))

        def f(c):
            return moe_layer.global_scatter(
                x, Tensor._wrap(c), Tensor._wrap(c), group=grp)._data

        with pytest.raises(NotImplementedError, match="traced"):
            jax.jit(f)(jnp.asarray(np.array([3, 1], np.int64)))

    def test_counts_length_not_multiple_raises(self):
        from paddle_tpu.incubate.distributed.models.moe import moe_layer

        grp = self._ep_group(2)
        x = paddle.to_tensor(np.ones((6, 2), np.float32))
        c = paddle.to_tensor(np.array([1, 1, 1], np.int64))
        with pytest.raises(ValueError, match="not a multiple"):
            moe_layer.global_scatter(x, c, c, group=grp)

    def test_mismatched_totals_raise(self):
        from paddle_tpu.incubate.distributed.models.moe import moe_layer

        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        lc = paddle.to_tensor(np.array([2, 2], np.int64))
        gc = paddle.to_tensor(np.array([1, 1], np.int64))
        with pytest.raises(ValueError, match="lose tokens"):
            moe_layer.global_scatter(x, lc, gc)

    def test_uniform_counts_exchange(self):
        """Uniform counts describe exactly the equal-split all_to_all."""
        from paddle_tpu.distributed import env as denv
        from paddle_tpu.incubate.distributed.models.moe import moe_layer

        mesh = denv.build_mesh({"ep": 2}, devices=jax.devices("cpu")[:2])
        prev = denv.get_mesh() if denv.is_initialized() else None
        denv.set_mesh(mesh)
        try:
            from paddle_tpu.distributed.collective import new_group

            grp = new_group(axes=["ep"], mesh=mesh)
            from jax.sharding import NamedSharding, PartitionSpec as P

            x = paddle.to_tensor(
                np.arange(8, dtype=np.float32).reshape(4, 2))
            # rank-sharded leading dim (the per-rank concat layout)
            x._data = jax.device_put(x._data,
                                     NamedSharding(mesh, P("ep", None)))
            uniform = paddle.to_tensor(np.array([1, 1], np.int64))
            out = moe_layer.global_scatter(x, uniform, uniform, group=grp)
            # all_to_all swaps the middle blocks (rank-major regrouping)
            want = np.asarray(x._data).reshape(2, 2, 2).swapaxes(0, 1) \
                .reshape(4, 2)
            np.testing.assert_allclose(np.asarray(out._data), want)
            back = moe_layer.global_gather(out, uniform, uniform, group=grp)
            np.testing.assert_allclose(np.asarray(back._data),
                                       np.asarray(x._data))
        finally:
            if prev is not None:
                denv.set_mesh(prev)


class TestMoELayer:
    def test_identical_experts_match_dense(self):
        """All experts share weights -> MoE(top-2 normalized) == dense FFN."""
        paddle.seed(3)
        dense = ExpertFFN(16, 32)
        experts = [ExpertFFN(16, 32) for _ in range(4)]
        sd = dense.state_dict()
        for e in experts:
            e.set_state_dict(sd)
        moe = MoELayer(16, experts, gate="gshard",
                       capacity_factor=float("inf"))
        x = _x()
        np.testing.assert_allclose(
            np.asarray(moe(x)._data), np.asarray(dense(x)._data),
            atol=1e-5)
        assert moe.l_aux is not None and float(moe.l_aux) > 0

    def test_backward_flows_to_experts_and_gate(self):
        paddle.seed(4)
        experts = [ExpertFFN(16, 32) for _ in range(4)]
        moe = MoELayer(16, experts, gate="switch", capacity_factor=2.0)
        x = _x(seed=5)
        out = moe(x)
        (out.sum() + moe.l_aux).backward()
        assert moe.gate_weight.grad is not None
        g = moe._parameters["experts__fc1__weight"].grad
        assert g is not None and g.shape[0] == 4
        assert x.grad is not None

    def test_ep_sharded_matches_unsharded(self):
        """Expert-parallel over ep=4 gives the same numbers as no mesh."""
        from paddle_tpu.distributed import env as denv

        paddle.seed(6)
        experts = [ExpertFFN(16, 32) for _ in range(4)]
        moe = MoELayer(16, experts, gate="gshard", capacity_factor=4.0)
        x = _x(seed=7)
        ref = np.asarray(moe(x)._data)

        mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("ep",))
        paddle.seed(6)
        experts2 = [ExpertFFN(16, 32) for _ in range(4)]
        moe2 = MoELayer(16, experts2, gate="gshard", capacity_factor=4.0,
                        mesh=mesh)
        # stacked params actually sharded over ep
        p = moe2._parameters["experts__fc1__weight"]
        assert "ep" in str(p._data.sharding)
        out = np.asarray(moe2(x)._data)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_capacity_overflow_drops_tokens(self):
        """Reference drop semantics: tokens over an expert's capacity get
        zero combine weight, so their layer output is exactly zero."""
        paddle.seed(3)
        layer = MoELayer(16, [ExpertFFN(16, 16) for _ in range(2)],
                         gate="switch", capacity_factor=2 / 16)  # 1 slot
        x = _x(b=1, s=16, seed=4)
        y = layer(x)
        out = np.asarray(y._data).reshape(16, 16)
        zero_rows = np.sum(np.all(np.abs(out) < 1e-7, axis=-1))
        # 16 tokens, 2 experts x 1 slot -> at least 14 dropped (exactly,
        # unless a token ties); drops are zeros, not garbage
        assert zero_rows >= 14
        assert np.all(np.isfinite(out))

    def test_train_step_with_moe(self):
        """MoE composes with the fused TrainStep (jit path)."""
        import paddle_tpu.optimizer as popt
        from paddle_tpu.jit import TrainStep
        import paddle_tpu.nn as nn

        paddle.seed(8)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.moe = MoELayer(16, [ExpertFFN(16, 32) for _ in range(2)],
                                    gate="switch", capacity_factor=2.0)
                self.head = nn.Linear(16, 4)

            def forward(self, x):
                return self.head(self.moe(x))

        net = Net()
        loss_fn = nn.CrossEntropyLoss()

        def loss(m, x, y):
            out = m(x).reshape([-1, 4])
            return loss_fn(out, y) + 0.01 * m.moe.l_aux

        opt = popt.AdamW(learning_rate=1e-3, parameters=net.parameters())
        step = TrainStep(net, loss, opt)
        x = _x(seed=9)
        y = paddle.to_tensor(
            np.random.default_rng(10).integers(0, 4, (16,)), dtype="int64")
        losses = [float(step(x, y)) for _ in range(3)]
        assert losses[-1] < losses[0]
        assert np.all(np.isfinite(losses))


class TestMoEGradClip:
    """VERDICT r4 weak #9 / next #7: global-norm clip over EP-sharded
    experts must count every expert's norm exactly once — proven by
    parity against the dense (unsharded) equivalent, and exposed under
    the reference API name (ClipGradForMOEByGlobalNorm)."""

    def _clip_run(self, mesh):
        from paddle_tpu.incubate.distributed.models.moe import (
            ClipGradForMOEByGlobalNorm,
        )

        paddle.seed(11)
        experts = [ExpertFFN(16, 32) for _ in range(4)]
        moe = MoELayer(16, experts, gate="switch", capacity_factor=4.0,
                       mesh=mesh)
        x = _x(seed=12)
        loss = (moe(x) ** 2).mean()
        loss.backward()
        pgs = [(p, p.grad) for p in moe.parameters()
               if p.grad is not None]
        clip = ClipGradForMOEByGlobalNorm(
            0.05, is_expert_param_func=lambda p: "experts__" in (p.name
                                                                 or ""))
        clipped = dict((id(p), g) for p, g in clip(pgs))
        import jax.numpy as jnp
        norm = float(jnp.sqrt(sum(
            jnp.sum(jnp.square(g._data.astype(jnp.float32)))
            for _, g in pgs)))
        return norm, {n: np.asarray(clipped[id(p)]._data, np.float32)
                      for n, p in moe.named_parameters()
                      if id(p) in clipped}

    def test_ep_clip_matches_dense(self):
        n_dense, g_dense = self._clip_run(mesh=None)
        mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("ep",))
        n_ep, g_ep = self._clip_run(mesh=mesh)
        np.testing.assert_allclose(n_ep, n_dense, rtol=1e-5)
        assert set(g_ep) == set(g_dense)
        for k in g_dense:
            np.testing.assert_allclose(g_ep[k], g_dense[k], atol=1e-6,
                                       err_msg=k)
        # and the clip actually clipped (norm above the 0.05 bound)
        assert n_dense > 0.05


class TestExpertParallelDispatch:
    """ISSUE 9: the REAL expert-parallel path — sliced expert stacks
    inside a shard_map binding the ep axis flip MoELayer onto explicit
    capacity-padded lax.all_to_all dispatch/combine."""

    @pytest.fixture(autouse=True)
    def _clean_mesh(self):
        from paddle_tpu.distributed import env as denv

        denv.reset()
        yield
        denv.reset()

    def _ep_forward(self, moe, x, ep=2):
        from jax.sharding import Mesh, PartitionSpec as P

        from paddle_tpu.framework.tensor import Tensor

        mesh = Mesh(np.array(jax.devices("cpu")[:ep]), ("ep",))
        leaves = [moe._parameters[f]._data for f, _ in
                  moe._stacked_names]
        params = [moe._parameters[f] for f, _ in moe._stacked_names]
        gw = moe.gate_weight._data

        def f(xl, gwl, *lv):
            saved = [p._data for p in params]
            saved_g = moe.gate_weight._data
            for p, d in zip(params, lv):
                p._data = d
            moe.gate_weight._data = gwl
            try:
                y = moe.forward(Tensor._wrap(xl))._data
                aux = moe.l_aux._data
            finally:
                for p, d in zip(params, saved):
                    p._data = d
                moe.gate_weight._data = saved_g
            return y, aux

        sm = jax.jit(jax.shard_map(
            f, mesh=mesh,
            in_specs=(P("ep"), P(), *[P("ep") for _ in leaves]),
            out_specs=(P("ep"), P()), check_vma=False))
        return sm, (x, gw, *leaves)

    def test_dispatch_combine_roundtrip_matches_dense(self):
        """EP output == per-shard dense routing, bit-for-bit: the
        all_to_all dispatch/combine is a pure re-homing of the same
        expert computation."""
        paddle.seed(20)
        moe = MoELayer(16, [ExpertFFN(16, 32) for _ in range(4)],
                       gate="gshard", capacity_factor=2.0)
        rng = np.random.default_rng(21)
        x = rng.standard_normal((4, 8, 16)).astype(np.float32)
        ref = np.concatenate(
            [np.asarray(moe(paddle.to_tensor(x[i * 2:(i + 1) * 2]))
                        ._data) for i in range(2)])
        sm, args = self._ep_forward(moe, x)
        y, aux = sm(*args)
        np.testing.assert_array_equal(np.asarray(y), ref)
        assert np.isfinite(float(aux))

    def test_ep_hlo_has_all_to_alls(self):
        paddle.seed(22)
        moe = MoELayer(16, [ExpertFFN(16, 32) for _ in range(4)],
                       gate="switch", capacity_factor=2.0)
        x = np.zeros((4, 8, 16), np.float32)
        sm, args = self._ep_forward(moe, x)
        txt = sm.lower(*args).compile().as_text()
        # dispatch + combine >= 2 ep all-to-alls
        assert txt.count("all-to-all(") >= 2

    def test_capacity_drop_determinism(self):
        """Same inputs -> identical routing and outputs across repeated
        EP forwards (drops are a pure function of the gate cumsum, no
        RNG)."""
        paddle.seed(23)
        moe = MoELayer(8, [ExpertFFN(8, 16) for _ in range(2)],
                       gate="switch", capacity_factor=0.25)
        rng = np.random.default_rng(24)
        x = rng.standard_normal((2, 16, 8)).astype(np.float32)
        sm, args = self._ep_forward(moe, x)
        y1, _ = sm(*args)
        y2, _ = sm(*args)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
        # drops actually happened (zero rows) and are zeros, not garbage
        out = np.asarray(y1).reshape(-1, 8)
        assert np.sum(np.all(np.abs(out) < 1e-7, axis=-1)) > 0
        assert np.isfinite(out).all()

    def test_ep_degree_validation(self):
        with pytest.raises(ValueError, match="divisible"):
            MoELayer(8, [ExpertFFN(8, 8) for _ in range(3)],
                     ep_degree=2)


class TestMoEGlobalMeshTensor:
    @pytest.fixture(autouse=True)
    def _clean_mesh(self):
        from paddle_tpu.distributed import env as denv

        yield
        denv.reset()

    def test_assembles_and_shards(self):
        """The planted NotImplementedError is gone: per-EP-rank expert
        slices assemble into one global tensor sharded over ep."""
        from paddle_tpu.distributed.auto_parallel import (
            ProcessMesh, Replicate, Shard, moe_global_mesh_tensor,
        )
        from paddle_tpu.distributed import env as denv

        denv.set_mesh(denv.build_mesh(
            {"ep": 2}, devices=jax.devices("cpu")[:2]))
        mesh = ProcessMesh(np.arange(2).reshape(2), ["ep"])
        locals_ = [paddle.to_tensor(np.full((2, 4), float(r),
                                            np.float32))
                   for r in range(2)]
        out = moe_global_mesh_tensor(locals_, mesh,
                                     [Shard(0)], local_mesh_dim="ep")
        assert tuple(out.shape) == (4, 4)
        got = np.asarray(out._data)
        np.testing.assert_allclose(got[:2], 0.0)
        np.testing.assert_allclose(got[2:], 1.0)
        assert "ep" in str(out._data.sharding)

    def test_replicate_placement_rejected(self):
        from paddle_tpu.distributed.auto_parallel import (
            ProcessMesh, Replicate, moe_global_mesh_tensor,
        )

        mesh = ProcessMesh(np.arange(2).reshape(2), ["ep"])
        with pytest.raises(ValueError, match="Shard"):
            moe_global_mesh_tensor(
                [paddle.to_tensor(np.zeros((2, 2), np.float32))] * 2,
                mesh, [Replicate()])


class TestMoEScanTrainStep:
    """MoEBlock inside FusedScanTrainStep/ShardedFusedScanTrainStep
    (ISSUE 9 acceptance): dp×ep == dp-only dense-equivalent routing
    <= 1e-5 over 4 steps, one compile per signature, aux loss folded
    into the training loss."""

    TINY = dict(vocab_size=96, hidden_size=32, num_layers=2,
                num_attention_heads=2, max_position_embeddings=16,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                num_experts=4, moe_capacity_factor=2.0)

    def _data(self, rows=8):
        rng = np.random.default_rng(30)
        ids = paddle.to_tensor(rng.integers(0, 96, (rows, 8)),
                               dtype="int64")
        labels = paddle.to_tensor(rng.integers(0, 96, (rows, 8)),
                                  dtype="int64")
        return ids, labels

    def _build_sharded(self, mesh, steps=4, **kw):
        import paddle_tpu.optimizer as popt
        from paddle_tpu.distributed import env as denv
        from paddle_tpu.jit.sharded_scan import ShardedFusedScanTrainStep
        from paddle_tpu.models import (
            GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
        )
        import paddle_tpu.nn as nn

        cfg = GPTConfig(**self.TINY, scan_layers=True)
        paddle.seed(31)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=1e-2,
                         parameters=model.parameters(),
                         grad_clip=nn.ClipGradByGlobalNorm(0.05))
        denv.set_mesh(mesh)
        step = ShardedFusedScanTrainStep(
            model, opt, criterion=GPTPretrainingCriterion(), mesh=mesh,
            **kw)
        ids, labels = self._data()
        losses = [float(step(ids, labels)) for _ in range(steps)]
        return losses, model, step

    def test_dp_ep_matches_dp_only(self):
        """The acceptance triangle: dp4×ep2 (real all_to_all expert
        parallelism) == dp8 (dense-equivalent routing: same per-rank
        token pools, full expert stacks everywhere)."""
        from jax.sharding import Mesh

        devs = jax.devices("cpu")[:8]
        ref, m_ref, s_ref = self._build_sharded(
            Mesh(np.array(devs), ("sharding",)), axis="sharding")
        epl, m_ep, s_ep = self._build_sharded(
            Mesh(np.array(devs).reshape(4, 2), ("dp", "ep")),
            axis="dp", ep_axis="ep")
        diff = max(abs(a - b) for a, b in zip(ref, epl))
        assert diff <= 1e-5, (ref, epl)
        # exactly one compiled executable per mesh signature
        assert s_ref._jitted._cache_size() == 1
        assert s_ep._jitted._cache_size() == 1
        # final params agree too (the grads assembled identically)
        for (n1, p1), (_, p2) in zip(m_ref.named_parameters(),
                                     m_ep.named_parameters()):
            np.testing.assert_allclose(
                np.asarray(p1._data, np.float32),
                np.asarray(p2._data, np.float32),
                rtol=5e-3, atol=5e-5, err_msg=n1)

    @pytest.mark.slow
    def test_ep_step_hlo_all_to_all_count(self):
        """>= 2 ep-axis all-to-alls counted by tools/hlo_overlap.py's
        per-axis classifier (the ISSUE acceptance receipt). Marked slow:
        the hermetic `moe` selftest lane asserts the same census on
        every bench run (tier-1 keeps the parity + compile probes)."""
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from paddle_tpu.jit.sharded_scan_selftest import (
            _load_hlo_overlap,
        )

        devs = jax.devices("cpu")[:8]
        _, _, step = self._build_sharded(
            Mesh(np.array(devs).reshape(4, 2), ("dp", "ep")),
            steps=1, axis="dp", ep_axis="ep")
        ids, labels = self._data()
        state = step._extract_state()
        txt = step._jitted.lower(
            state, jnp.float32(1e-2), ids._data, labels._data,
            None).compile().as_text()
        v = _load_hlo_overlap().analyze(
            txt, axis_degrees={"dp": 4, "ep": 2})
        ep_counts = v["per_axis_counts"].get("ep", {})
        assert ep_counts.get("all-to-all", 0) >= 2, v["per_axis_counts"]
        # grads scatter over the flattened dp×ep product, nothing
        # unclassified
        assert "other" not in v["per_axis_counts"]

    def test_aux_loss_in_fused_step_matches_eager(self):
        """Single-device FusedScanTrainStep loss == eager
        model.loss() (CE + weighted layer-mean aux) on the same model —
        the aux plumbing through the scan carries the exact value."""
        import paddle_tpu.optimizer as popt
        from paddle_tpu.jit.fused_scan_step import FusedScanTrainStep
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(**self.TINY, scan_layers=True)
        paddle.seed(33)
        model = GPTForCausalLM(cfg)
        ids, labels = self._data(rows=4)
        eager = float(model.loss(ids, labels))
        opt = popt.AdamW(learning_rate=0.0,
                         parameters=model.parameters())
        step = FusedScanTrainStep(model, opt)
        got = float(step(ids, labels))
        assert abs(got - eager) < 1e-5, (got, eager)

    def test_moe_under_pipeline_rejected(self):
        import paddle_tpu.optimizer as popt
        from paddle_tpu.distributed import env as denv
        from paddle_tpu.jit.pipeline_step import PipelineScanTrainStep
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(**self.TINY, scan_layers=True)
        paddle.seed(34)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=1e-2,
                         parameters=model.parameters())
        mesh = denv.build_mesh({"dp": 2, "pp": 2},
                               devices=jax.devices("cpu")[:4])
        with pytest.raises(ValueError, match="MoE"):
            PipelineScanTrainStep(model, opt, mesh=mesh, num_micro=2)

    def test_ep_axis_on_dense_model_rejected(self):
        import paddle_tpu.optimizer as popt
        from jax.sharding import Mesh
        from paddle_tpu.jit.sharded_scan import ShardedFusedScanTrainStep
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        tiny = dict(self.TINY)
        tiny["num_experts"] = 0
        cfg = GPTConfig(**tiny, scan_layers=True)
        paddle.seed(35)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=1e-2,
                         parameters=model.parameters())
        mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(4, 2),
                    ("dp", "ep"))
        with pytest.raises(ValueError, match="no MoE"):
            ShardedFusedScanTrainStep(model, opt, mesh=mesh, axis="dp",
                                      ep_axis="ep")

    def test_select_train_step_dispatches_ep(self):
        import paddle_tpu.optimizer as popt
        from jax.sharding import Mesh
        from paddle_tpu.distributed import env as denv
        from paddle_tpu.jit.sharded_scan import (
            ShardedFusedScanTrainStep, select_train_step,
        )
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(**self.TINY, scan_layers=True)
        paddle.seed(36)
        model = GPTForCausalLM(cfg)
        opt = popt.AdamW(learning_rate=1e-2,
                         parameters=model.parameters())
        mesh = Mesh(np.array(jax.devices("cpu")[:8]).reshape(4, 2),
                    ("dp", "ep"))
        denv.set_mesh(mesh)
        step = select_train_step(model, opt, mesh=mesh)
        assert isinstance(step, ShardedFusedScanTrainStep)
        assert step._ep_axis == "ep" and step._ep_degree == 2
        assert step._batch_degree == 8


class TestAuxLossValue:
    """Aux-loss value vs an independent numpy model of the GShard
    formula (E * sum(mean_prob * frac_routed), switch eq. 4)."""

    def test_top1_aux_vs_numpy(self):
        import scipy.special as sps

        rng = np.random.default_rng(40)
        logits = rng.standard_normal((24, 4)).astype(np.float32)
        _, _, aux = top1_gating(jnp.asarray(logits), capacity=24)
        probs = sps.softmax(logits, axis=-1)
        sel = np.eye(4)[np.argmax(probs, axis=-1)]
        want = 4 * np.sum(probs.mean(0) * sel.mean(0))
        np.testing.assert_allclose(float(aux), want, rtol=1e-5)

    def test_top2_aux_vs_numpy(self):
        import scipy.special as sps

        rng = np.random.default_rng(41)
        logits = rng.standard_normal((16, 4)).astype(np.float32)
        _, _, aux = top2_gating(jnp.asarray(logits), capacity=16)
        probs = sps.softmax(logits, axis=-1)
        sel = np.eye(4)[np.argmax(probs, axis=-1)]   # first choice
        want = 4 * np.sum(probs.mean(0) * sel.mean(0))
        np.testing.assert_allclose(float(aux), want, rtol=1e-5)


class TestFusedMoEFunctional:
    """r5 (VERDICT r4 missing #5 tail): fused_moe vs an independent
    numpy Mixtral-style reference (softmax-all -> topk -> renorm ->
    SwiGLU experts -> combine)."""

    def _np_ref(self, x, gw, w1, b1, w2, b2, topk, norm):
        import scipy.special as sps

        b, s, d = x.shape
        t = b * s
        xt = x.reshape(t, d)
        probs = sps.softmax(xt @ gw, axis=-1)
        E = gw.shape[-1]
        out = np.zeros((t, d), np.float32)
        for ti in range(t):
            sel = np.argsort(-probs[ti])[:topk]
            w = probs[ti, sel]
            if norm:
                w = w / w.sum()
            for wi, e in zip(w, sel):
                h1 = xt[ti] @ w1[e] + b1[e, 0]
                g, u = np.split(h1, 2)
                hs = g * sps.expit(g) * u
                out[ti] += wi * (hs @ w2[e] + b2[e, 0])
        return out.reshape(b, s, d)

    def test_matches_numpy(self):
        from paddle_tpu.incubate.nn.functional import fused_moe

        rng = np.random.default_rng(0)
        b, s, d, ff, E = 2, 3, 8, 16, 4
        x = rng.standard_normal((b, s, d)).astype(np.float32) * 0.5
        gw = rng.standard_normal((d, E)).astype(np.float32) * 0.5
        w1 = rng.standard_normal((E, d, 2 * ff)).astype(np.float32) * 0.2
        b1 = rng.standard_normal((E, 1, 2 * ff)).astype(np.float32) * 0.1
        w2 = rng.standard_normal((E, ff, d)).astype(np.float32) * 0.2
        b2 = rng.standard_normal((E, 1, d)).astype(np.float32) * 0.1
        for norm in (True, False):
            got = fused_moe(paddle.to_tensor(x), paddle.to_tensor(gw),
                            paddle.to_tensor(w1), paddle.to_tensor(b1),
                            paddle.to_tensor(w2), paddle.to_tensor(b2),
                            moe_topk=2, norm_topk_prob=norm)
            want = self._np_ref(x, gw, w1, b1, w2, b2, 2, norm)
            np.testing.assert_allclose(np.asarray(got._data), want,
                                       rtol=1e-4, atol=1e-5)

    def test_grads_flow(self):
        from paddle_tpu.incubate.nn.functional import fused_moe

        rng = np.random.default_rng(1)
        x = paddle.to_tensor(
            rng.standard_normal((1, 4, 8)).astype(np.float32),
            stop_gradient=False)
        gw = paddle.to_tensor(
            rng.standard_normal((8, 3)).astype(np.float32),
            stop_gradient=False)
        w1 = paddle.to_tensor(
            rng.standard_normal((3, 8, 8)).astype(np.float32) * 0.3,
            stop_gradient=False)
        b1 = paddle.to_tensor(np.zeros((3, 1, 8), np.float32))
        w2 = paddle.to_tensor(
            rng.standard_normal((3, 4, 8)).astype(np.float32) * 0.3,
            stop_gradient=False)
        b2 = paddle.to_tensor(np.zeros((3, 1, 8), np.float32))
        out = fused_moe(x, gw, w1, b1, w2, b2, moe_topk=1)
        (out ** 2).mean().backward()
        assert x.grad is not None and w1.grad is not None
        assert np.isfinite(np.asarray(w1.grad._data)).all()


class TestFusedEcMoe:
    """r5: expert-choice MoE vs an independent numpy model of the
    reference baseline (test_fused_ec_moe_op.py semantics: each expert
    takes its top-(s//16) tokens by logit, weights by softmax prob,
    residual add)."""

    def _np_ref(self, x, g, w0, b0, w1, b1, act):
        import scipy.special as sps

        b, s, d = x.shape
        e = g.shape[-1]
        cap = max(s // 16, 1)
        gates = sps.softmax(g, axis=-1)
        out = x.copy()
        for bi in range(b):
            for ei in range(e):
                top = np.argsort(-g[bi, :, ei], kind="stable")[:cap]
                for t in top:
                    h = x[bi, t] @ w0[ei] + b0[ei, 0]
                    h = (h * 0.5 * (1 + sps.erf(h / np.sqrt(2)))
                         if act == "gelu" else np.maximum(h, 0))
                    o = h @ w1[ei] + b1[ei, 0]
                    out[bi, t] += gates[bi, t, ei] * o
        return out

    def test_matches_numpy(self):
        from paddle_tpu.incubate.nn.functional import fused_ec_moe

        rng = np.random.default_rng(3)
        b, s, d, ff, e = 2, 32, 8, 16, 4
        x = rng.standard_normal((b, s, d)).astype(np.float32) * 0.3
        g = rng.standard_normal((b, s, e)).astype(np.float32)
        w0 = rng.standard_normal((e, d, ff)).astype(np.float32) * 0.2
        b0 = rng.standard_normal((e, 1, ff)).astype(np.float32) * 0.1
        w1 = rng.standard_normal((e, ff, d)).astype(np.float32) * 0.2
        b1 = rng.standard_normal((e, 1, d)).astype(np.float32) * 0.1
        for act in ("gelu", "relu"):
            got = fused_ec_moe(paddle.to_tensor(x), paddle.to_tensor(g),
                               paddle.to_tensor(w0), paddle.to_tensor(b0),
                               paddle.to_tensor(w1), paddle.to_tensor(b1),
                               act_type=act)
            want = self._np_ref(x, g, w0, b0, w1, b1, act)
            np.testing.assert_allclose(np.asarray(got._data), want,
                                       rtol=1e-4, atol=1e-5,
                                       err_msg=act)

    def test_layer_and_grads(self):
        from paddle_tpu.incubate.nn import FusedEcMoe

        paddle.seed(0)
        layer = FusedEcMoe(8, 16, 4, act_type="relu")
        rng = np.random.default_rng(4)
        x = paddle.to_tensor(
            rng.standard_normal((1, 32, 8)).astype(np.float32),
            stop_gradient=False)
        g = paddle.to_tensor(
            rng.standard_normal((1, 32, 4)).astype(np.float32))
        out = layer(x, g)
        assert tuple(out.shape) == (1, 32, 8)
        (out ** 2).mean().backward()
        assert layer.bmm_weight0.grad is not None
