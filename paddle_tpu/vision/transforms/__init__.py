"""Image transforms.

Reference parity: python/paddle/vision/transforms/ (transforms.py +
functional.py). Numpy/ndarray implementations (HWC uint8 in, as the
reference's 'backend=cv2/pil' paths); ToTensor produces CHW float32.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

from ...framework.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class ToTensor(BaseTransform):
    """HWC [0,255] → CHW float32 [0,1] (reference functional.to_tensor)."""

    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _as_hwc(img)
        was_int = np.issubdtype(img.dtype, np.integer)
        img = img.astype(np.float32)
        if was_int:
            img = img / 255.0
        if self.data_format == "CHW":
            img = img.transpose(2, 0, 1)
        return Tensor(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            arr = np.asarray(img._data)
        else:
            arr = np.asarray(img, np.float32)
        n = self.mean.shape[0]
        if self.data_format == "CHW":
            shape = (n,) + (1,) * (arr.ndim - 1)
        else:
            shape = (1,) * (arr.ndim - 1) + (n,)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = self.size
        ih, iw = img.shape[:2]
        yi = (np.arange(h) + 0.5) * ih / h - 0.5
        xi = (np.arange(w) + 0.5) * iw / w - 0.5
        yi = np.clip(yi, 0, ih - 1)
        xi = np.clip(xi, 0, iw - 1)
        y0 = np.floor(yi).astype(int)
        x0 = np.floor(xi).astype(int)
        y1 = np.minimum(y0 + 1, ih - 1)
        x1 = np.minimum(x0 + 1, iw - 1)
        wy = (yi - y0)[:, None, None]
        wx = (xi - x0)[None, :, None]
        orig_dtype = img.dtype
        img = img.astype(np.float32)
        top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
        bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
        return (top * (1 - wy) + bot * wy).astype(orig_dtype)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = self.size
        ih, iw = img.shape[:2]
        top = max(0, (ih - h) // 2)
        left = max(0, (iw - w) // 2)
        return img[top:top + h, left:left + w]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding
            p = (p, p) if isinstance(p, int) else p
            img = np.pad(img, ((p[0], p[0]), (p[1], p[1]), (0, 0)))
        h, w = self.size
        ih, iw = img.shape[:2]
        top = random.randint(0, max(0, ih - h))
        left = random.randint(0, max(0, iw - w))
        return img[top:top + h, left:left + w]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[:, ::-1]
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _as_hwc(img)[::-1]
        return _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size)

    def _apply_image(self, img):
        img = _as_hwc(img)
        ih, iw = img.shape[:2]
        area = ih * iw
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= iw and 0 < h <= ih:
                top = random.randint(0, ih - h)
                left = random.randint(0, iw - w)
                return self._resize._apply_image(img[top:top + h,
                                                     left:left + w])
        return self._resize._apply_image(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


# ---------------------------------------------------------------------------
# r5: photometric + geometric batch completing the reference
# vision/transforms surface (functional forms + class wrappers). All run
# host-side on numpy HWC arrays, like the rest of this module — the
# loader's transform stage is host work by design.
# ---------------------------------------------------------------------------
def adjust_brightness(img, brightness_factor):
    """blend towards black (reference functional.adjust_brightness)."""
    h = _as_hwc(img).astype(np.float32)
    out = h * float(brightness_factor)
    return _like(out, img)


def adjust_contrast(img, contrast_factor):
    h = _as_hwc(img).astype(np.float32)
    mean = _gray(h).mean()
    out = mean + float(contrast_factor) * (h - mean)
    return _like(out, img)


def _gray(h):
    if h.shape[-1] == 1:
        return h[..., 0]
    return (0.299 * h[..., 0] + 0.587 * h[..., 1] + 0.114 * h[..., 2])


def _like(out, img):
    ref = np.asarray(img)
    if np.issubdtype(ref.dtype, np.integer):
        return np.clip(np.round(out), 0, 255).astype(ref.dtype)
    return out.astype(ref.dtype if ref.dtype.kind == "f" else np.float32)


def adjust_saturation(img, saturation_factor):
    h = _as_hwc(img).astype(np.float32)
    g = _gray(h)[..., None]
    out = g + float(saturation_factor) * (h - g)
    return _like(out, img)


def adjust_hue(img, hue_factor):
    """rotate hue by hue_factor (in [-0.5, 0.5] turns) via HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    h = _as_hwc(img).astype(np.float32)
    scale = 255.0 if np.issubdtype(np.asarray(img).dtype,
                                   np.integer) else 1.0
    rgb = h[..., :3] / scale
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn
    hue = np.zeros_like(mx)
    m = diff > 0
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    idx = (mx == r) & m
    hue[idx] = ((g - b)[idx] / diff[idx]) % 6
    idx = (mx == g) & m
    hue[idx] = (b - r)[idx] / diff[idx] + 2
    idx = (mx == b) & m
    hue[idx] = (r - g)[idx] / diff[idx] + 4
    hue = (hue / 6.0 + hue_factor) % 1.0
    sat = np.where(mx > 0, diff / np.maximum(mx, 1e-12), 0.0)
    # HSV -> RGB
    i = np.floor(hue * 6.0)
    f = hue * 6.0 - i
    p = mx * (1 - sat)
    q = mx * (1 - sat * f)
    t = mx * (1 - sat * (1 - f))
    i = i.astype(np.int32) % 6
    out = np.choose(i[..., None],
                    [np.stack([mx, t, p], -1), np.stack([q, mx, p], -1),
                     np.stack([p, mx, t], -1), np.stack([p, q, mx], -1),
                     np.stack([t, p, mx], -1), np.stack([mx, p, q], -1)])
    return _like(out * scale, img)


def to_grayscale(img, num_output_channels=1):
    h = _as_hwc(img).astype(np.float32)
    g = _gray(h)[..., None]
    out = np.repeat(g, num_output_channels, axis=-1)
    return _like(out, img)


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def pad(img, padding, fill=0, padding_mode="constant"):
    h = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    spec = ((pt, pb), (pl, pr), (0, 0))
    if padding_mode == "constant":
        return np.pad(h, spec, mode="constant", constant_values=fill)
    mode = {"reflect": "reflect", "edge": "edge",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(h, spec, mode=mode)


def erase(img, i, j, h, w, v, inplace=False):
    arr = np.asarray(img)
    out = arr if inplace else arr.copy()
    hwc = out if out.ndim == 3 and out.shape[-1] <= 4 else None
    if hwc is not None:                 # HWC layout
        out[i:i + h, j:j + w] = v
    else:                               # CHW layout
        out[..., i:i + h, j:j + w] = v
    return out


def _sample_at(h, sx, sy, fill=0, interpolation="bilinear"):
    """Inverse-map sampling at per-pixel source coordinates (sx, sy) —
    the one warp kernel shared by rotate / affine / perspective."""
    H, W = h.shape[:2]
    if interpolation == "nearest":
        xi = np.round(sx).astype(np.int32)
        yi = np.round(sy).astype(np.int32)
        valid = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
        out = h[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)]
        return np.where(valid[..., None], out, np.float32(fill))
    if interpolation != "bilinear":
        raise ValueError(
            f"unsupported interpolation {interpolation!r} "
            "(bilinear/nearest)")
    x0 = np.floor(sx).astype(np.int32)
    y0 = np.floor(sy).astype(np.int32)
    wx = sx - x0
    wy = sy - y0
    out = np.zeros(sx.shape + (h.shape[2],), np.float32)
    total_w = np.zeros(sx.shape + (1,), np.float32)
    for dy in (0, 1):
        for dx in (0, 1):
            xi = x0 + dx
            yi = y0 + dy
            valid = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
            wgt = (np.where(dx, wx, 1 - wx)
                   * np.where(dy, wy, 1 - wy)) * valid
            out += (h[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)]
                    * wgt[..., None])
            total_w += wgt[..., None]
    return out + (1 - total_w) * fill


def _affine_grid_sample(h, matrix, out_shape=None, fill=0,
                        interpolation="bilinear"):
    """2x3 affine inverse map (output -> input coords) over _sample_at."""
    H, W = h.shape[:2]
    oh, ow = out_shape or (H, W)
    ys, xs = np.meshgrid(np.arange(oh, dtype=np.float32),
                         np.arange(ow, dtype=np.float32), indexing="ij")
    a, b, c, d, e, f = matrix
    return _sample_at(h, a * xs + b * ys + c, d * xs + e * ys + f,
                      fill=fill, interpolation=interpolation)


def rotate(img, angle, interpolation="bilinear", expand=False,
           center=None, fill=0):
    h = _as_hwc(img).astype(np.float32)
    H, W = h.shape[:2]
    cx, cy = center if center is not None else ((W - 1) / 2,
                                                (H - 1) / 2)
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    oh, ow, ox, oy = H, W, 0.0, 0.0
    if expand:
        # canvas large enough for the rotated corners; keep the rotation
        # centered in the new canvas
        ow = int(np.ceil(abs(W * cos) + abs(H * sin)))
        oh = int(np.ceil(abs(W * sin) + abs(H * cos)))
        ox = (ow - 1) / 2 - cx
        oy = (oh - 1) / 2 - cy
    # inverse rotation about (cx, cy), output shifted by (ox, oy)
    mat = (cos, sin, cx - cos * (cx + ox) - sin * (cy + oy),
           -sin, cos, cy + sin * (cx + ox) - cos * (cy + oy))
    out = _affine_grid_sample(h, mat, out_shape=(oh, ow), fill=fill,
                              interpolation=interpolation)
    return _like(out, img)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    h = _as_hwc(img).astype(np.float32)
    H, W = h.shape[:2]
    cx, cy = center if center is not None else ((W - 1) / 2,
                                                (H - 1) / 2)
    rad = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in shear)
    # forward matrix: translate(center) . rot/shear/scale . translate(-center) . translate(t)
    a = scale * np.cos(rad + sy) / np.cos(sy)
    b = scale * (np.cos(rad + sy) * np.tan(sx) / np.cos(sy)
                 - np.sin(rad))
    c = scale * np.sin(rad + sy) / np.cos(sy)
    d = scale * (np.sin(rad + sy) * np.tan(sx) / np.cos(sy)
                 + np.cos(rad))
    fwd = np.array([[a, b, 0], [c, d, 0], [0, 0, 1]], np.float32)
    pre = np.array([[1, 0, -cx - translate[0]],
                    [0, 1, -cy - translate[1]], [0, 0, 1]], np.float32)
    post = np.array([[1, 0, cx], [0, 1, cy], [0, 0, 1]], np.float32)
    inv = np.linalg.inv(post @ fwd @ pre)
    mat = (inv[0, 0], inv[0, 1], inv[0, 2],
           inv[1, 0], inv[1, 1], inv[1, 2])
    return _like(_affine_grid_sample(h, mat, fill=fill,
                                     interpolation=interpolation), img)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0):
    """4-point perspective warp (reference functional.perspective):
    solve the homography end->start and inverse-sample."""
    h = _as_hwc(img).astype(np.float32)
    A, bvec = [], []
    for (ex, ey), (sx, sy) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bvec += [sx, sy]
    coef = np.linalg.solve(np.asarray(A, np.float32),
                           np.asarray(bvec, np.float32))
    H_, W_ = h.shape[:2]
    ys, xs = np.meshgrid(np.arange(H_, dtype=np.float32),
                         np.arange(W_, dtype=np.float32), indexing="ij")
    den = coef[6] * xs + coef[7] * ys + 1.0
    sxm = (coef[0] * xs + coef[1] * ys + coef[2]) / den
    sym = (coef[3] * xs + coef[4] * ys + coef[5]) / den
    return _like(_sample_at(h, sxm, sym, fill=fill,
                            interpolation=interpolation), img)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0.0, 1 - self.value),
                              1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0.0, 1 - self.value),
                              1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0.0, 1 - self.value),
                              1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value,
                                                 self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self._ts = [BrightnessTransform(brightness),
                    ContrastTransform(contrast),
                    SaturationTransform(saturation),
                    HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(4)
        for i in order:
            img = self._ts[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant",
                 keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="bilinear", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        ang = np.random.uniform(*self.degrees)
        return rotate(img, ang, center=self.center, fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="bilinear", fill=0, center=None,
                 keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        H, W = _as_hwc(img).shape[:2]
        ang = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0],
                                   self.translate[0]) * W
            ty = np.random.uniform(-self.translate[1],
                                   self.translate[1]) * H
        sc = (np.random.uniform(*self.scale)
              if self.scale is not None else 1.0)
        sh = (0.0, 0.0)
        if self.shear is not None:
            s = self.shear
            if isinstance(s, numbers.Number):
                s = (-abs(s), abs(s))
            sh = (np.random.uniform(s[0], s[1]), 0.0)
        return affine(img, angle=ang, translate=(tx, ty), scale=sc,
                      shear=sh, fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="bilinear", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if np.random.uniform() >= self.prob:
            return img
        H, W = _as_hwc(img).shape[:2]
        d = self.distortion_scale

        def jig(x, y, sx, sy):
            return (x + sx * np.random.uniform(0, d * W / 2),
                    y + sy * np.random.uniform(0, d * H / 2))

        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [jig(0, 0, 1, 1), jig(W - 1, 0, -1, 1),
               jig(W - 1, H - 1, -1, -1), jig(0, H - 1, 1, -1)]
        return perspective(img, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.uniform() >= self.prob:
            return img
        arr = np.asarray(img)
        hwc = arr.ndim == 3 and arr.shape[-1] <= 4
        H, W = (arr.shape[:2] if hwc or arr.ndim == 2
                else arr.shape[-2:])
        area = H * W
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < H and ew < W:
                i = np.random.randint(0, H - eh)
                j = np.random.randint(0, W - ew)
                return erase(img, i, j, eh, ew, self.value,
                             inplace=self.inplace)
        return img


from . import functional  # noqa: E402,F401
