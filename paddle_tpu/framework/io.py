"""Single-process checkpoint: paddle.save / paddle.load.

Reference parity: python/paddle/framework/io.py:773 (save) / :1020 (load) —
pickled nested state_dicts. Tensors serialize as numpy arrays (bfloat16 via
ml_dtypes survives the round-trip); the distributed sharded checkpoint lives
in paddle_tpu.distributed.checkpoint.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .tensor import Tensor


class _TensorPayload:
    """Pickle-stable tensor container (bfloat16 stored as uint16 raw)."""

    def __init__(self, array):
        dtype_name = array.dtype.name if hasattr(array.dtype, "name") else str(array.dtype)
        self.dtype_name = dtype_name
        if dtype_name == "bfloat16":
            self.raw = np.asarray(array).view(np.uint16)
        else:
            self.raw = np.asarray(array)
        self.shape = tuple(array.shape)

    def to_array(self):
        if self.dtype_name == "bfloat16":
            import jax.numpy as jnp

            return self.raw.view(jnp.bfloat16)
        return self.raw


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return type(obj)(packed) if not isinstance(obj, tuple) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        arr = obj.to_array()
        return arr if return_numpy else Tensor(arr)
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        vals = [_unpack(v, return_numpy) for v in obj]
        return tuple(vals) if isinstance(obj, tuple) else vals
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # crash-safe: serialize fully, write to a same-directory temp file,
    # fsync, then atomically rename over the target — a reader (or a
    # process restarted after SIGKILL mid-save) can observe the old file
    # or the new file, never a truncated pickle
    data = pickle.dumps(_pack(obj), protocol=protocol)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
