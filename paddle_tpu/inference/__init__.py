"""paddle.inference — minimal Predictor over the jit servable.

Reference parity surface: paddle/fluid/inference (Config:
paddle.inference.Config, create_predictor, Predictor.run). The 92k-LoC
deployment stack (pass pipelines, TensorRT) is explicitly descoped
(docs/DECISIONS.md §4); what ships is the piece a ported serving script
needs: load a `paddle.jit.save` artifact and run it as a compiled XLA
executable with the reference's handle-style API.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """reference paddle.inference.Config(prog_file?) — here: the
    jit.save path prefix."""

    def __init__(self, model_path=None, params_path=None):
        self._model_path = model_path
        self._use_gpu = False
        self._ir_optim = True

    def model_path(self):
        return self._model_path

    # accepted-for-parity toggles: XLA owns optimization/placement
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True

    def disable_gpu(self):
        self._use_gpu = False

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_memory_optim(self, flag=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass


class _Handle:
    """Input/output handle (reference ZeroCopyTensor surface)."""

    def __init__(self):
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load as jit_load

        if config.model_path() is None:
            raise ValueError("Config needs the jit.save path prefix")
        self._layer = jit_load(config.model_path())
        self._inputs = {}
        self._outputs = []

    def get_input_names(self):
        # arity from the saved artifact (jit.save records it), so the
        # reference workflow — get_input_names() first, then bind each —
        # works for multi-input servables; fall back to bound handles
        # for pre-arity artifacts
        n = getattr(self._layer, "num_inputs", None)
        return [f"x{i}" for i in range(n or max(1, len(self._inputs)))]

    def get_input_handle(self, name):
        return self._inputs.setdefault(name, _Handle())

    def get_output_names(self):
        n = getattr(self._layer, "num_outputs", None)
        return [f"out{i}" for i in range(n or max(1, len(self._outputs)))]

    def get_output_handle(self, name):
        idx = int(name[3:]) if name.startswith("out") else 0
        while len(self._outputs) <= idx:
            self._outputs.append(_Handle())
        return self._outputs[idx]

    def run(self):
        import paddle_tpu as paddle

        def _key(item):
            name = item[0]
            digits = "".join(c for c in name if c.isdigit())
            return (int(digits) if digits else 0, name)

        args = [paddle.to_tensor(h._value)
                for _, h in sorted(self._inputs.items(), key=_key)]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for i, o in enumerate(outs):
            while len(self._outputs) <= i:
                self._outputs.append(_Handle())
            self._outputs[i]._value = np.asarray(o._data)
        return True


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
