"""Pallas kernel pack tests.

Run the real kernels in interpret mode (hermetic on any backend,
pallas_guide.md debugging section) against the plain-XLA reference path.
Tolerances: flash-attn recomputes softmax from LSE in backward, so grads
carry the formulation's intrinsic f32 floor (~1e-4), not pure rounding.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attention as fa

HP = jax.lax.Precision.HIGHEST


def _ref(q, k, v, causal, scale):
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        precision=HP).astype(jnp.float32) * scale
    if causal:
        s = logits.shape[-1]
        m = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(m, logits, -jnp.inf)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v, precision=HP)


def _rand_qkv(b=2, s=128, h=3, d=64, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    return mk(), mk(), mk()


class TestFlashAttentionKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_xla(self, causal):
        q, k, v = _rand_qkv()
        scale = 1.0 / q.shape[-1] ** 0.5
        out = fa.flash_attention(q, k, v, causal=causal, scale=scale,
                                 interpret=True)
        want = _ref(q, k, v, causal, scale)
        assert float(jnp.max(jnp.abs(out - want))) < 2e-5

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_xla(self, causal):
        q, k, v = _rand_qkv()
        scale = 1.0 / q.shape[-1] ** 0.5

        def loss_fa(q, k, v):
            return jnp.sum(jnp.sin(fa.flash_attention(
                q, k, v, causal=causal, scale=scale, interpret=True)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(_ref(q, k, v, causal, scale)))

        got = jax.grad(loss_fa, (0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            assert float(jnp.max(jnp.abs(g - w))) < 3e-4

    def test_multi_block_online_softmax(self):
        # force several k blocks so the online rescale path runs
        q, k, v = _rand_qkv(b=1, s=256, h=2, d=32)
        scale = 0.17
        out = fa.flash_attention(q, k, v, causal=True, scale=scale,
                                 block_q=64, block_k=64, interpret=True)
        want = _ref(q, k, v, True, scale)
        assert float(jnp.max(jnp.abs(out - want))) < 2e-5

    @pytest.mark.parametrize("causal", [False, True])
    def test_tiled_fused_backward_grads(self, causal):
        """The single-pass fused backward (dK/dV HBM accumulators via
        aliasing, in-kernel delta, qi_base causal offsets) — forced via
        explicit blocks so the single-block path can't take it."""
        q, k, v = _rand_qkv(b=1, s=256, h=2, d=32, seed=3)
        scale = 1.0 / 32 ** 0.5

        def loss_fa(q, k, v):
            return jnp.sum(jnp.sin(fa.flash_attention(
                q, k, v, causal=causal, scale=scale,
                block_q=64, block_k=64, interpret=True)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(_ref(q, k, v, causal, scale)))

        got = jax.grad(loss_fa, (0, 1, 2))(q, k, v)
        want = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            assert float(jnp.max(jnp.abs(g - w))) < 3e-4

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("seq", [80, 208])
    def test_backward_non_tile_multiple_seq(self, causal, seq):
        """Non-multiple-of-128 (but %16) lengths take the single-block
        path; parity-check BACKWARD there too, not just tile-aligned
        forward shapes (ISSUE 7 satellite)."""
        q, k, v = _rand_qkv(b=2, s=seq, h=2, d=32, seed=11)
        scale = 1.0 / 32 ** 0.5
        out = fa.flash_attention(q, k, v, causal=causal, scale=scale,
                                 interpret=True)
        want = _ref(q, k, v, causal, scale)
        assert float(jnp.max(jnp.abs(out - want))) < 2e-5

        def loss_fa(q, k, v):
            return jnp.sum(jnp.sin(fa.flash_attention(
                q, k, v, causal=causal, scale=scale, interpret=True)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(_ref(q, k, v, causal, scale)))

        got = jax.grad(loss_fa, (0, 1, 2))(q, k, v)
        wantg = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for g, w in zip(got, wantg):
            assert float(jnp.max(jnp.abs(g - w))) < 3e-4

    def test_bf16(self):
        q, k, v = _rand_qkv(dtype=jnp.bfloat16)
        out = fa.flash_attention(q, k, v, causal=True, interpret=True)
        want = _ref(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), True, 1.0 / 8.0)
        assert out.dtype == jnp.bfloat16
        assert float(jnp.max(jnp.abs(out.astype(jnp.float32) - want))) < 3e-2

    def test_supports_gate(self):
        assert fa.supports((2, 1024, 8, 64), jnp.bfloat16, True)
        assert not fa.supports((2, 1021, 8, 64), jnp.float32, True)  # prime seq
        assert not fa.supports((2, 1024, 8, 512), jnp.float32, True)  # huge d


class TestFunctionalIntegration:
    def test_sdpa_routes_to_pallas(self, monkeypatch):
        """With the min-seqlen flag lowered, F.scaled_dot_product_attention
        must route through the pallas kernel and agree with the XLA path."""
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.utils import flags

        calls = {}
        orig = fa.flash_attention

        def spy(*a, **kw):
            calls["hit"] = True
            kw.setdefault("interpret", True)
            return orig(*a, **kw)

        monkeypatch.setattr(fa, "flash_attention", spy)
        flags.set_flags({"FLAGS_pallas_flash_min_seqlen": 64})
        try:
            q, k, v = _rand_qkv(b=1, s=64, h=2, d=32)
            qt, kt, vt = (paddle.to_tensor(np.asarray(x)) for x in (q, k, v))
            out = F.scaled_dot_product_attention(qt, kt, vt, is_causal=True)
            assert calls.get("hit"), "pallas path not taken"
            want = _ref(q, k, v, True, 1.0 / 32 ** 0.5)
            np.testing.assert_allclose(np.asarray(out._data), np.asarray(want),
                                       atol=2e-5)
        finally:
            flags.set_flags({"FLAGS_pallas_flash_min_seqlen": 1024})

    def test_sdpa_backward_through_pallas(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.utils import flags

        flags.set_flags({"FLAGS_pallas_flash_min_seqlen": 64})
        try:
            qn = np.random.default_rng(1).standard_normal(
                (1, 64, 2, 32)).astype(np.float32)
            q = paddle.to_tensor(qn, stop_gradient=False)
            k = paddle.to_tensor(qn * 0.5, stop_gradient=False)
            v = paddle.to_tensor(qn * 0.25, stop_gradient=False)
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
            out.sum().backward()
            assert q.grad is not None and np.isfinite(
                np.asarray(q.grad._data)).all()
        finally:
            flags.set_flags({"FLAGS_pallas_flash_min_seqlen": 1024})
