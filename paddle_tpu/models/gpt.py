"""GPT model family — the flagship pretraining model (BASELINE config 4:
GPT-3 1.3B, sharding stage 2/3 + recompute).

Reference parity: the GPT nets used by Paddle's Fleet examples
(python/paddle/incubate/ layers + nn/layer/transformer.py building blocks).
TPU-first: the model is plain dygraph Layers whose params carry stable names;
`sharding_rules()` maps those names to `jax.sharding.PartitionSpec`s so the
same model runs single-chip, tensor-parallel (Megatron layout over the "mp"
mesh axis), fully-sharded ("fsdp"/dp axis) or both — XLA GSPMD inserts the
collectives (SURVEY.md §5.8 north star).

Megatron TP layout (reference fleet/layers/mpu/mp_layers.py:47,334,541):
  - qkv / fc1: column-parallel — weight [in, out] sharded on out → "mp"
  - out-proj / fc2: row-parallel — weight sharded on in → "mp"
  - token embedding: vocab-parallel — sharded on vocab dim
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

from .. import nn
from ..nn import functional as F
from ..framework.tensor import Tensor
from ..ops import creation as C


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 0          # 0 → 4 * hidden
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.0
    attention_dropout_prob: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_recompute: bool = False
    # remat granularity: None = full (reference semantics), "dots" = keep
    # linear/MLP dot outputs, recompute only attention (less recompute
    # FLOPs for a modest activation-memory cost)
    recompute_policy: str = None
    # long-context: route attention through the sep-axis ppermute ring
    # (meta_parallel/ring_attention.py) instead of GSPMD's k/v all-gather —
    # O(seq/n) activation memory per device on a sep mesh
    use_ring_attention: bool = False
    # compile-time lever: stack the identical decoder blocks on a leading
    # [num_layers] dim and run them as ONE lax.scan body instead of
    # num_layers inlined copies. XLA compiles one block instead of 24+ —
    # the standard big-model trick on TPU (the 1.3b whole-step compile
    # drops from ~17 min to minutes; see PERF.md). Same math; param names
    # become blocks__<template-name> with a stacked leading dim.
    scan_layers: bool = False

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size


# Named configs (sizes follow the GPT-3 paper table; 1.3B is the BASELINE
# north-star pretrain config).
GPT_CONFIGS = {
    "gpt3-125m": dict(hidden_size=768, num_layers=12, num_attention_heads=12),
    "gpt3-350m": dict(hidden_size=1024, num_layers=24, num_attention_heads=16),
    "gpt3-1.3b": dict(hidden_size=2048, num_layers=24, num_attention_heads=32),
    "gpt3-2.7b": dict(hidden_size=2560, num_layers=32, num_attention_heads=32),
    "gpt3-6.7b": dict(hidden_size=4096, num_layers=32, num_attention_heads=32),
    "gpt3-13b": dict(hidden_size=5120, num_layers=40, num_attention_heads=40),
}


def gpt_config(name: str, **overrides) -> GPTConfig:
    kw = dict(GPT_CONFIGS[name])
    kw.update(overrides)
    return GPTConfig(**kw)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.qkv = nn.Linear(h, 3 * h)
        self.out_proj = nn.Linear(h, h)
        self.dropout_p = config.attention_dropout_prob
        self._use_ring = config.use_ring_attention

    def _ring_mesh(self):
        if not self._use_ring:
            return None
        from ..distributed import env as denv

        if not denv.is_initialized():
            return None
        mesh = denv.get_mesh()
        if "sep" in mesh.axis_names and mesh.shape["sep"] > 1:
            return mesh
        return None

    def _ring_attention(self, q, k, v, mesh):
        from ..distributed.fleet.meta_parallel import ring_attention
        from ..framework.autograd import apply_op

        return apply_op(
            lambda qq, kk, vv: ring_attention(qq, kk, vv, mesh=mesh,
                                              causal=True),
            [q, k, v], name="ring_attention")

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv(x)                              # [b, s, 3h]
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]                               # [b, s, nh, hd]
        ring_mesh = self._ring_mesh()
        # ring requirements: seq divisible by the ring, and no attention
        # dropout (the ring kernel has no dropout plumbing) — otherwise
        # fall back to the dense path rather than diverge or crash
        drop_active = self.dropout_p > 0.0 and self.training
        if (ring_mesh is not None and not drop_active
                and s % int(ring_mesh.shape["sep"]) == 0):
            out = self._ring_attention(q, k, v, ring_mesh)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.dropout_p,
                training=self.training,
            )                                           # [b, s, nh, hd]
        out = out.reshape([b, s, h])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc1 = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc2 = nn.Linear(config.intermediate_size, config.hidden_size)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTBlock(nn.Layer):
    """Pre-LN transformer decoder block."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self._use_recompute = config.use_recompute
        self._recompute_policy = config.recompute_policy

    def _inner(self, x):
        x = x + self.dropout(self.attn(self.ln_1(x)))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x

    def forward(self, x):
        if self._use_recompute and self.training:
            from ..distributed.fleet import recompute

            return recompute(self._inner, x,
                             policy=self._recompute_policy)
        return self._inner(x)


class GPTStackedBlocks(nn.Layer):
    """The decoder stack as ONE scanned block over [num_layers]-stacked
    parameters (see GPTConfig.scan_layers). Mirrors the stage-stacking of
    models/gpt_pipe.py (which scans within a pipeline stage); this is the
    single-chip/whole-model variant."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        n = config.num_layers
        object.__setattr__(self, "_template", GPTBlock(config))
        self._stacked_names = []
        from ..framework.random import host_normal
        import jax.numpy as jnp

        std = config.initializer_range
        for pname, p in self._template.named_parameters():
            shape = (n,) + tuple(p.shape)
            if p.ndim >= 2:
                data = host_normal(shape, std)
                if re.search(r"(out_proj|fc2)\.weight$", pname):
                    data = data / (2.0 * n) ** 0.5
            else:
                data = jnp.broadcast_to(p._data, shape)
            flat = "blocks__" + pname.replace(".", "__")
            from ..nn.layer.layers import Parameter

            param = Parameter(jnp.asarray(data))
            param.layer_stacked = True   # optimizer chunks the update
            self.add_parameter(flat, param)
            self._stacked_names.append((flat, pname))

    def forward(self, x):
        import jax

        from ..framework.autograd import apply_op, no_grad
        from ..framework.tensor import Tensor

        template = self._template
        leaves = [p for _, p in template.named_parameters()]
        training = self.training
        cfg = self.config

        def one_layer(h, layer_leaves):
            with no_grad():
                saved = [p._data for p in leaves]
                for p, d in zip(leaves, layer_leaves):
                    p._data = d
                template.training = training
                try:
                    y = template._inner(Tensor._wrap(h))._data
                finally:
                    for p, d in zip(leaves, saved):
                        p._data = d
            return y, None

        if cfg.use_recompute and training:
            policy = (jax.checkpoint_policies
                      .dots_with_no_batch_dims_saveable
                      if cfg.recompute_policy == "dots" else None)
            one_layer = (jax.checkpoint(one_layer, policy=policy)
                         if policy is not None
                         else jax.checkpoint(one_layer))

        stacked = [self._parameters[flat] for flat, _ in
                   self._stacked_names]

        def scanfn(h, *stk):
            out, _ = jax.lax.scan(one_layer, h, list(stk))
            return out

        return apply_op(scanfn, [x] + stacked, name="gpt_scan_blocks")


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        if config.scan_layers and (config.hidden_dropout_prob
                                   or config.attention_dropout_prob):
            # the scan body traces once, so eager dropout keys would be
            # shared by every layer — refuse rather than silently
            # correlate masks across layers
            raise ValueError(
                "scan_layers=True requires zero dropout (per-layer "
                "RNG is not threaded through the scan yet)")
        if config.scan_layers:
            self.blocks = GPTStackedBlocks(config)
        else:
            self.blocks = nn.LayerList([GPTBlock(config)
                                        for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self._init_weights(config)

    def _init_weights(self, config):
        import jax

        from ..framework.random import host_normal
        import jax.numpy as jnp

        std = config.initializer_range
        for name, p in self.named_parameters():
            if "blocks__" in name:
                continue  # stacked scan params init in GPTStackedBlocks
            if p.ndim >= 2:
                p._data = host_normal(p._data.shape, std)
                if re.search(r"(out_proj|fc2)\.weight$", name):
                    # GPT-2 residual-scaled init
                    p._data = p._data / math.sqrt(2.0 * config.num_layers)

    def forward(self, input_ids, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = C.arange(0, s, dtype="int64").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        if self.config.scan_layers:
            x = self.blocks(x)
        else:
            for block in self.blocks:
                x = block(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    """GPT + LM head; forward returns logits, `loss()` the CE training loss."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, position_ids=None):
        hidden = self.gpt(input_ids, position_ids)
        if self.lm_head is None:
            from .. import ops

            logits = ops.matmul(hidden, self.gpt.wte.weight, transpose_y=True)
        else:
            logits = self.lm_head(hidden)
        return logits

    def sharding_rules(self, tp_axis="mp", fsdp_axis=None):
        """Advertise the Megatron TP placement to the auto-parallel
        planner (distributed/auto_parallel/planner.py)."""
        return gpt_sharding_rules(tp_axis=tp_axis, fsdp_axis=fsdp_axis)

    def loss(self, input_ids, labels, loss_mask=None, position_ids=None):
        """Training loss via the fused LM head: hidden states go straight
        into F.fused_linear_cross_entropy, so the [tokens, vocab] logits are
        never materialized (chunked logsumexp + recompute-in-backward).
        Numerically equal to GPTPretrainingCriterion(self(ids), labels)."""
        hidden = self.gpt(input_ids, position_ids)
        if self.lm_head is None:
            w, t_y = self.gpt.wte.weight, True
        else:
            w, t_y = self.lm_head.weight, False
        return fused_lm_loss(hidden, w, t_y, labels, loss_mask)


def fused_lm_loss(hidden, weight, transpose_y, labels, loss_mask=None):
    """Shared fused-LM-head loss used by the GPT/LLaMA `model.loss()`
    paths: fused CE, then the criterion's masked-mean reduction."""
    if loss_mask is None:
        return F.fused_linear_cross_entropy(hidden, weight, labels,
                                            transpose_y=transpose_y)
    from .. import ops

    losses = F.fused_linear_cross_entropy(hidden, weight, labels,
                                          transpose_y=transpose_y,
                                          reduction="none")
    m = loss_mask.astype(losses.dtype)
    return ops.sum(losses * m) / ops.clip(ops.sum(m), min=1.0)


class GPTPretrainingCriterion(nn.Layer):
    """Shifted-token cross entropy: mean over non-masked positions (and,
    like F.cross_entropy, over non-ignore_index labels — keeping this
    numerically equal to the fused `model.loss()` path when labels carry
    -100 padding)."""

    def forward(self, logits, labels, loss_mask=None):
        from .. import ops
        from ..distributed.fleet.layers.mpu import ParallelCrossEntropy

        # ParallelCrossEntropy owns the routing: an active mp axis that
        # divides the vocab → explicit sharded-logsumexp CE (no replicated
        # [tokens, vocab] buffer per device); otherwise plain CE. Its mesh
        # resolution happens per forward, so one criterion instance works
        # across fleet re-inits. Constructed lazily (no params).
        if not hasattr(self, "_ce"):
            object.__setattr__(self, "_ce", ParallelCrossEntropy())
        vocab = logits.shape[-1]
        flat_logits = logits.reshape([-1, vocab])
        flat_labels = labels.reshape([-1])
        loss = self._ce(flat_logits, flat_labels)         # [N], 0 at -100
        if loss_mask is None:
            m = (flat_labels != -100).astype(loss.dtype)
        else:
            m = loss_mask.reshape([-1]).astype(loss.dtype)
        return ops.sum(loss * m) / ops.clip(ops.sum(m), min=1.0)


# ---------------------------------------------------------------------------
# Sharding rules: param-name regex → PartitionSpec axes per dim.
# Axis names: "dp" (data/fsdp), "mp" (tensor), "pp" (pipeline — handled by
# the pipeline module, not these specs).
# ---------------------------------------------------------------------------

def gpt_sharding_rules(tp_axis="mp", fsdp_axis=None):
    """Megatron TP placement (+optional ZeRO-3 sharding of the other dim).

    Returns list of (regex, spec) where spec is a tuple of mesh-axis names
    (or None) per tensor dim. First match wins; unmatched params replicate.
    """
    def spec(*axes):
        return tuple(axes)

    rules = [
        # column-parallel: [in, out] → shard out on mp, in on fsdp
        (r"\.qkv\.weight$", spec(fsdp_axis, tp_axis)),
        (r"\.fc1\.weight$", spec(fsdp_axis, tp_axis)),
        (r"\.qkv\.bias$", spec(tp_axis)),
        (r"\.fc1\.bias$", spec(tp_axis)),
        # row-parallel: [in, out] → shard in on mp, out on fsdp
        (r"\.out_proj\.weight$", spec(tp_axis, fsdp_axis)),
        (r"\.fc2\.weight$", spec(tp_axis, fsdp_axis)),
        # vocab-parallel embedding: [vocab, hidden]
        (r"\bwte\.weight$", spec(tp_axis, fsdp_axis)),
        (r"\bwpe\.weight$", spec(None, fsdp_axis)),
        (r"lm_head\.weight$", spec(fsdp_axis, tp_axis)),
    ]
    return rules


def match_sharding(name, rules):
    for pat, spec in rules:
        if re.search(pat, name):
            return spec
    return ()
