"""Pipeline-parallel runtime.

Reference parity: PipelineParallel (fleet/meta_parallel/pipeline_parallel.py:231)
— train_batch splits the batch into micro-batches and runs the 1F1B schedule
(forward_backward_pipeline :547) with P2P activation transfer;
PipelineParallelWithInterleave (:1138) adds virtual stages.

TPU-first: stage placement is expressed through the mesh; micro-batches are
accumulated with the tape engine, and the whole train_batch body is
jit-compiled by TrainStep when used through it. The host-driven per-rank
send/recv loop of the reference (p2p_communication.py) is replaced by XLA
scheduling the cross-stage transfers inside one program — on real multi-chip
meshes the overlapped schedule comes from the stacked-stage shard_map path
(pipelined_blocks, below) which pipelines micro-batches over `ppermute`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.tensor import Tensor
from ....nn.layer.layers import Layer
from .pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel wraps a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = (getattr(strategy, "pipeline_configs", None) or
               {"accumulate_steps": 1})
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.num_stages = (hcg.get_pipe_parallel_world_size()
                           if hcg is not None else layers.get_num_stages())
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data, n):
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d, n) for d in data]
            return [tuple(p[i] for p in parts) for i in range(n)]
        if isinstance(data, Tensor):
            b = data.shape[0]
            assert b % n == 0, f"batch {b} not divisible by micro-steps {n}"
            sz = b // n
            return [data[i * sz:(i + 1) * sz] for i in range(n)]
        return [data] * n

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference pipeline_parallel.py:547 forward_backward_pipeline.

        Runs `accumulate_steps` micro-steps: each forward+backward
        accumulates grads on the tape; then one optimizer step. Loss
        returned is the micro-step mean."""
        micro_batches = self._split_micro(data, self.accumulate_steps)
        total = None
        for mb in micro_batches:
            inputs, labels = mb if isinstance(mb, tuple) else (mb, None)
            out = self._layers(*(inputs if isinstance(inputs, tuple)
                                 else (inputs,)))
            if self._layers._loss_fn is not None and labels is not None:
                loss = self._layers._loss_fn(out, labels)
            else:
                loss = out
            scaled = loss * (1.0 / self.accumulate_steps)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = scaled if total is None else total + scaled
        self._layers.allreduce_shared_weight_gradients()
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total.detach() if isinstance(total, Tensor) else total

    def eval_batch(self, data, compute_loss=True):
        micro_batches = self._split_micro(data, self.accumulate_steps)
        total = None
        for mb in micro_batches:
            inputs, labels = mb if isinstance(mb, tuple) else (mb, None)
            out = self._layers(*(inputs if isinstance(inputs, tuple)
                                 else (inputs,)))
            if compute_loss and self._layers._loss_fn is not None:
                out = self._layers._loss_fn(out, labels)
            total = out if total is None else total + out * 1.0
        return total

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)


class PipelineParallelWithInterleave(PipelineParallel):
    """Reference pipeline_parallel.py:1138 — virtual stages. Scheduling is
    XLA's inside the fused program; the wrapper keeps API parity."""
    pass


def pipelined_blocks(block_fn, params_stacked, x, n_microbatch, axis="pp"):
    """TPU-native overlapped pipeline over a stack of identical stages:
    shard_map over the pp axis, `ppermute` passing activations ring-wise
    (scaling-book pipelining pattern; supersedes the reference's host-driven
    P2P loop). `params_stacked`: pytree with leading stage dim sharded on
    `axis`; `x`: [n_microbatch * mb, ...] batch.

    Runs n_stages + n_microbatch - 1 ticks of lax.scan; returns outputs
    in microbatch order. Use inside jit over a mesh containing `axis`.
    """
    def staged(params, xs):
        # params: this stage's params (leading dim stripped by shard_map)
        # xs: microbatch queue for stage 0, zeros elsewhere
        stage = jax.lax.axis_index(axis)
        n_stages = jax.lax.axis_size(axis)
        mb = xs.shape[0] // n_microbatch
        state = jnp.zeros((mb,) + xs.shape[1:], xs.dtype)
        outs = jnp.zeros_like(xs)

        def tick(carry, t):
            state, outs = carry
            # stage 0 ingests microbatch t (if any remain)
            take = jnp.clip(t, 0, n_microbatch - 1)
            fresh = jax.lax.dynamic_slice_in_dim(xs, take * mb, mb, 0)
            inp = jnp.where(stage == 0, fresh, state)
            y = block_fn(params, inp)
            # pass to next stage; last stage's output wraps to be collected
            passed = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)])
            # collect finished microbatch on the "virtual sink" (stage 0 slot)
            done_idx = t - (n_stages - 1)
            collect = jnp.clip(done_idx, 0, n_microbatch - 1)
            outs = jax.lax.cond(
                done_idx >= 0,
                lambda o: jax.lax.dynamic_update_slice_in_dim(
                    o, passed, collect * mb, 0),
                lambda o: o, outs)
            return (passed, outs), None

        (state, outs), _ = jax.lax.scan(
            tick, (state, outs), jnp.arange(n_stages + n_microbatch - 1))
        return outs

    return staged(params_stacked, x)
