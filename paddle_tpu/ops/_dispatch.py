"""Op dispatch helpers.

The TPU-native analog of the Phi kernel dispatch layer
(paddle/phi/core/kernel_factory.h:316, paddle/phi/api/lib/kernel_dispatch.h):
every op funnels through `apply_op`, which executes the jax computation and
records the autograd node. Scalars ride along as closure constants (the
reference's attribute path), tensors as traced operands.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework.autograd import apply_op
from ..framework.dtype import to_jax_dtype, get_default_dtype


def ensure_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    return Tensor(x, dtype=dtype)


def unary(fn, x, name="", **attrs):
    x = ensure_tensor(x)
    return apply_op(fn, [x], attrs=attrs, name=name)


def binary(fn, x, y, name=""):
    xt, yt = isinstance(x, Tensor), isinstance(y, Tensor)
    if xt and yt:
        return apply_op(fn, [x, y], name=name)
    if xt:
        yv = y._data if isinstance(y, Tensor) else y
        return apply_op(lambda a: fn(a, yv), [x], name=name)
    if yt:
        xv = x
        return apply_op(lambda b: fn(xv, b), [y], name=name)
    return Tensor._wrap(fn(jnp.asarray(x), jnp.asarray(y)))


def nary(fn, tensors, name="", **attrs):
    tensors = [ensure_tensor(t) for t in tensors]
    return apply_op(fn, tensors, attrs=attrs, name=name)


def default_float():
    return to_jax_dtype(get_default_dtype())


def resolve_dtype(dtype, default=None):
    if dtype is None:
        return default if default is not None else default_float()
    return to_jax_dtype(dtype)
