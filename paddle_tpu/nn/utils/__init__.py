"""nn.utils parity (reference python/paddle/nn/utils/):
spectral_norm / weight_norm wrappers, parameter vector helpers."""
from .spectral_norm import SpectralNorm, spectral_norm  # noqa: F401
from .weight_norm import weight_norm, remove_weight_norm  # noqa: F401


def parameters_to_vector(parameters, name=None):
    # built from ops so the result stays on the autograd tape (an
    # L2-over-flattened-params loss must reach the parameters)
    from ...ops import concat, reshape

    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        chunk = vec._data[offset:offset + n].reshape(p._data.shape)
        p._data = chunk.astype(p._data.dtype)   # keep the param's dtype
        offset += n


from ..clip import clip_grad_norm_  # noqa: E402,F401  (stub-era export)


def clip_grad_value_(parameters, clip_value):
    """torch/paddle-style utility (reference nn/utils/clip_grad_value_):
    clamp every parameter's grad to [-clip_value, clip_value] in place."""
    import jax.numpy as jnp

    if hasattr(parameters, "shape"):
        parameters = [parameters]
    cv = float(clip_value)
    for p in parameters:
        if p.grad is None:
            continue
        p.grad._data = jnp.clip(p.grad._data, -cv, cv)
