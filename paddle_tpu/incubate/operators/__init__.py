"""paddle.incubate.operators (reference incubate/operators/__init__.py):
graph sampling ops + fused softmax-mask — re-exports of the live
implementations (geometric / incubate.nn.functional)."""
from ...geometric import (  # noqa: F401
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
    send_u_recv as graph_send_recv,
)
from ..nn.functional import (  # noqa: F401
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)


def graph_khop_sampler(*args, **kwargs):
    """Late-bound alias of incubate.graph_khop_sampler (defined in the
    parent package; importing it eagerly would be circular)."""
    from .. import graph_khop_sampler as _impl

    return _impl(*args, **kwargs)


class ResNetUnit:
    """reference incubate/operators/resnet_unit.py: cuDNN-fused
    conv+BN+add+relu block. XLA performs this fusion on the plain
    composition, so the fused layer object has no TPU counterpart."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "ResNetUnit is a cuDNN fusion wrapper; compose nn.Conv2D + "
            "nn.BatchNorm2D + F.relu — XLA fuses the same pattern")


def unzip(input, lod, len):
    raise NotImplementedError(
        "unzip operates on LoD tensors (parameter-server data path, "
        "descoped docs/DECISIONS.md §3)")
