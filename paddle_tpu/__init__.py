"""paddle_tpu — a TPU-native deep learning framework with PaddlePaddle's
capabilities (reference: /root/reference, see SURVEY.md).

Public namespace mirrors `paddle.*`: tensor ops at the top level, `nn`,
`optimizer`, `amp`, `io`, `distributed`, `vision`, `jit`, `static`-less —
but the engine underneath is jax/XLA/PJRT, designed TPU-first (SURVEY.md §7).
"""

__version__ = "0.1.0"

# jax compat: this codebase targets the top-level `jax.shard_map` (with the
# `check_vma=` kwarg); on older jax (< 0.6, e.g. the baked-in 0.4.x
# toolchain) that lives at jax.experimental.shard_map.shard_map with the
# kwarg named `check_rep`. Install a translating alias BEFORE any submodule
# (or test) touches jax.shard_map.
import jax as _jax  # noqa: E402

# True when running on the legacy (< 0.6) jax the compat aliases below
# bridge; a few tests skip paths that hard-crash its jaxlib
jax_compat_legacy = not hasattr(_jax, "shard_map")

if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def _shard_map_compat(f, mesh=None, in_specs=None, out_specs=None,
                          check_vma=None, check_rep=None, axis_names=None,
                          **kw):
        if check_rep is None and check_vma is not None:
            check_rep = check_vma
        if check_rep is not None:
            kw["check_rep"] = check_rep
        if axis_names is not None:
            # new API: manual ONLY over axis_names; old API spells that
            # as auto = the complement
            kw["auto"] = frozenset(mesh.axis_names) - set(axis_names)
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)

    _jax.shard_map = _shard_map_compat

from .framework import (  # noqa: F401
    # dtypes
    DType,
    bool_ as bool,  # noqa: A001 — paddle exposes paddle.bool
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
    set_default_dtype,
    get_default_dtype,
    # device
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    set_device,
    get_device,
    device_count,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    # tensor & autograd
    Tensor,
    to_tensor,
    no_grad,
    enable_grad,
    is_grad_enabled,
    set_grad_enabled,
    # rng
    seed,
    get_rng_state,
    set_rng_state,
    Generator,
)

# CUDA-rng compat aliases (single accelerator RNG stream on TPU) + float8
# storage dtypes (jnp-native)
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state
import jax.numpy as _jnp_f8  # noqa: E402

float8_e4m3fn = _jnp_f8.float8_e4m3fn
float8_e5m2 = _jnp_f8.float8_e5m2

from .ops import *  # noqa: F401,F403  — paddle.* tensor ops
from . import ops  # noqa: F401

from . import nn  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import device  # noqa: E402,F401
from . import utils  # noqa: E402,F401

from .hapi import Model  # noqa: E402,F401
from .hapi.model_summary import summary  # noqa: E402,F401
from .utils.flags import get_flags, set_flags  # noqa: E402,F401
from .distributed import DataParallel  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import observability  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from . import fft  # noqa: E402,F401
from . import signal  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import models  # noqa: E402,F401
from . import audio  # noqa: E402,F401
from . import callbacks  # noqa: E402,F401
from . import dataset  # noqa: E402,F401
from . import geometric  # noqa: E402,F401
from . import hub  # noqa: E402,F401
from . import inference  # noqa: E402,F401
# the ops star-import above already bound `linalg` to ops.linalg (the
# reference tensor.linalg surface); the PACKAGE paddle_tpu.linalg
# wraps that same surface and adds `.distributed` — import it
# explicitly (a plain `from . import linalg` would see the existing
# attribute and skip the submodule import) and rebind
import importlib as _importlib  # noqa: E402

linalg = _importlib.import_module(".linalg", __name__)
from . import onnx  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
from . import reader  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from . import static  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401
from . import tensor  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import version  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from .framework.io import save, load  # noqa: E402,F401
from .nn import ParamAttr  # noqa: E402,F401


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """paddle.grad parity (python/paddle/autograd/__init__.py; C++
    general_grad.h partial-graph path)."""
    from .framework import run_backward
    from .framework.tensor import Tensor as _T

    if create_graph:
        raise NotImplementedError(
            "create_graph=True (double backward) is not supported yet by the "
            "tape engine; higher-order grads land with the functional "
            "autograd transform"
        )
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    capture = {id(t): t for t in inputs}
    captured = run_backward(
        list(outputs),
        list(grad_outputs) if grad_outputs is not None else None,
        # NB: plain `bool` is shadowed here by the paddle.bool DType export
        retain_graph=((not not retain_graph) if retain_graph is not None
                      else create_graph),
        capture=capture,
        accumulate_leaf=False,
    )
    results = []
    for t in inputs:
        g = captured.get(id(t))
        if g is None:
            if allow_unused:
                results.append(None)
            else:
                raise ValueError(
                    "one of the input tensors was not used in the graph; set "
                    "allow_unused=True to return None for it (reference "
                    "general_grad.h unused-input check)"
                )
        else:
            results.append(_T._wrap(g))
    return results


from .hapi.model_summary import flops  # noqa: E402,F401


# -- dtype info + mode-switch parity shims ---------------------------------
from .framework.dtype import DType as dtype  # noqa: E402,F401


def iinfo(t):
    """paddle.iinfo parity over framework dtypes."""
    import numpy as _np

    from .framework.dtype import to_jax_dtype as _tj

    return _np.iinfo(_np.dtype(_tj(t)))


def finfo(t):
    """paddle.finfo parity (bfloat16 via ml_dtypes)."""
    import ml_dtypes as _ml
    import numpy as _np

    from .framework.dtype import to_jax_dtype as _tj

    d = _np.dtype(_tj(t))
    return _ml.finfo(d) if d.name == "bfloat16" else _np.finfo(d)


_dynamic_mode = True


def in_dynamic_mode():
    return _dynamic_mode


def disable_static():
    """Reference paddle.disable_static — dygraph IS the default here."""
    global _dynamic_mode
    _dynamic_mode = True


def enable_static():
    """The legacy static-graph Program world has no TPU equivalent (jit/
    to_static is the compiled path); scripts calling this get a clear
    error instead of silently-wrong eager semantics."""
    raise NotImplementedError(
        "paddle_tpu has no legacy static-graph mode; use paddle_tpu.jit."
        "to_static / TrainStep for compiled execution")


class LazyGuard:
    """Reference LazyGuard defers param init to the first forward; params
    here are cheap host-side jax arrays, so eager init is fine and the
    guard is a no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

# Tensor method completion: attach the reference's tensor_method_func
# surface once every namespace above exists (framework/tensor_methods.py)
import sys as _sys  # noqa: E402

from .framework import tensor_methods as _tensor_methods  # noqa: E402

_tensor_methods.install(_sys.modules[__name__])
