"""Megatron sequence parallelism.

Reference parity: fleet/utils/sequence_parallel_utils.py — ScatterOp /
GatherOp / AllGatherOp / ReduceScatterOp PyLayers (:85-137),
ColumnSequenceParallelLinear (:427) with comm/compute overlap
(SPInnerOverlapLinear :255), RowSequenceParallelLinear,
register_sequence_parallel_allreduce_hooks (:192).

TPU-first: SP is a layout discipline — activations outside TP blocks are
sharded on the sequence dim over the mp axis; the column linear's input is
all-gathered and the row linear's output reduce-scattered. With GSPMD these
are sharding constraints and XLA inserts (and overlaps) the collectives;
the explicit PyLayers map to constraint helpers with identical names so
reference code ports 1:1.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .... import nn
from ....framework.tensor import Tensor
from ....framework.autograd import apply_op
from ....nn import functional as F
from ..layers.mpu.mp_layers import _mp_axis_and_mesh, _constrain, _shard_param
from ....nn.initializer import XavierUniform, Constant


def _seq_spec(ndim, axis):
    # activations are [s, b, h] in the reference SP utils; shard dim 0
    return P(axis, *([None] * (ndim - 1)))


class ScatterOp:
    """Reference :85 — split activation along seq dim onto mp ranks."""

    @staticmethod
    def apply(x, axis=0):
        ax, mesh = _mp_axis_and_mesh()
        spec = P(*([None] * axis + [ax]))
        return _constrain(x, mesh, spec)


class GatherOp:
    """Reference :~110 — gather seq-sharded activation to full."""

    @staticmethod
    def apply(x, axis=0):
        ax, mesh = _mp_axis_and_mesh()
        return _constrain(x, mesh, P())


class AllGatherOp:
    """Reference :~120 — allgather along seq in fwd, reduce-scatter in bwd
    (GSPMD derives the transpose automatically)."""

    @staticmethod
    def apply(x):
        ax, mesh = _mp_axis_and_mesh()
        return _constrain(x, mesh, P())


class ReduceScatterOp:
    """Reference :~130 — reduce-scatter along seq in fwd, allgather in bwd."""

    @staticmethod
    def apply(x):
        ax, mesh = _mp_axis_and_mesh()
        return _constrain(x, mesh, _seq_spec(x.ndim, ax))


def scatter(x, axis=0):
    return ScatterOp.apply(x, axis)


def all_gather(x):
    return AllGatherOp.apply(x)


def reduce_scatter(x):
    return ReduceScatterOp.apply(x)


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse=False):
    """Reference :192 — SP params (LN weights etc.) need grads allreduced
    over mp. Under GSPMD replicated params already get reduced grads; the
    hook registration is a no-op kept for parity."""
    return None


class ColumnSequenceParallelLinear(nn.Layer):
    """Reference :427 — input seq-sharded, all-gathered before the column
    matmul; output stays tp-sharded on the feature dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, mp_group=None, name=None):
        super().__init__()
        self._axis, self._mesh = _mp_axis_and_mesh(mp_group)
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform(),
        )
        if out_features % self._mesh.shape[self._axis] == 0:
            _shard_param(self.weight, self._mesh, P(None, self._axis))
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], is_bias=True,
                default_initializer=Constant(0.0))
        self.gather_output = gather_output

    def forward(self, x):
        # x arrives seq-sharded [s/mp, b, h] (global view: constraint on s)
        x = _constrain(x, self._mesh, P())  # all-gather seq
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(out, self._mesh, P())
        spec = P(*([None] * (out.ndim - 1) + [self._axis]))
        return _constrain(out, self._mesh, spec)


class RowSequenceParallelLinear(nn.Layer):
    """Reference RowSequenceParallelLinear — input tp-sharded on features,
    output reduce-scattered along seq."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, mp_group=None,
                 name=None):
        super().__init__()
        self._axis, self._mesh = _mp_axis_and_mesh(mp_group)
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform(),
        )
        if in_features % self._mesh.shape[self._axis] == 0:
            _shard_param(self.weight, self._mesh, P(self._axis, None))
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], is_bias=True,
                default_initializer=Constant(0.0))

    def forward(self, x):
        spec = P(*([None] * (x.ndim - 1) + [self._axis]))
        x = _constrain(x, self._mesh, spec)
        out = F.linear(x, self.weight, None)
        # reduce + scatter along seq dim (dim 0)
        out = _constrain(out, self._mesh, _seq_spec(out.ndim, self._axis))
        if self.bias is not None:
            out = out + self.bias
        return out
