"""Sharded checkpoint load with reshard-on-load.

Reference parity: python/paddle/distributed/checkpoint/load_state_dict.py:277
(chunk-overlap resolution) and :362 (cross-rank fetch). TPU-first: the
template state_dict's arrays carry their TARGET shardings, so each process
assembles exactly the slices its devices need via
``jax.make_array_from_callback`` — the "which rank has my bytes"
point-to-point dance is replaced by reading the overlapping chunks from the
checkpoint files (storage is the transport; no collectives needed).
"""
from __future__ import annotations

import os
import pickle
import zlib
from typing import Dict, Optional

import numpy as np

import jax

from .metadata import LocalTensorIndex, Metadata
from .utils import (
    CheckpointError, flatten_state_dict, to_jax_array, unpack_numpy,
)


def _read_metadata(path: str) -> Metadata:
    """The manifest, or a CheckpointError naming the file (missing,
    truncated, or un-unpicklable — never a bare UnpicklingError)."""
    meta_path = os.path.join(path, "0.metadata")
    try:
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
    except FileNotFoundError:
        raise CheckpointError(
            f"checkpoint {path!r} has no manifest (0.metadata): not a "
            "committed checkpoint (crash before commit, or wrong path)")
    except Exception as e:
        raise CheckpointError(
            f"checkpoint manifest {meta_path!r} is corrupt or "
            f"truncated: {type(e).__name__}: {e}") from e
    if not isinstance(meta, Metadata):
        raise CheckpointError(
            f"checkpoint manifest {meta_path!r} does not contain "
            f"Metadata (got {type(meta).__name__})")
    return meta


class _ChunkReader:
    """Lazy per-file chunk cache. Every read is verified against the
    manifest's CRC32/size before any chunk from that file is trusted;
    failures raise CheckpointError naming the file (and tensor key)."""

    def __init__(self, path: str, checksums: Optional[Dict] = None):
        self.path = path
        self._checksums = checksums or {}
        self._files: Dict[str, dict] = {}

    def _load_file(self, file_name: str) -> dict:
        full = os.path.join(self.path, file_name)
        try:
            with open(full, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointError(
                f"checkpoint chunk file {full!r} unreadable: "
                f"{type(e).__name__}: {e}") from e
        want = self._checksums.get(file_name)
        if want is not None:
            crc, size = want
            if len(raw) != size or zlib.crc32(raw) != crc:
                raise CheckpointError(
                    f"checkpoint chunk file {full!r} fails its manifest "
                    f"checksum (size {len(raw)} vs {size}, crc mismatch: "
                    "truncated write or bit flip after commit)")
        try:
            payload = pickle.loads(raw)
        except Exception as e:
            raise CheckpointError(
                f"checkpoint chunk file {full!r} is corrupt or "
                f"truncated: {type(e).__name__}: {e}") from e
        if not isinstance(payload, dict):
            raise CheckpointError(
                f"checkpoint chunk file {full!r} does not contain a "
                f"chunk dict (got {type(payload).__name__})")
        return payload

    def chunk(self, file_name: str, key, offset):
        if file_name not in self._files:
            self._files[file_name] = self._load_file(file_name)
        try:
            payload = self._files[file_name][(key, offset)]
        except KeyError:
            raise CheckpointError(
                f"tensor {key!r} (offset {offset}) missing from "
                f"checkpoint chunk file "
                f"{os.path.join(self.path, file_name)!r} — manifest and "
                "chunk file disagree (partial or mixed-version "
                "checkpoint)") from None
        try:
            return unpack_numpy(payload)
        except Exception as e:
            raise CheckpointError(
                f"tensor {key!r} in checkpoint chunk file "
                f"{os.path.join(self.path, file_name)!r} cannot be "
                f"decoded: {type(e).__name__}: {e}") from e


def _assemble(key, region_index, shape, dtype, chunks, storage, reader):
    """Fill the [region] slice of logical tensor `key` from saved chunks."""
    starts = [sl.start or 0 for sl in region_index]
    stops = [sl.stop if sl.stop is not None else dim
             for sl, dim in zip(region_index, shape)]
    region_shape = tuple(b - a for a, b in zip(starts, stops))
    out = np.empty(region_shape, dtype)
    filled = np.zeros(region_shape, bool) if chunks else None
    for c in chunks:
        c_starts = list(c.global_offset)
        c_stops = [o + s for o, s in zip(c.global_offset, c.local_shape)]
        lo = [max(a, ca) for a, ca in zip(starts, c_starts)]
        hi = [min(b, cb) for b, cb in zip(stops, c_stops)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue
        file_name = storage[LocalTensorIndex(key, c.global_offset)]
        data = reader.chunk(file_name, key, c.global_offset)
        src = tuple(slice(l - ca, h - ca)
                    for l, h, ca in zip(lo, hi, c_starts))
        dst = tuple(slice(l - a, h - a) for l, h, a in zip(lo, hi, starts))
        out[dst] = data[src]
        filled[dst] = True
    if filled is None or not filled.all():
        raise CheckpointError(
            f"checkpoint chunks do not cover tensor {key!r} region "
            f"{region_index} (shape {shape})")
    return out


def verify_checkpoint(path: str, deep: bool = True) -> Metadata:
    """Validate a checkpoint directory without loading tensors: the
    manifest must unpickle, and every chunk file it names must exist —
    with its recorded CRC32/size when ``deep`` (the default; pass
    ``deep=False`` to skip streaming the chunk bytes when the caller
    will CRC-verify each chunk on read anyway, as load_state_dict
    does). Returns the Metadata on success; raises CheckpointError
    naming the first offending file. Manifests from before the
    checksum field verify structurally only."""
    meta = _read_metadata(path)
    checks = getattr(meta, "file_checksums", {}) or {}
    files = set(meta.storage_metadata.values())
    for file_name in sorted(files):
        full = os.path.join(path, file_name)
        if not os.path.exists(full):
            raise CheckpointError(
                f"checkpoint {path!r} is missing chunk file "
                f"{file_name!r} named by its manifest")
        want = checks.get(file_name) if deep else None
        if want is None:
            continue
        from .utils import file_crc32_size

        crc, size = file_crc32_size(full)
        if (crc, size) != tuple(want):
            raise CheckpointError(
                f"checkpoint chunk file {full!r} fails its manifest "
                f"checksum (crc/size {crc}/{size} vs expected "
                f"{want[0]}/{want[1]}: truncated write or bit flip "
                "after commit)")
    return meta


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0) -> None:
    """Load into the template ``state_dict`` IN PLACE, resharding saved
    chunks to each tensor's current sharding (any mesh/layout). Chunk
    bytes are checksum-verified on read (manifest CRC32/size); corrupt
    or truncated files raise CheckpointError naming file and tensor."""
    meta = _read_metadata(path)
    flat, _ = flatten_state_dict(state_dict)
    reader = _ChunkReader(path, getattr(meta, "file_checksums", {}))

    from ...framework.tensor import Tensor

    for key, value in flat.items():
        if key not in meta.state_dict_metadata:
            raise KeyError(f"{key!r} not found in checkpoint {path!r}")
        saved = meta.state_dict_metadata[key]
        if not isinstance(saved, list):
            # scalar entry: restore the saved value into the template dict
            node = state_dict
            parts = meta.flat_mapping.get(key) or tuple(key.split("."))
            for p in parts[:-1]:
                node = node[p]
            node[parts[-1]] = saved
            continue
        target = to_jax_array(value)
        shape = tuple(target.shape)
        # resharding restore of __scan_shard_*__ flat buckets onto a
        # DIFFERENT mesh shape (ISSUE 11): the bucket's entry layout is
        # independent of the device count, but its trailing zero pad is
        # rounded up to the flattened mesh degree — so a dp8-saved
        # [L, numel8] flat array restores into a dp4 template's
        # [L, numel4] (and vice versa) by copying the common prefix of
        # the LAST dim and zero-filling the rest. Only the pad region
        # differs; the data region is bit-identical.
        saved_shape = (tuple(
            max(c.global_offset[d] + c.local_shape[d] for c in saved)
            for d in range(len(saved[0].local_shape)))
            if saved else shape)
        reshard_pad = ("__scan_shard_" in key.rsplit(".", 1)[-1]
                       and len(saved_shape) == len(shape)
                       and saved_shape[:-1] == shape[:-1]
                       and saved_shape[-1] != shape[-1])
        saved_dtype = np.dtype(saved[0].dtype) if saved else target.dtype
        if saved_dtype.name == "bfloat16":
            import ml_dtypes

            saved_dtype = np.dtype(ml_dtypes.bfloat16)

        def cb(index, _key=key, _saved=saved, _shape=shape,
               _dtype=saved_dtype, _reshard=reshard_pad,
               _saved_shape=saved_shape):
            full = tuple(
                slice(sl.start or 0,
                      sl.stop if sl.stop is not None else dim)
                for sl, dim in zip(index, _shape))
            if not _reshard:
                return _assemble(_key, full, _shape, _dtype, _saved,
                                 meta.storage_metadata, reader)
            # pad-resharding path: assemble the overlap of the
            # requested region with the saved extent, zero-fill the
            # requested tail beyond it (the flat bucket's pad region)
            out = np.zeros(tuple(sl.stop - sl.start for sl in full),
                           _dtype)
            lo, hi = full[-1].start, min(full[-1].stop,
                                         _saved_shape[-1])
            if hi > lo:
                clipped = full[:-1] + (slice(lo, hi),)
                out[..., :hi - lo] = _assemble(
                    _key, clipped, _saved_shape, _dtype, _saved,
                    meta.storage_metadata, reader)
            return out

        new = jax.make_array_from_callback(shape, target.sharding, cb)
        if new.dtype != target.dtype:
            new = new.astype(target.dtype)
        if isinstance(value, Tensor):
            value._data = new
        else:
            # plain-array template: rebind in the dict via the flat key path
            node = state_dict
            parts = meta.flat_mapping.get(key) or tuple(key.split("."))
            for p in parts[:-1]:
                node = node[p]
            node[parts[-1]] = new
