"""Pooling functionals (python/paddle/nn/functional/pooling.py parity;
reference kernels paddle/phi/kernels/pool_kernel.h). XLA reduce_window maps
these to efficient TPU windowed reductions.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops._dispatch import unary, ensure_tensor
from .conv import _tuplize


def _pool_nd(x, kernel, stride, padding, n, reducer, init, ceil_mode=False,
             data_format="NCHW", count_include_pad=True, average=False):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"
    ks = _tuplize(kernel, n)
    st = _tuplize(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pads = padding.upper()
    else:
        p = _tuplize(padding, n)
        pads = [(int(pi), int(pi)) for pi in p]

    if channel_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pad_full = [(0, 0)] + pads + [(0, 0)] if isinstance(pads, list) else pads
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pad_full = [(0, 0), (0, 0)] + pads if isinstance(pads, list) else pads

    def f(v):
        # init values must be CONCRETE numpy scalars: a jnp constant becomes
        # a tracer under jit, defeating jax's monoid-reducer matching, and
        # reduce_window then loses its autodiff rule (fails only inside
        # jit-of-vjp, e.g. TrainStep over a conv net).
        if average:
            zero = np.zeros((), v.dtype)
            summed = jax.lax.reduce_window(
                v, zero, jax.lax.add, window, strides, padding=pad_full
            )
            if count_include_pad or not isinstance(pad_full, list) or all(p == (0, 0) for p in pad_full):
                denom = np.prod(ks)
                return (summed / denom).astype(v.dtype)
            ones = jnp.ones_like(v)
            counts = jax.lax.reduce_window(
                ones, zero, jax.lax.add, window, strides, padding=pad_full
            )
            return (summed / counts).astype(v.dtype)
        if jnp.issubdtype(v.dtype, jnp.floating):
            init_v = np.asarray(-np.inf, v.dtype)
        else:
            init_v = np.asarray(jnp.iinfo(v.dtype).min, v.dtype)
        return jax.lax.reduce_window(
            v, init_v, reducer, window, strides, padding=pad_full
        )

    return unary(f, x, "pool")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 1, jax.lax.max,
                   lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                   ceil_mode, data_format)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 2, jax.lax.max,
                   lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                   ceil_mode, data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, jax.lax.max,
                    lambda d: -jnp.inf if jnp.issubdtype(d, jnp.floating) else jnp.iinfo(d).min,
                    ceil_mode, data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, jax.lax.add, lambda d: 0,
                    ceil_mode, data_format, count_include_pad=not exclusive, average=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, jax.lax.add, lambda d: 0,
                    ceil_mode, data_format, count_include_pad=not exclusive, average=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, jax.lax.add, lambda d: 0,
                    ceil_mode, data_format, count_include_pad=not exclusive, average=True)


def _adaptive_sizes(in_size, out_size):
    # start/end indices per output cell (paddle adaptive pooling semantics)
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, n, average, data_format):
    x = ensure_tensor(x)
    channel_last = data_format[-1] == "C"
    spatial_axes = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
    out_sizes = _tuplize(output_size, n)

    def f(v):
        out = v
        for ax, osz in zip(spatial_axes, out_sizes):
            isz = out.shape[ax]
            if isz % osz == 0:
                # uniform: reshape + reduce (fast path)
                k = isz // osz
                new_shape = list(out.shape)
                new_shape[ax : ax + 1] = [osz, k]
                r = out.reshape(new_shape)
                out = jnp.mean(r, axis=ax + 1) if average else jnp.max(r, axis=ax + 1)
            else:
                starts, ends = _adaptive_sizes(isz, osz)
                slices = []
                for s, e in zip(starts, ends):
                    sl = jax.lax.slice_in_dim(out, s, e, axis=ax)
                    red = jnp.mean(sl, axis=ax, keepdims=True) if average else jnp.max(sl, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out

    return unary(f, x, "adaptive_pool")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, True, "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, True, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, True, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, False, "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, False, "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, False, "NCDHW")
